"""Sandboxed CEL-style expression language for declarative policy hooks.

The gpu_ext paper (PAPERS.md) argues that user policy belongs in small
verified programs injected into a privileged engine, not in forked
operator code. This module is that program layer for the upgrade
operator: a deliberately tiny expression language — CEL's operator set
and call style, none of its macro/comprehension machinery — parsed once
at policy-load time and evaluated under a hard step budget against an
allowlisted environment.

Safety model (the whole point — see docs/policy-engine.md §3):

- **No loops, no recursion, no user definitions.** The grammar has
  exactly one shape: an expression tree. Evaluation cost is bounded by
  tree size times the step budget's per-node accounting, so a program
  cannot even express unbounded work; the budget is belt and
  suspenders against pathological trees and slow membership tests.
- **Allowlisted environment.** Identifiers resolve against the dict
  the hook point provides (``node``, ``fleet``, ``now``, ...) and
  nothing else — no builtins, no imports, no attribute access on
  Python objects (member access works on plain dicts only).
- **Allowlisted functions.** ``size``, ``has``, ``startsWith``,
  ``endsWith``, ``contains``, ``min``, ``max``, ``abs`` — total
  functions over the value domain. Method-call sugar
  (``name.startsWith("s0-")``) desugars to the same allowlist.
- **Budgets raise, the caller parks.** :class:`EvalBudgetExceeded` /
  :class:`PolicyEvalError` never escape the
  :class:`~tpu_operator_libs.policy.hooks.PolicyHookRegistry`; the
  registry converts them into an audited park/deny verdict
  (fail-closed for admission hooks, fail-open for observation hooks).

``parse`` performs full syntax + static checks (so ``tools/
policy_lint.py`` and spec validation share one implementation);
``Program.identifiers`` / ``Program.functions`` expose the free names
for environment type-checking against the hook catalog.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable

__all__ = [
    "PolicyExprError",
    "PolicyEvalError",
    "EvalBudgetExceeded",
    "Program",
    "parse",
    "ALLOWED_FUNCTIONS",
    "DEFAULT_MAX_STEPS",
    "DEFAULT_MAX_MILLIS",
    "MAX_STEPS_CEILING",
    "MAX_MILLIS_CEILING",
    "MAX_PROGRAM_LENGTH",
]


class PolicyExprError(ValueError):
    """Raised at parse time: syntax error, unknown function, program
    too large."""


class PolicyEvalError(RuntimeError):
    """Raised at evaluation time: unknown identifier, type error,
    division by zero — anything a correct program cannot do."""


class EvalBudgetExceeded(PolicyEvalError):
    """The program exceeded its per-evaluation step or wall budget."""


#: Default/ceiling budgets. A hook program runs once per node per pass,
#: so even the ceiling keeps one pass's policy cost bounded well below
#: a single apiserver round-trip.
DEFAULT_MAX_STEPS = 2000
DEFAULT_MAX_MILLIS = 5.0
MAX_STEPS_CEILING = 100_000
MAX_MILLIS_CEILING = 1000.0
#: Programs ship inside CRD annotations/spec fields; bound their size.
MAX_PROGRAM_LENGTH = 4096

#: name -> (min_args, max_args, implementation). Total functions only:
#: every implementation terminates in O(size of its arguments).
ALLOWED_FUNCTIONS: "dict[str, tuple[int, int, Callable[..., Any]]]" = {}


def _register(name: str, min_args: int, max_args: int):
    def wrap(fn: Callable[..., Any]):
        ALLOWED_FUNCTIONS[name] = (min_args, max_args, fn)
        return fn
    return wrap


@_register("size", 1, 1)
def _fn_size(value: Any) -> int:
    if isinstance(value, (str, list, dict, tuple)):
        return len(value)
    raise PolicyEvalError(f"size() takes a string, list or map, "
                          f"got {type(value).__name__}")


@_register("has", 2, 2)
def _fn_has(container: Any, key: Any) -> bool:
    if isinstance(container, dict):
        return key in container
    if isinstance(container, (list, tuple, str)):
        return key in container
    raise PolicyEvalError(f"has() takes a map, list or string, "
                          f"got {type(container).__name__}")


@_register("startsWith", 2, 2)
def _fn_starts_with(value: Any, prefix: Any) -> bool:
    if not isinstance(value, str) or not isinstance(prefix, str):
        raise PolicyEvalError("startsWith() takes two strings")
    return value.startswith(prefix)


@_register("endsWith", 2, 2)
def _fn_ends_with(value: Any, suffix: Any) -> bool:
    if not isinstance(value, str) or not isinstance(suffix, str):
        raise PolicyEvalError("endsWith() takes two strings")
    return value.endswith(suffix)


@_register("contains", 2, 2)
def _fn_contains(container: Any, needle: Any) -> bool:
    return _fn_has(container, needle)


@_register("min", 1, 8)
def _fn_min(*args: Any) -> Any:
    values = args[0] if len(args) == 1 \
        and isinstance(args[0], (list, tuple)) else args
    if not values:
        raise PolicyEvalError("min() of an empty sequence")
    return min(values)


@_register("max", 1, 8)
def _fn_max(*args: Any) -> Any:
    values = args[0] if len(args) == 1 \
        and isinstance(args[0], (list, tuple)) else args
    if not values:
        raise PolicyEvalError("max() of an empty sequence")
    return max(values)


@_register("abs", 1, 1)
def _fn_abs(value: Any) -> Any:
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        raise PolicyEvalError("abs() takes a number")
    return abs(value)


# ---------------------------------------------------------------------------
# tokenizer
# ---------------------------------------------------------------------------

_TWO_CHAR_OPS = ("==", "!=", "<=", ">=", "&&", "||")
_ONE_CHAR_OPS = "+-*/%<>!?:(),[]{}."
_KEYWORDS = {"true": True, "false": False, "null": None}


@dataclass(slots=True)
class _Token:
    kind: str   # "num" | "str" | "ident" | "op" | "end"
    value: Any
    pos: int


def _tokenize(text: str) -> "list[_Token]":
    tokens: list[_Token] = []
    i, n = 0, len(text)
    while i < n:
        ch = text[i]
        if ch in " \t\r\n":
            i += 1
            continue
        if text[i:i + 2] in _TWO_CHAR_OPS:
            tokens.append(_Token("op", text[i:i + 2], i))
            i += 2
            continue
        if ch in ('"', "'"):
            quote, j, out = ch, i + 1, []
            while j < n and text[j] != quote:
                if text[j] == "\\" and j + 1 < n:
                    esc = text[j + 1]
                    out.append({"n": "\n", "t": "\t", "\\": "\\",
                                '"': '"', "'": "'"}.get(esc, esc))
                    j += 2
                else:
                    out.append(text[j])
                    j += 1
            if j >= n:
                raise PolicyExprError(
                    f"unterminated string literal at offset {i}")
            tokens.append(_Token("str", "".join(out), i))
            i = j + 1
            continue
        if ch.isdigit():
            j = i
            while j < n and (text[j].isdigit() or text[j] == "."):
                j += 1
            lit = text[i:j]
            try:
                value: Any = float(lit) if "." in lit else int(lit)
            except ValueError:
                raise PolicyExprError(
                    f"malformed number {lit!r} at offset {i}") from None
            tokens.append(_Token("num", value, i))
            i = j
            continue
        if ch.isalpha() or ch == "_":
            j = i
            while j < n and (text[j].isalnum() or text[j] == "_"):
                j += 1
            word = text[i:j]
            if word in _KEYWORDS:
                tokens.append(_Token("num", _KEYWORDS[word], i))
            elif word == "in":
                tokens.append(_Token("op", "in", i))
            else:
                tokens.append(_Token("ident", word, i))
            i = j
            continue
        if ch in _ONE_CHAR_OPS:
            tokens.append(_Token("op", ch, i))
            i += 1
            continue
        raise PolicyExprError(f"unexpected character {ch!r} at offset {i}")
    tokens.append(_Token("end", None, n))
    return tokens


# ---------------------------------------------------------------------------
# AST — plain tuples: ("lit", v) | ("ident", name) | ("unary", op, x)
# | ("binary", op, a, b) | ("ternary", c, a, b) | ("member", obj, name)
# | ("index", obj, key) | ("call", fname, args) | ("list", items)
# | ("map", [(k, v), ...])
# ---------------------------------------------------------------------------

class _Parser:
    """Recursive-descent with precedence climbing (ternary lowest)."""

    def __init__(self, tokens: "list[_Token]") -> None:
        self._tokens = tokens
        self._i = 0

    def _peek(self) -> _Token:
        return self._tokens[self._i]

    def _next(self) -> _Token:
        token = self._tokens[self._i]
        self._i += 1
        return token

    def _expect_op(self, op: str) -> None:
        token = self._next()
        if token.kind != "op" or token.value != op:
            raise PolicyExprError(
                f"expected {op!r} at offset {token.pos}, "
                f"got {token.value!r}")

    def parse(self) -> tuple:
        node = self._ternary()
        tail = self._peek()
        if tail.kind != "end":
            raise PolicyExprError(
                f"unexpected trailing {tail.value!r} at offset {tail.pos}")
        return node

    def _ternary(self) -> tuple:
        cond = self._or()
        if self._peek().kind == "op" and self._peek().value == "?":
            self._next()
            then = self._ternary()
            self._expect_op(":")
            other = self._ternary()
            return ("ternary", cond, then, other)
        return cond

    def _or(self) -> tuple:
        node = self._and()
        while self._peek().kind == "op" and self._peek().value == "||":
            self._next()
            node = ("binary", "||", node, self._and())
        return node

    def _and(self) -> tuple:
        node = self._cmp()
        while self._peek().kind == "op" and self._peek().value == "&&":
            self._next()
            node = ("binary", "&&", node, self._cmp())
        return node

    def _cmp(self) -> tuple:
        node = self._add()
        while self._peek().kind == "op" and self._peek().value in (
                "==", "!=", "<", "<=", ">", ">=", "in"):
            op = self._next().value
            node = ("binary", op, node, self._add())
        return node

    def _add(self) -> tuple:
        node = self._mul()
        while self._peek().kind == "op" and self._peek().value in "+-":
            op = self._next().value
            node = ("binary", op, node, self._mul())
        return node

    def _mul(self) -> tuple:
        node = self._unary()
        while self._peek().kind == "op" and self._peek().value in "*/%":
            op = self._next().value
            node = ("binary", op, node, self._unary())
        return node

    def _unary(self) -> tuple:
        token = self._peek()
        if token.kind == "op" and token.value in ("!", "-"):
            self._next()
            return ("unary", token.value, self._unary())
        return self._postfix()

    def _postfix(self) -> tuple:
        node = self._primary()
        while True:
            token = self._peek()
            if token.kind == "op" and token.value == ".":
                self._next()
                name = self._next()
                if name.kind != "ident":
                    raise PolicyExprError(
                        f"expected member name at offset {name.pos}")
                if self._peek().kind == "op" \
                        and self._peek().value == "(":
                    # method sugar: x.f(a) == f(x, a); same allowlist
                    args = self._call_args()
                    node = self._make_call(name.value, [node] + args,
                                           name.pos)
                else:
                    node = ("member", node, name.value)
            elif token.kind == "op" and token.value == "[":
                self._next()
                key = self._ternary()
                self._expect_op("]")
                node = ("index", node, key)
            else:
                return node

    def _call_args(self) -> "list[tuple]":
        self._expect_op("(")
        args: list[tuple] = []
        if self._peek().kind == "op" and self._peek().value == ")":
            self._next()
            return args
        while True:
            args.append(self._ternary())
            token = self._next()
            if token.kind == "op" and token.value == ")":
                return args
            if not (token.kind == "op" and token.value == ","):
                raise PolicyExprError(
                    f"expected ',' or ')' at offset {token.pos}")

    @staticmethod
    def _make_call(name: str, args: "list[tuple]", pos: int) -> tuple:
        spec = ALLOWED_FUNCTIONS.get(name)
        if spec is None:
            raise PolicyExprError(
                f"unknown function {name!r} at offset {pos} (allowed: "
                f"{', '.join(sorted(ALLOWED_FUNCTIONS))})")
        min_args, max_args, _ = spec
        if not min_args <= len(args) <= max_args:
            raise PolicyExprError(
                f"{name}() takes {min_args}..{max_args} argument(s), "
                f"got {len(args)} at offset {pos}")
        return ("call", name, args)

    def _primary(self) -> tuple:
        token = self._next()
        if token.kind in ("num", "str"):
            return ("lit", token.value)
        if token.kind == "ident":
            if self._peek().kind == "op" and self._peek().value == "(":
                args = self._call_args()
                return self._make_call(token.value, args, token.pos)
            return ("ident", token.value)
        if token.kind == "op" and token.value == "(":
            node = self._ternary()
            self._expect_op(")")
            return node
        if token.kind == "op" and token.value == "[":
            items: list[tuple] = []
            if self._peek().kind == "op" and self._peek().value == "]":
                self._next()
                return ("list", items)
            while True:
                items.append(self._ternary())
                tail = self._next()
                if tail.kind == "op" and tail.value == "]":
                    return ("list", items)
                if not (tail.kind == "op" and tail.value == ","):
                    raise PolicyExprError(
                        f"expected ',' or ']' at offset {tail.pos}")
        if token.kind == "op" and token.value == "{":
            pairs: list[tuple] = []
            if self._peek().kind == "op" and self._peek().value == "}":
                self._next()
                return ("map", pairs)
            while True:
                key = self._ternary()
                self._expect_op(":")
                pairs.append((key, self._ternary()))
                tail = self._next()
                if tail.kind == "op" and tail.value == "}":
                    return ("map", pairs)
                if not (tail.kind == "op" and tail.value == ","):
                    raise PolicyExprError(
                        f"expected ',' or '}}' at offset {tail.pos}")
        raise PolicyExprError(
            f"unexpected {token.value!r} at offset {token.pos}")


# ---------------------------------------------------------------------------
# evaluation
# ---------------------------------------------------------------------------

class _Budget:
    """Step + wall budget for ONE evaluation. The wall clock is checked
    every 64 steps — cheap enough to leave always-on, tight enough that
    a slow membership test over a large env value cannot stall a pass."""

    __slots__ = ("steps_left", "deadline")

    def __init__(self, max_steps: int, max_millis: float) -> None:
        self.steps_left = max_steps
        self.deadline = time.monotonic() + max_millis / 1000.0

    def spend(self, cost: int = 1) -> None:
        self.steps_left -= cost
        if self.steps_left <= 0:
            raise EvalBudgetExceeded("evaluation step budget exhausted")
        if self.steps_left % 64 == 0 \
                and time.monotonic() > self.deadline:
            raise EvalBudgetExceeded("evaluation wall budget exhausted")


def _is_number(value: Any) -> bool:
    return isinstance(value, (int, float)) and not isinstance(value, bool)


def _eval(node: tuple, env: "dict[str, Any]", budget: _Budget) -> Any:
    budget.spend()
    kind = node[0]
    if kind == "lit":
        return node[1]
    if kind == "ident":
        name = node[1]
        if name not in env:
            raise PolicyEvalError(
                f"unknown identifier {name!r} (environment: "
                f"{', '.join(sorted(env))})")
        return env[name]
    if kind == "unary":
        value = _eval(node[2], env, budget)
        if node[1] == "!":
            if not isinstance(value, bool):
                raise PolicyEvalError("'!' takes a boolean")
            return not value
        if not _is_number(value):
            raise PolicyEvalError("unary '-' takes a number")
        return -value
    if kind == "binary":
        op = node[1]
        if op == "&&":
            left = _eval(node[2], env, budget)
            if not isinstance(left, bool):
                raise PolicyEvalError("'&&' takes booleans")
            if not left:
                return False
            right = _eval(node[3], env, budget)
            if not isinstance(right, bool):
                raise PolicyEvalError("'&&' takes booleans")
            return right
        if op == "||":
            left = _eval(node[2], env, budget)
            if not isinstance(left, bool):
                raise PolicyEvalError("'||' takes booleans")
            if left:
                return True
            right = _eval(node[3], env, budget)
            if not isinstance(right, bool):
                raise PolicyEvalError("'||' takes booleans")
            return right
        left = _eval(node[2], env, budget)
        right = _eval(node[3], env, budget)
        if op == "==":
            return left == right
        if op == "!=":
            return left != right
        if op == "in":
            # cost proportional to the container, not a free lookup
            if isinstance(right, (list, tuple, str, dict)):
                budget.spend(max(1, len(right) // 16))
                return left in right
            raise PolicyEvalError("'in' takes a list, map or string "
                                  "on the right")
        if op in ("<", "<=", ">", ">="):
            if not ((_is_number(left) and _is_number(right))
                    or (isinstance(left, str) and isinstance(right, str))):
                raise PolicyEvalError(
                    f"{op!r} takes two numbers or two strings")
            return {"<": left < right, "<=": left <= right,
                    ">": left > right, ">=": left >= right}[op]
        if op == "+":
            if isinstance(left, str) and isinstance(right, str):
                if len(left) + len(right) > MAX_PROGRAM_LENGTH:
                    raise PolicyEvalError("string concatenation too large")
                return left + right
            if _is_number(left) and _is_number(right):
                return left + right
            raise PolicyEvalError("'+' takes two numbers or two strings")
        if not (_is_number(left) and _is_number(right)):
            raise PolicyEvalError(f"{op!r} takes two numbers")
        if op == "-":
            return left - right
        if op == "*":
            return left * right
        if op in ("/", "%"):
            if right == 0:
                raise PolicyEvalError("division by zero")
            return left / right if op == "/" else left % right
        raise PolicyEvalError(f"unknown operator {op!r}")  # unreachable
    if kind == "ternary":
        cond = _eval(node[1], env, budget)
        if not isinstance(cond, bool):
            raise PolicyEvalError("ternary condition must be a boolean")
        return _eval(node[2] if cond else node[3], env, budget)
    if kind == "member":
        obj = _eval(node[1], env, budget)
        if not isinstance(obj, dict):
            raise PolicyEvalError(
                f"member access on {type(obj).__name__} (maps only)")
        if node[2] not in obj:
            raise PolicyEvalError(f"no such member {node[2]!r}")
        return obj[node[2]]
    if kind == "index":
        obj = _eval(node[1], env, budget)
        key = _eval(node[2], env, budget)
        if isinstance(obj, dict):
            if key not in obj:
                raise PolicyEvalError(f"no such key {key!r}")
            return obj[key]
        if isinstance(obj, (list, tuple, str)):
            if not isinstance(key, int) or isinstance(key, bool):
                raise PolicyEvalError("list/string index must be an int")
            if not -len(obj) <= key < len(obj):
                raise PolicyEvalError(f"index {key} out of range")
            return obj[key]
        raise PolicyEvalError(
            f"indexing a {type(obj).__name__} (maps, lists, strings)")
    if kind == "call":
        _, _, fn = ALLOWED_FUNCTIONS[node[1]]
        args = [_eval(arg, env, budget) for arg in node[2]]
        for arg in args:
            if isinstance(arg, (str, list, tuple, dict)):
                budget.spend(max(1, len(arg) // 16))
        return fn(*args)
    if kind == "list":
        return [_eval(item, env, budget) for item in node[1]]
    if kind == "map":
        out: dict = {}
        for key_node, value_node in node[1]:
            key = _eval(key_node, env, budget)
            if not isinstance(key, (str, int, float, bool)):
                raise PolicyEvalError("map keys must be scalars")
            out[key] = _eval(value_node, env, budget)
        return out
    raise PolicyEvalError(f"unknown node kind {kind!r}")  # unreachable


def _walk(node: tuple):
    yield node
    kind = node[0]
    if kind in ("unary",):
        yield from _walk(node[2])
    elif kind == "binary":
        yield from _walk(node[2])
        yield from _walk(node[3])
    elif kind == "ternary":
        for child in node[1:]:
            yield from _walk(child)
    elif kind in ("member", "index"):
        yield from _walk(node[1])
        if kind == "index":
            yield from _walk(node[2])
    elif kind == "call":
        for arg in node[2]:
            yield from _walk(arg)
    elif kind == "list":
        for item in node[1]:
            yield from _walk(item)
    elif kind == "map":
        for key_node, value_node in node[1]:
            yield from _walk(key_node)
            yield from _walk(value_node)


@dataclass(frozen=True)
class Program:
    """One parsed policy program, reusable across evaluations."""

    source: str
    _ast: tuple

    def evaluate(self, env: "dict[str, Any]",
                 max_steps: int = DEFAULT_MAX_STEPS,
                 max_millis: float = DEFAULT_MAX_MILLIS) -> Any:
        """Evaluate against ``env`` under the given budgets. Raises
        :class:`PolicyEvalError` (or the :class:`EvalBudgetExceeded`
        subclass) — callers translate into park/deny verdicts."""
        return _eval(self._ast, env, _Budget(max_steps, max_millis))

    def evaluate_bool(self, env: "dict[str, Any]",
                      max_steps: int = DEFAULT_MAX_STEPS,
                      max_millis: float = DEFAULT_MAX_MILLIS) -> bool:
        value = self.evaluate(env, max_steps, max_millis)
        if not isinstance(value, bool):
            raise PolicyEvalError(
                f"program must return a boolean, got "
                f"{type(value).__name__} ({value!r})")
        return value

    def identifiers(self) -> "frozenset[str]":
        """Free root identifiers — the names the environment must
        provide (static type-check input for tools/policy_lint.py)."""
        return frozenset(node[1] for node in _walk(self._ast)
                         if node[0] == "ident")

    def functions(self) -> "frozenset[str]":
        return frozenset(node[1] for node in _walk(self._ast)
                         if node[0] == "call")

    def node_count(self) -> int:
        return sum(1 for _ in _walk(self._ast))


def parse(text: str) -> Program:
    """Parse one policy program. Raises :class:`PolicyExprError` on any
    syntax problem, unknown function, or oversized program — the same
    check spec validation, the CRD webhook path and ``policy_lint``
    share."""
    if not isinstance(text, str) or not text.strip():
        raise PolicyExprError("empty policy program")
    if len(text) > MAX_PROGRAM_LENGTH:
        raise PolicyExprError(
            f"policy program exceeds {MAX_PROGRAM_LENGTH} characters")
    return Program(source=text, _ast=_Parser(_tokenize(text)).parse())
