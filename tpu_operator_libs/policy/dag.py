"""ArtifactDAGCoordinator: dependency-ordered multi-artifact upgrades.

The driving scenario of the policy engine (ISSUE 15, "The Kubernetes
Network Driver Model" in PAPERS.md): a node runs several
DaemonSet-delivered artifacts — libtpu, the TPU device plugin, the
network driver, the node OS-image agent — whose upgrades are
dependency-ordered (the device plugin and network driver need the new
libtpu ABI; the OS-image agent needs both). Upgrading them as four
independent rollouts would cordon/drain every node four times; this
coordinator advances ALL of them through the node's ONE cordon/drain
cycle, in DAG order, purely from declarative data
(:class:`~tpu_operator_libs.api.policy_spec.ArtifactDAGSpec`) — zero
operator-code changes per scenario.

Mechanics, per reconcile pass (all re-derived from cluster state —
the coordinator holds no durable state of its own):

1. **Targets.** Each artifact's target revision is its DaemonSet's
   newest ControllerRevision (the same oracle the primary machine
   uses); a quarantined newest falls back to the restored previous.
2. **Verdicts → quarantine → suffix rollback.** An artifact pod
   crash-looping AT its target revision is a failure verdict; at
   ``failureThreshold`` distinct nodes the revision is quarantined
   (durable DS annotation FIRST — the crash-ordered commit, the PR 4
   idiom), the artifact's DaemonSet is rolled back to the previous
   revision, and every transitive dependent whose own newest revision
   has landed on no node yet (zero stamps — the un-started suffix) is
   rolled back with it. Artifacts outside the dependent suffix are
   untouched and keep rolling forward.
3. **Trigger.** An idle (done/unknown) node whose artifact pods are
   out of sync with their targets gets the one-shot
   ``upgrade-requested`` annotation — the state machine's existing
   re-entry trigger — so a bump of ANY artifact drives the full
   shared cordon/drain cycle.
4. **Advance.** For each node in ``validation-required`` (cordoned,
   drained, primary runtime already restarted by the machine): walk
   the artifacts in topological order; the primary is stamped from
   its in-sync runtime pod; every other artifact may act only once
   ALL its dependencies carry stamps equal to their targets
   (**dag-order**) — an out-of-sync pod is deleted (the DS controller
   recreates it at the target), and a ready pod at the target writes
   the artifact's durable revision stamp. Stamps are node
   annotations written through the state provider (crash-fused,
   shard-fenced), one patch each, in dependency order — so a crash at
   any point leaves a durable DAG prefix the next incarnation resumes
   from.
5. **Gate.** :meth:`node_complete` parks the node in validation until
   every applicable artifact is stamped at its target; the
   ValidationManager treats an incomplete DAG as a park (no failure
   timer — progress comes from the DS controller, liveness from the
   chaos gate's convergence check).
"""

from __future__ import annotations

import logging
from typing import TYPE_CHECKING, Callable, Optional

from tpu_operator_libs.consts import (
    POD_CONTROLLER_REVISION_HASH_LABEL,
    TRUE_STRING,
    UpgradeKeys,
    UpgradeState,
)
from tpu_operator_libs.k8s.client import (
    ApiServerError,
    ConflictError,
    K8sClient,
    NotFoundError,
)
from tpu_operator_libs.k8s.selectors import selector_from_labels
from tpu_operator_libs.util import Clock

if TYPE_CHECKING:  # pragma: no cover - import cycle guard
    # (api.policy_spec imports policy.expr; this module is pulled in
    # by policy/__init__, so the spec types are annotation-only here)
    from tpu_operator_libs.api.policy_spec import (
        ArtifactDAGSpec,
        ArtifactSpec,
    )
    from tpu_operator_libs.k8s.objects import DaemonSet, Node, Pod
    from tpu_operator_libs.upgrade.state_manager import ClusterUpgradeState
    from tpu_operator_libs.upgrade.state_provider import (
        NodeUpgradeStateProvider,
    )

logger = logging.getLogger(__name__)

#: Transient cluster errors: the affected artifact/node simply waits
#: for the next pass (the manager's per-node deferral semantics).
_TRANSIENT = (ApiServerError, ConflictError, NotFoundError)


class _ArtifactView:
    """One artifact's resolved per-pass picture."""

    __slots__ = ("spec", "ds", "newest", "target", "quarantined",
                 "primary", "pods_by_node")

    def __init__(self, spec: ArtifactSpec) -> None:
        self.spec = spec
        self.ds: "Optional[DaemonSet]" = None
        self.newest = ""          # newest ControllerRevision hash
        self.target = ""          # newest, or previous when quarantined
        self.quarantined = ""     # the condemned hash (DS annotation)
        self.primary = False
        self.pods_by_node: "dict[str, Pod]" = {}


class ArtifactDAGCoordinator:
    """Drives every non-primary artifact through the shared cycle."""

    def __init__(self, client: K8sClient, keys: UpgradeKeys,
                 provider: "NodeUpgradeStateProvider",
                 clock: Optional[Clock] = None,
                 audit: "Optional[Callable[..., None]]" = None,
                 pod_failure_threshold: int = 10) -> None:
        self.client = client
        self.keys = keys
        self.provider = provider
        self.clock = clock or Clock()
        #: audit(kind, subject, decision, rule, inputs) — the
        #: DecisionAudit bridge (None = silent).
        self.audit = audit
        self.pod_failure_threshold = pod_failure_threshold
        self.spec: Optional[ArtifactDAGSpec] = None
        self._order: "list[ArtifactSpec]" = []
        #: per-pass views keyed by artifact name.
        self._views: "dict[str, _ArtifactView]" = {}
        #: pods this INCARNATION deleted for an upgrade (advisory only:
        #: avoids re-deleting while the event is in flight; a fresh
        #: incarnation re-derives intent from pod-vs-target alone).
        self._deleted_pod_uids: "set[str]" = set()
        #: (artifact, node) pairs with a deletion in flight — keeps
        #: node_complete parked through the recreate gap (advisory for
        #: the same reason; a crash here at worst skips one stamp,
        #: which the next rollout rewrites).
        self._deleted_for: "set[tuple[str, str]]" = set()
        #: lifetime counters (metrics / gate-teeth evidence)
        self.stamps_total = 0
        self.pods_advanced_total = 0
        self.quarantines_total = 0
        self.suffix_rollbacks_total = 0
        self.upgrade_requests_total = 0
        self.failure_verdicts_total = 0
        self._verdicts_seen: "set[tuple[str, str, str]]" = set()

    # ------------------------------------------------------------------
    # spec lifecycle
    # ------------------------------------------------------------------
    def refresh(self, spec: ArtifactDAGSpec) -> None:
        """Install the pass's spec (reference re-read semantics)."""
        self.spec = spec
        self._order = spec.topo_order()

    @property
    def active(self) -> bool:
        return (self.spec is not None and self.spec.enable
                and bool(self._order))

    def stamp_key(self, artifact: str) -> str:
        return f"{self.keys.artifact_stamp_prefix}{artifact}"

    # ------------------------------------------------------------------
    # the per-pass walk
    # ------------------------------------------------------------------
    def advance(self, state: "ClusterUpgradeState", namespace: str,
                runtime_labels: "dict[str, str]") -> None:
        """One coordinator pass over the snapshot. Transient cluster
        errors defer the affected artifact or node; nothing here may
        wedge the reconcile (hard crashes from the provider's fused
        writes do propagate — they ARE the simulated process death)."""
        if not self.active:
            return
        self._resolve_views(namespace, runtime_labels)
        self._assess_revisions()
        self._request_idle_upgrades(state)
        for ns in state.bucket(UpgradeState.VALIDATION_REQUIRED):
            self._advance_node(ns.node)

    def _resolve_views(self, namespace: str,
                       runtime_labels: "dict[str, str]") -> None:
        self._views = {}
        for spec in self._order:
            view = _ArtifactView(spec)
            view.primary = (spec.runtime_labels == runtime_labels)
            ns = spec.namespace or namespace
            selector = selector_from_labels(spec.runtime_labels)
            try:
                ds_list = self.client.list_daemon_sets(ns, selector)
                view.ds = ds_list[0] if ds_list else None
                if view.ds is not None:
                    view.newest = self._newest_revision(ns, view.ds)
                    view.quarantined = view.ds.metadata.annotations.get(
                        self.keys.quarantined_revision_annotation, "")
                    view.target = view.newest
                    if view.quarantined and view.quarantined == view.newest:
                        # between the quarantine commit and the DS
                        # rollback: target the previous revision
                        view.target = self._previous_revision(
                            ns, view.ds, view.newest)
                    for pod in self.client.list_pods(
                            namespace=ns, label_selector=selector):
                        node_name = pod.spec.node_name
                        if node_name:
                            view.pods_by_node[node_name] = pod
            except _TRANSIENT as exc:
                logger.warning(
                    "artifact %s unresolvable this pass: %s",
                    spec.name, exc)
                view.ds = None
            self._views[spec.name] = view

    def _newest_revision(self, namespace: str, ds: "DaemonSet") -> str:
        prefix = f"{ds.metadata.name}-"
        revs = [rev for rev in self.client.list_controller_revisions(
                    namespace, selector_from_labels(ds.spec.selector))
                if rev.metadata.name.startswith(prefix)
                and "-" not in rev.metadata.name[len(prefix):]]
        if not revs:
            return ""
        return max(revs, key=lambda rev: rev.revision).hash

    def _previous_revision(self, namespace: str, ds: "DaemonSet",
                           newest: str) -> str:
        prefix = f"{ds.metadata.name}-"
        revs = [rev for rev in self.client.list_controller_revisions(
                    namespace, selector_from_labels(ds.spec.selector))
                if rev.metadata.name.startswith(prefix)
                and "-" not in rev.metadata.name[len(prefix):]
                and rev.hash != newest]
        if not revs:
            return newest  # single-revision history: nothing to fall to
        return max(revs, key=lambda rev: rev.revision).hash

    # ------------------------------------------------------------------
    # bad-revision containment (the PR 4 rollback arc, per artifact)
    # ------------------------------------------------------------------
    def _assess_revisions(self) -> None:
        spec = self.spec
        for view in self._views.values():
            if view.primary or view.ds is None or not view.newest:
                # the PRIMARY artifact's verdicts belong to the
                # RolloutGuard (canary/halt/rollback machinery)
                continue
            if view.quarantined == view.newest:
                # durable quarantine commit exists but the rollback has
                # not landed yet (crash between the two): finish it —
                # idempotent, rollback_daemon_set no-ops once newest
                # moved
                self._contain(view)
                continue
            failures = {
                node_name
                for node_name, pod in view.pods_by_node.items()
                if pod.metadata.labels.get(
                    POD_CONTROLLER_REVISION_HASH_LABEL) == view.newest
                and pod.is_failing(self.pod_failure_threshold)}
            for node_name in failures:
                key = (view.spec.name, view.newest, node_name)
                if key not in self._verdicts_seen:
                    self._verdicts_seen.add(key)
                    self.failure_verdicts_total += 1
            if len(failures) >= spec.failure_threshold:
                self._quarantine(view, failures)

    def _quarantine(self, view: _ArtifactView,
                    failures: "set[str]") -> None:
        ds = view.ds
        try:
            fresh = self.client.patch_daemon_set_annotations(
                ds.metadata.namespace, ds.metadata.name,
                {self.keys.quarantined_revision_annotation: view.newest})
        except _TRANSIENT as exc:
            logger.warning("artifact %s quarantine commit deferred: %s",
                           view.spec.name, exc)
            return
        ds.metadata.annotations = fresh.metadata.annotations
        view.quarantined = view.newest
        self.quarantines_total += 1
        logger.warning(
            "ARTIFACT QUARANTINE: revision %s of artifact %s condemned "
            "(%d crash-looping node(s): %s)", view.newest,
            view.spec.name, len(failures), sorted(failures))
        self._audit("artifact", "", "quarantine", "artifact-quarantine",
                    {"artifact": view.spec.name,
                     "revision": view.newest,
                     "failures": sorted(failures)})
        self._contain(view)

    def _contain(self, view: _ArtifactView) -> None:
        """Roll the quarantined artifact back, then its un-started
        dependent suffix — and nothing else."""
        previous = self._previous_revision(
            view.spec.namespace or view.ds.metadata.namespace,
            view.ds, view.quarantined)
        try:
            self.client.rollback_daemon_set(
                view.ds.metadata.namespace, view.ds.metadata.name,
                previous)
        except _TRANSIENT as exc:
            logger.warning("artifact %s rollback deferred: %s",
                           view.spec.name, exc)
            return
        view.newest = previous
        view.target = previous
        self._audit("artifact", "", "rollback", "artifact-rollback",
                    {"artifact": view.spec.name, "to": previous})
        stamped = self._stamped_revisions()
        for dependent in self.spec.dependents_of(view.spec.name):
            dep_view = self._views.get(dependent)
            if dep_view is None or dep_view.ds is None \
                    or dep_view.primary or not dep_view.newest:
                continue
            if dep_view.newest in stamped.get(dependent, ()):
                # the dependent's new revision already landed on some
                # node — it is mid-rollout on its own merits, not an
                # un-started suffix; containment leaves it alone
                continue
            dep_previous = self._previous_revision(
                dep_view.spec.namespace or dep_view.ds.metadata.namespace,
                dep_view.ds, dep_view.newest)
            if dep_previous == dep_view.newest:
                continue  # no older revision to fall back to
            try:
                self.client.rollback_daemon_set(
                    dep_view.ds.metadata.namespace,
                    dep_view.ds.metadata.name, dep_previous)
            except _TRANSIENT as exc:
                logger.warning("dependent %s suffix rollback deferred: "
                               "%s", dependent, exc)
                continue
            dep_view.newest = dep_previous
            dep_view.target = dep_previous
            self.suffix_rollbacks_total += 1
            logger.warning(
                "artifact %s rolled back to %s: un-started dependent "
                "suffix of quarantined %s", dependent, dep_previous,
                view.spec.name)
            self._audit("artifact", "", "rollback",
                        "artifact-suffix-rollback",
                        {"artifact": dependent, "to": dep_previous,
                         "quarantined": view.spec.name})

    def _stamped_revisions(self) -> "dict[str, set[str]]":
        """artifact -> set of revision hashes stamped on ANY node
        (from the node annotations the provider reads — durable
        truth)."""
        out: "dict[str, set[str]]" = {}
        try:
            nodes = self.client.list_nodes()
        except _TRANSIENT:
            return out
        for node in nodes:
            for artifact in self._views:
                stamp = node.metadata.annotations.get(
                    self.stamp_key(artifact))
                if stamp:
                    out.setdefault(artifact, set()).add(stamp)
        return out

    # ------------------------------------------------------------------
    # re-entry trigger
    # ------------------------------------------------------------------
    def _request_idle_upgrades(self, state: "ClusterUpgradeState") -> None:
        """Idle nodes with any out-of-sync artifact pod re-enter the
        machine via the one-shot upgrade-requested annotation (consumed
        at admission) — a device-plugin-only bump still drives the full
        shared cordon/drain cycle."""
        for bucket in (UpgradeState.DONE, UpgradeState.UNKNOWN):
            for ns in state.bucket(bucket):
                node = ns.node
                if node.metadata.annotations.get(
                        self.keys.upgrade_requested_annotation) \
                        == TRUE_STRING:
                    continue
                if not self._node_needs_artifacts(node.metadata.name):
                    continue
                try:
                    self.provider.change_node_upgrade_annotation(
                        node, self.keys.upgrade_requested_annotation,
                        TRUE_STRING)
                except _TRANSIENT as exc:
                    logger.warning(
                        "artifact upgrade request for node %s "
                        "deferred: %s", node.metadata.name, exc)
                    continue
                self.upgrade_requests_total += 1
                self._audit("artifact", node.metadata.name,
                            "upgrade-requested", "artifact-out-of-sync",
                            {"artifacts": self._stale_artifacts(
                                node.metadata.name)})

    def _node_needs_artifacts(self, node_name: str) -> bool:
        return bool(self._stale_artifacts(node_name))

    def _stale_artifacts(self, node_name: str) -> "list[str]":
        stale = []
        for view in self._views.values():
            if view.primary or view.ds is None or not view.target:
                continue
            pod = view.pods_by_node.get(node_name)
            if pod is None:
                continue  # artifact not scheduled here (or mid-recreate)
            if pod.metadata.labels.get(
                    POD_CONTROLLER_REVISION_HASH_LABEL) != view.target:
                stale.append(view.spec.name)
        return sorted(stale)

    # ------------------------------------------------------------------
    # the in-cycle DAG walk
    # ------------------------------------------------------------------
    def _advance_node(self, node: "Node") -> None:
        """Advance one cordoned node's artifacts in topological order.
        Each stamp is its own durable patch, written only once every
        dependency stamp is durable — the crash-ordered prefix
        property."""
        name = node.metadata.name
        for spec in self._order:
            view = self._views.get(spec.name)
            if view is None or view.ds is None or not view.target:
                continue
            stamp = node.metadata.annotations.get(
                self.stamp_key(spec.name))
            if view.primary:
                if stamp != view.target:
                    self._stamp_primary(node, view)
                continue
            pod = view.pods_by_node.get(name)
            pod_rev = (pod.metadata.labels.get(
                POD_CONTROLLER_REVISION_HASH_LABEL)
                if pod is not None else None)
            if stamp == view.target \
                    and (pod is None or pod_rev == view.target):
                continue  # fully advanced (this or a prior cycle)
            # NOTE stamp==target with a STALE pod still falls through:
            # a re-bump can land between a cycle's stamp and a later
            # rollback making the old stamp "current" again while the
            # pod sits on the condemned revision — the pod's sync is
            # the truth, the stamp only orders it
            if not self._deps_satisfied(node, spec):
                # dag-order: neither the stamp nor the pod advance may
                # precede the dependencies' stamps — stop this
                # artifact here; it is reconsidered next pass (or next
                # cycle when the dependency can only move then)
                continue
            if pod is None:
                continue  # DS controller recreating; wait
            if pod_rev != view.target:
                self._advance_pod(node, view, pod)
                continue
            if not pod.is_ready():
                continue  # recreated at target; readiness pending
            if stamp == view.target:
                continue  # re-synced pod under an already-current stamp
            try:
                self.provider.change_node_upgrade_annotation(
                    node, self.stamp_key(spec.name), view.target)
            except _TRANSIENT as exc:
                logger.warning("artifact %s stamp on node %s deferred: "
                               "%s", spec.name, name, exc)
                continue
            self.stamps_total += 1
            self._audit("artifact", name, "stamp", "dag-order",
                        {"artifact": spec.name, "revision": view.target})
            logger.info("artifact %s stamped at %s on node %s",
                        spec.name, view.target, name)

    def _stamp_primary(self, node: "Node", view: _ArtifactView) -> None:
        """The primary artifact is driven by the machine's own
        pod-restart arc; its stamp just records the in-sync revision so
        dependents gate on durable state, not a pod read."""
        pod = view.pods_by_node.get(node.metadata.name)
        if pod is None or not pod.is_ready():
            return
        pod_rev = pod.metadata.labels.get(
            POD_CONTROLLER_REVISION_HASH_LABEL)
        if pod_rev != view.target:
            return
        try:
            self.provider.change_node_upgrade_annotation(
                node, self.stamp_key(view.spec.name), view.target)
        except _TRANSIENT as exc:
            logger.warning("primary stamp on node %s deferred: %s",
                           node.metadata.name, exc)
            return
        self.stamps_total += 1
        self._audit("artifact", node.metadata.name, "stamp", "dag-order",
                    {"artifact": view.spec.name,
                     "revision": view.target})

    def _deps_satisfied(self, node: "Node", spec: ArtifactSpec) -> bool:
        for dep in spec.depends_on:
            dep_view = self._views.get(dep)
            if dep_view is None or not dep_view.target:
                return False
            if node.metadata.annotations.get(self.stamp_key(dep)) \
                    != dep_view.target:
                return False
        return True

    def _advance_pod(self, node: "Node", view: _ArtifactView,
                     pod: "Pod") -> None:
        if pod.metadata.uid in self._deleted_pod_uids:
            return  # deletion already dispatched; recreate in flight
        try:
            self.client.delete_pod(pod.metadata.namespace,
                                   pod.metadata.name)
        except _TRANSIENT as exc:
            logger.warning("artifact %s pod advance on node %s "
                           "deferred: %s", view.spec.name,
                           node.metadata.name, exc)
            return
        self._deleted_pod_uids.add(pod.metadata.uid)
        self._deleted_for.add((view.spec.name, node.metadata.name))
        self.pods_advanced_total += 1
        self._audit("artifact", node.metadata.name, "advance",
                    "dag-order",
                    {"artifact": view.spec.name,
                     "from": pod.metadata.labels.get(
                         POD_CONTROLLER_REVISION_HASH_LABEL, ""),
                     "to": view.target})
        logger.info("artifact %s pod on node %s advancing to %s",
                    view.spec.name, node.metadata.name, view.target)

    # ------------------------------------------------------------------
    # the validation gate + status
    # ------------------------------------------------------------------
    def _artifact_pending(self, node: "Node",
                          spec: "ArtifactSpec") -> bool:
        """True while the artifact still has ACTIONABLE work on this
        node in the current cycle: a pod advancing (deleted /
        recreating / awaiting readiness) or a stamp catch-up. An
        artifact whose dependencies cannot be satisfied this cycle
        (e.g. the primary was re-bumped mid-validation — only the
        machine's next pod-restart arc can move it) is NOT pending:
        the node completes its cycle and the idle trigger re-enters
        it, exactly like the machine's own mid-rollout re-entry."""
        view = self._views.get(spec.name)
        if view is None or view.ds is None or not view.target:
            return False
        name = node.metadata.name
        stamp = node.metadata.annotations.get(self.stamp_key(spec.name))
        pod = view.pods_by_node.get(name)
        pod_rev = (pod.metadata.labels.get(
            POD_CONTROLLER_REVISION_HASH_LABEL)
            if pod is not None else None)
        if view.primary:
            # stamp catch-up only: the pod's lifecycle belongs to the
            # machine's pod-restart arc
            return (stamp != view.target and pod is not None
                    and pod.is_ready() and pod_rev == view.target)
        if stamp == view.target:
            if pod is None:
                # mid-recreate after our deletion (advisory memory; a
                # crash at worst skips one readiness wait)
                return (spec.name, name) in self._deleted_for
            if pod_rev == view.target:
                # in sync; if WE advanced it this cycle, hold the
                # uncordon until it is ready again
                return not pod.is_ready() \
                    and (spec.name, name) in self._deleted_for
            # current stamp over a STALE pod (re-bump + rollback race):
            # actionable whenever the dependencies allow a re-sync
            return self._deps_satisfied(node, spec)
        if not self._deps_satisfied(node, spec):
            return False  # unreachable this cycle
        if pod is None:
            # mid-recreate after our deletion (advisory memory; a
            # crash at worst skips one stamp, rewritten next rollout)
            return (spec.name, name) in self._deleted_for \
                or stamp is not None
        return True  # out-of-sync (delete pending) or awaiting ready

    def node_complete(self, node: "Node") -> bool:
        """True when no artifact has actionable work left on this node
        — the validation-required parking gate."""
        if not self.active:
            return True
        return not any(self._artifact_pending(node, spec)
                       for spec in self._order)

    def incomplete_artifacts(self, node: "Node") -> "list[str]":
        """Names still pending on the node (explain() detail)."""
        return [spec.name for spec in self._order
                if self._artifact_pending(node, spec)]

    def status(self) -> dict:
        """JSON-able block for cluster_status["artifactDAG"]."""
        artifacts = {}
        for spec in self._order:
            view = self._views.get(spec.name)
            if view is None:
                continue
            artifacts[spec.name] = {
                "target": view.target,
                "quarantined": view.quarantined,
                "primary": view.primary,
                "dependsOn": list(spec.depends_on),
            }
        return {
            "artifacts": artifacts,
            "stampsTotal": self.stamps_total,
            "podsAdvancedTotal": self.pods_advanced_total,
            "quarantinesTotal": self.quarantines_total,
            "suffixRollbacksTotal": self.suffix_rollbacks_total,
            "failureVerdictsTotal": self.failure_verdicts_total,
            "upgradeRequestsTotal": self.upgrade_requests_total,
        }

    def _audit(self, kind: str, subject: str, decision: str, rule: str,
               inputs: dict) -> None:
        if self.audit is None:
            return
        try:
            self.audit(kind, subject, decision=decision, rule=rule,
                       inputs=inputs)
        except Exception:  # noqa: BLE001 — auditing must not block
            pass
