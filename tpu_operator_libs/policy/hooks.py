"""The unified policy-hook registry: named, versioned hook points.

Before ISSUE 15 every extension point of the operator was its own
constructor argument — eviction gates via ``with_eviction_gate``,
validators via ``with_validation_enabled(extra_validator=...)``,
planner wrappers via the ``planner`` property, the canary verdict
buried in the RolloutGuard, abort/window audits as bare manager
attributes. Changing behavior meant forking operator wiring, and a
misbehaving hook could wedge a reconcile pass.

This module absorbs those seams behind ONE catalog of named, versioned
hook points (:data:`HOOK_POINTS`) and one registry
(:class:`PolicyHookRegistry`) that accepts both:

- **Python callables** — the old constructor seams, now registered by
  hook name (the ServingDrainGate, the ICI probe validator, a custom
  admission predicate) and run under the same boundary semantics; and
- **declarative programs** — CEL-style expressions shipped in the CRD
  (:class:`~tpu_operator_libs.api.policy_spec.PolicyHooksSpec`),
  compiled once and evaluated sandboxed with per-hook step/wall
  budgets.

Failure semantics are the registry's contract, not each caller's ad-hoc
choice: an ADMISSION hook that raises or overruns its budget fails
**closed** — the subject node parks with an audited ``policy-error`` /
``policy-budget`` reason; an OBSERVATION hook fails **open** — the
event proceeds, the failure is audited. Either way the pass itself
never raises out of a hook (the chaos gate's ``policy-sandbox``
invariant pins this).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Any, Callable, Optional

from tpu_operator_libs.policy.expr import (
    EvalBudgetExceeded,
    Program,
    parse,
)

logger = logging.getLogger(__name__)

#: Hook kinds. Admission hooks gate a state-machine edge (deny parks
#: the node); observation hooks watch one (their result cannot block).
ADMISSION = "admission"
OBSERVATION = "observation"


@dataclass(frozen=True)
class HookPoint:
    """One named, versioned extension point."""

    name: str
    version: str
    kind: str  # ADMISSION | OBSERVATION
    #: Identifiers the evaluation environment provides — the static
    #: type-check surface policy_lint and spec validation share.
    env: frozenset
    description: str

    @property
    def admission(self) -> bool:
        return self.kind == ADMISSION


def _point(name: str, kind: str, env: "tuple[str, ...]",
           description: str) -> HookPoint:
    return HookPoint(name=name, version="v1", kind=kind,
                     env=frozenset(env), description=description)


#: The hook catalog. Every scattered seam of the pre-policy operator
#: maps onto exactly one row (docs/policy-engine.md §2 is generated
#: from these descriptions — keep them one line).
HOOK_POINTS: "dict[str, HookPoint]" = {p.name: p for p in (
    _point("eviction.filter", ADMISSION, ("node", "pods"),
           "May this node's workload pods be evicted now? Deny parks "
           "the node in its eviction-wanting state (the EvictionGate "
           "seam)."),
    _point("planner.admission", ADMISSION, ("node", "fleet", "now"),
           "May this upgrade-required candidate enter the wave? Deny "
           "holds it with an audited rule (the planner-wrapper seam)."),
    _point("window.gate", ADMISSION, ("node", "now", "close"),
           "May this candidate start given the maintenance-window "
           "close? Deny defers it (the window-gate seam)."),
    _point("validation.verdict", ADMISSION, ("node", "now"),
           "Is this restarted node healthy enough to return to "
           "service? False runs the validation-timeout ladder (the "
           "extra-validator seam)."),
    _point("canary.verdict", OBSERVATION, ("node", "revision", "pod"),
           "Does this canary node count as a failure verdict on the "
           "revision under test? (the RolloutGuard verdict seam)."),
    _point("abort.audit", OBSERVATION, ("kind", "node", "now", "reason"),
           "Fires on every mid-flight abort admission/completion (the "
           "abort-audit seam)."),
)}


class UnknownHookError(KeyError):
    """Registration against a hook name not in the catalog."""


@dataclass
class HookVerdict:
    """Outcome of evaluating every registration on one hook point."""

    #: The aggregate decision (admission: AND of every registration;
    #: observation: last value, informational).
    value: Any
    #: True when every registration evaluated cleanly.
    ok: bool
    #: "" | "policy-error" | "policy-budget" — the park/audit rule when
    #: not ok (admission hooks) or the audit rule (observation hooks).
    rule: str = ""
    #: Human detail for the audit record.
    detail: str = ""


@dataclass
class _Registration:
    point: HookPoint
    name: str           # source label ("crd", "python:<fn>")
    program: Optional[Program] = None
    fn: Optional[Callable[..., Any]] = None
    max_steps: int = 0
    max_millis: float = 0.0


class PolicyHookRegistry:
    """Named hook points -> ordered registrations, with sandboxed
    evaluation, budget enforcement and lifetime counters.

    ``audit`` (optional) is called ``audit(kind, subject, decision,
    rule, inputs)`` for every error/budget overrun AND every
    declarative deny — the DecisionAudit bridge. An audit failure is
    swallowed: auditing a failure must not create one.
    """

    def __init__(self, audit: "Optional[Callable[..., None]]" = None,
                 ) -> None:
        self._hooks: dict[str, list[_Registration]] = {}
        self.audit = audit
        #: lifetime counters (metrics feed; keyed by hook name)
        self.evals_total: dict[str, int] = {}
        self.errors_total: dict[str, int] = {}
        self.budget_exceeded_total: dict[str, int] = {}
        self.denies_total: dict[str, int] = {}
        #: (hook, seconds) samples since the last drain — the
        #: eval-duration histogram feed (predictor drain idiom).
        self._eval_samples: list[tuple[str, float]] = []
        #: overruns/errors that failed to produce an audit record
        #: (should stay 0 forever; the policy-sandbox invariant's
        #: teeth).
        self.unaudited_failures = 0

    # ------------------------------------------------------------------
    # registration
    # ------------------------------------------------------------------
    def _point(self, hook: str) -> HookPoint:
        point = HOOK_POINTS.get(hook)
        if point is None:
            raise UnknownHookError(
                f"unknown hook point {hook!r} (known: "
                f"{', '.join(sorted(HOOK_POINTS))})")
        return point

    def register_program(self, hook: str, program_text: str,
                         max_steps: int, max_millis: float,
                         name: str = "crd") -> None:
        """Compile and attach a declarative program. Parse errors raise
        here (policy-load time), never mid-pass."""
        point = self._point(hook)
        self._hooks.setdefault(hook, []).append(_Registration(
            point=point, name=name, program=parse(program_text),
            max_steps=max_steps, max_millis=max_millis))

    def register_callable(self, hook: str, fn: Callable[..., Any],
                          name: str = "") -> None:
        """Attach a Python callable (the absorbed constructor seams).
        The callable receives the hook's env as keyword arguments and
        runs under the same fail-closed/fail-open boundary as a
        program (no step budget — Python hooks are trusted code, but a
        raise still parks instead of wedging)."""
        point = self._point(hook)
        self._hooks.setdefault(hook, []).append(_Registration(
            point=point, name=name or f"python:{getattr(fn, '__name__', fn)!r}",
            fn=fn))

    def clear(self, source: "Optional[str]" = None) -> None:
        """Drop registrations (all, or only those whose name matches
        ``source`` — the per-pass CRD refresh drops only "crd")."""
        if source is None:
            self._hooks.clear()
            return
        for hook in list(self._hooks):
            kept = [r for r in self._hooks[hook] if r.name != source]
            if kept:
                self._hooks[hook] = kept
            else:
                del self._hooks[hook]

    def has(self, hook: str) -> bool:
        return bool(self._hooks.get(hook))

    @property
    def active_hooks(self) -> "dict[str, int]":
        """hook name -> registration count (the active-policy gauge)."""
        return {hook: len(regs) for hook, regs in self._hooks.items()}

    # ------------------------------------------------------------------
    # evaluation
    # ------------------------------------------------------------------
    def evaluate(self, hook: str, env: "dict[str, Any]",
                 subject: str = "") -> HookVerdict:
        """Run every registration on ``hook`` against ``env``.

        Admission points AND the boolean results: the first deny (or
        failure — fail closed) wins. Observation points run every
        registration and fail open. No exception ever escapes."""
        regs = self._hooks.get(hook, ())
        point = HOOK_POINTS[hook]
        if not regs:
            return HookVerdict(value=True if point.admission else None,
                               ok=True)
        value: Any = True if point.admission else None
        for reg in regs:
            self.evals_total[hook] = self.evals_total.get(hook, 0) + 1
            started = time.perf_counter()
            try:
                if reg.program is not None:
                    result = (reg.program.evaluate_bool(
                        env, reg.max_steps, reg.max_millis)
                        if point.admission
                        else reg.program.evaluate(
                            env, reg.max_steps, reg.max_millis))
                else:
                    result = reg.fn(**env)
            except EvalBudgetExceeded as exc:
                self._eval_samples.append(
                    (hook, time.perf_counter() - started))
                self.budget_exceeded_total[hook] = \
                    self.budget_exceeded_total.get(hook, 0) + 1
                return self._failure(point, subject, reg, "policy-budget",
                                     str(exc))
            except Exception as exc:  # noqa: BLE001 — the sandbox
                # boundary: nothing a hook does may escape
                self._eval_samples.append(
                    (hook, time.perf_counter() - started))
                self.errors_total[hook] = \
                    self.errors_total.get(hook, 0) + 1
                return self._failure(point, subject, reg, "policy-error",
                                     f"{type(exc).__name__}: {exc}")
            self._eval_samples.append(
                (hook, time.perf_counter() - started))
            if point.admission:
                if result is not True:
                    self.denies_total[hook] = \
                        self.denies_total.get(hook, 0) + 1
                    return HookVerdict(
                        value=False, ok=True, rule="policy-deny",
                        detail=f"{hook} denied by {reg.name}")
            else:
                value = result
        return HookVerdict(value=value if not point.admission else True,
                           ok=True)

    def _failure(self, point: HookPoint, subject: str,
                 reg: _Registration, rule: str,
                 detail: str) -> HookVerdict:
        """Convert a hook failure into the contracted verdict: deny for
        admission (fail closed), neutral for observation (fail open) —
        audited either way."""
        logger.warning("policy hook %s (%s) failed %s for %s: %s "
                       "(%s)", point.name, reg.name,
                       "closed" if point.admission else "open",
                       subject or "fleet", rule, detail)
        audited = False
        if self.audit is not None:
            try:
                self.audit("policy", subject,
                           decision=("park" if point.admission
                                     else "observed-error"),
                           rule=rule,
                           inputs={"hook": point.name,
                                   "source": reg.name,
                                   "detail": detail[:160]})
                audited = True
            except Exception:  # noqa: BLE001 — auditing a failure
                pass           # must not create one
        if not audited:
            self.unaudited_failures += 1
        if point.admission:
            return HookVerdict(value=False, ok=False, rule=rule,
                               detail=detail)
        return HookVerdict(value=None, ok=False, rule=rule, detail=detail)

    # ------------------------------------------------------------------
    # metrics feed
    # ------------------------------------------------------------------
    def drain_eval_samples(self) -> "list[tuple[str, float]]":
        samples, self._eval_samples = self._eval_samples, []
        return samples

    def stats(self) -> dict:
        """JSON-able counter snapshot (cluster_status / the chaos
        gate's policy-sandbox probe)."""
        return {
            "activeHooks": dict(sorted(self.active_hooks.items())),
            "evalsTotal": dict(sorted(self.evals_total.items())),
            "errorsTotal": dict(sorted(self.errors_total.items())),
            "budgetExceededTotal": dict(sorted(
                self.budget_exceeded_total.items())),
            "deniesTotal": dict(sorted(self.denies_total.items())),
            "unauditedFailures": self.unaudited_failures,
        }
