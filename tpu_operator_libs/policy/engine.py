"""PolicyEngine: binds declarative hook programs into the manager seams.

The engine is the privileged half of the gpu_ext architecture
(PAPERS.md): it owns the :class:`~tpu_operator_libs.policy.hooks.
PolicyHookRegistry`, compiles the CRD's
:class:`~tpu_operator_libs.api.policy_spec.PolicyHooksSpec` into it
(refreshed every pass — reference policy-re-read semantics, so editing
the CRD takes effect without a restart), and exposes seam-shaped
adapters the :class:`~tpu_operator_libs.upgrade.state_manager.
ClusterUpgradeStateManager` installs:

- :class:`PolicyEvictionGate` — wraps the installed EvictionGate; the
  ``eviction.filter`` hook is consulted FIRST (deny parks, fail
  closed), then the inner gate (ServingDrainGate etc.) keeps its
  semantics, including ``release``.
- :class:`PolicyAdmissionPlanner` — outermost semantic planner layer;
  ``planner.admission`` and ``window.gate`` filter the candidate list
  before the inner chain, recording per-node holds the decision audit
  and ``explain()`` surface (``policy-deny`` / ``policy-error`` /
  ``policy-budget`` rules).
- :meth:`PolicyEngine.validation_gate` — the ValidationManager's
  ``policy_validator`` seam (verdict False runs the normal validation
  timeout; a failing program PARKS the node instead — audited, no
  timer, no wedge).
- :meth:`PolicyEngine.canary_verdict` — the RolloutGuard's
  ``extra_verdict`` seam (observation: failures audit and contribute
  nothing).
- :meth:`PolicyEngine.observe_abort` — fan-in for the abort-audit
  seam.

Every adapter keeps the sandbox contract: nothing a policy does can
raise out of a reconcile pass.
"""

from __future__ import annotations

import logging
from typing import TYPE_CHECKING, Any, Callable, Optional

from tpu_operator_libs.policy.hooks import PolicyHookRegistry

if TYPE_CHECKING:  # pragma: no cover - import cycle guard
    # (api.policy_spec imports policy.expr; spec types stay
    # annotation-only here)
    from tpu_operator_libs.api.policy_spec import PolicyHooksSpec
    from tpu_operator_libs.k8s.objects import Node, Pod
    from tpu_operator_libs.upgrade.state_manager import (
        ClusterUpgradeState,
        NodeUpgradeState,
        UpgradePlanner,
    )

logger = logging.getLogger(__name__)

#: ValidationManager.policy_validator return values (see
#: upgrade/validation_manager.py): None = pass; VERDICT_FAIL runs the
#: validation-timeout ladder; VERDICT_PARK holds the node with no
#: timer (the sandboxed fail-closed park).
VERDICT_FAIL = "policy-verdict"
VERDICT_PARK = "policy-park"


def node_env(node: "Node", state: str = "") -> "dict[str, Any]":
    """The ``node`` value every hook environment shares: a plain dict
    (the sandbox has no attribute access on Python objects)."""
    return {
        "name": node.metadata.name,
        "labels": dict(node.metadata.labels),
        "annotations": dict(node.metadata.annotations),
        "unschedulable": node.is_unschedulable(),
        "ready": node.is_ready(),
        "state": state,
    }


def _pod_env(pod: "Pod") -> "dict[str, Any]":
    restarts = 0
    ready = True
    for status in pod.status.container_statuses:
        restarts = max(restarts, status.restart_count)
        ready = ready and status.ready
    return {
        "name": pod.metadata.name,
        "namespace": pod.metadata.namespace,
        "labels": dict(pod.metadata.labels),
        "ready": ready and pod.is_ready(),
        "restarts": restarts,
    }


class PolicyEvictionGate:
    """EvictionGate adapter: policy first (fail closed), inner second.

    One persistent instance lives on the manager; ``inner`` and
    ``engine`` are re-pointed per pass so GateKeeper.set_gate's
    identity comparison sees ONE stable gate (no release/re-park churn
    on every reconcile)."""

    def __init__(self, engine: "Optional[PolicyEngine]" = None,
                 inner: "Optional[Callable]" = None) -> None:
        self.engine = engine
        self.inner = inner

    def __call__(self, node: "Node", pods: "list[Pod]") -> bool:
        engine = self.engine
        if engine is not None and engine.registry.has("eviction.filter"):
            env = {"node": node_env(node),
                   "pods": [_pod_env(p) for p in pods]}
            verdict = engine.registry.evaluate(
                "eviction.filter", env, subject=node.metadata.name)
            if verdict.value is not True:
                return False
        inner = self.inner
        if inner is None:
            return True
        return bool(inner(node, pods))

    def release(self, node: "Node", pods: "list[Pod]") -> None:
        release = getattr(self.inner, "release", None)
        if release is not None:
            release(node, pods)


class PolicyAdmissionPlanner:
    """Outermost semantic planner layer: filters candidates through the
    ``planner.admission`` and ``window.gate`` hooks before the inner
    chain plans. Holds land in ``engine.last_holds`` (the audit
    wrapper's rule source) and in the decision audit via the engine's
    audit bridge."""

    def __init__(self, inner: "UpgradePlanner",
                 engine: "PolicyEngine") -> None:
        self.inner = inner
        self.engine = engine
        #: pass context installed by the manager before planning.
        self.fleet_env: dict = {}
        self.now: float = 0.0
        self.window_close: "Optional[float]" = None

    def plan(self, candidates: "list[NodeUpgradeState]", available: int,
             state: "ClusterUpgradeState") -> "list[NodeUpgradeState]":
        engine = self.engine
        registry = engine.registry
        check_admission = registry.has("planner.admission")
        check_window = registry.has("window.gate")
        if not check_admission and not check_window:
            return self.inner.plan(candidates, available, state)
        allowed: list = []
        for ns in candidates:
            name = ns.node.metadata.name
            env_node = node_env(ns.node, state=str(
                ns.node.metadata.labels.get(engine.state_label, "")))
            held = None
            if check_admission:
                verdict = registry.evaluate(
                    "planner.admission",
                    {"node": env_node, "fleet": self.fleet_env,
                     "now": self.now},
                    subject=name)
                if verdict.value is not True:
                    held = (verdict.rule or "policy-deny",
                            verdict.detail or "planner.admission denied")
            if held is None and check_window:
                verdict = registry.evaluate(
                    "window.gate",
                    {"node": env_node, "now": self.now,
                     "close": self.window_close},
                    subject=name)
                if verdict.value is not True:
                    held = (verdict.rule or "policy-deny",
                            verdict.detail or "window.gate denied")
            if held is None:
                allowed.append(ns)
            else:
                engine.note_hold(name, held[0], held[1])
        return self.inner.plan(allowed, available, state)


class PolicyEngine:
    """The policy subsystem's front door (one per state manager)."""

    def __init__(self, keys: "object",
                 audit: "Optional[Callable[..., None]]" = None) -> None:
        self.registry = PolicyHookRegistry(audit=audit)
        self.state_label = getattr(keys, "state_label", "")
        #: node -> (rule, detail) of this pass's policy holds — the
        #: _AuditingPlanner's rule source and the explain() feed.
        self.last_holds: dict[str, tuple] = {}
        #: fingerprint of the last-compiled CRD spec (avoid recompiling
        #: identical programs every pass).
        self._spec_fingerprint: "Optional[tuple]" = None
        #: lifetime holds recorded (teeth evidence for the gates).
        self.holds_total = 0

    # ------------------------------------------------------------------
    # spec lifecycle
    # ------------------------------------------------------------------
    def refresh(self, spec: "Optional[PolicyHooksSpec]") -> None:
        """(Re)compile the CRD's programs into the registry. Reference
        semantics: the policy document is re-read every pass, so this
        is called from ``apply_state`` — the fingerprint makes the
        steady case free. A spec that fails validation here is dropped
        whole (audited), never half-installed."""
        if spec is None or not spec.enable or not spec.hooks:
            if self._spec_fingerprint is not None:
                self.registry.clear("crd")
                self._spec_fingerprint = None
            return
        fingerprint = tuple(
            (h.hook, h.program, h.max_steps, h.max_millis)
            for h in spec.hooks)
        if fingerprint == self._spec_fingerprint:
            return
        self.registry.clear("crd")
        try:
            spec.validate()
            for hook_spec in spec.hooks:
                self.registry.register_program(
                    hook_spec.hook, hook_spec.program,
                    hook_spec.max_steps, hook_spec.max_millis,
                    name="crd")
        except Exception as exc:  # noqa: BLE001 — a bad policy
            # document must not wedge the pass: drop it, audit, run
            # with no declarative hooks until it is fixed
            self.registry.clear("crd")
            logger.warning("policyHooks spec rejected; running without "
                           "declarative hooks: %s", exc)
            audit = self.registry.audit
            if audit is not None:
                try:
                    audit("policy", "", decision="spec-rejected",
                          rule="policy-error",
                          inputs={"detail": str(exc)[:160]})
                except Exception:  # noqa: BLE001
                    pass
        self._spec_fingerprint = fingerprint

    def begin_pass(self) -> None:
        self.last_holds = {}

    @property
    def active(self) -> bool:
        return bool(self.registry.active_hooks)

    # ------------------------------------------------------------------
    # seam adapters
    # ------------------------------------------------------------------
    def note_hold(self, node: str, rule: str, detail: str) -> None:
        self.last_holds[node] = (rule, detail)
        self.holds_total += 1
        audit = self.registry.audit
        if audit is not None and rule == "policy-deny":
            # error/budget failures were already audited inside the
            # registry; the clean declarative deny is audited here so
            # every policy hold has exactly one record
            try:
                audit("policy", node, decision="hold", rule=rule,
                      inputs={"detail": detail[:160]})
            except Exception:  # noqa: BLE001
                pass

    def validation_gate(self, node: "Node",
                        now: float) -> "Optional[str]":
        """The ValidationManager ``policy_validator`` seam. Returns
        None (pass), :data:`VERDICT_FAIL` (program said unhealthy —
        normal timeout ladder) or :data:`VERDICT_PARK` (program
        failed/over budget — park, audited, no timer)."""
        if not self.registry.has("validation.verdict"):
            return None
        verdict = self.registry.evaluate(
            "validation.verdict",
            {"node": node_env(node), "now": now},
            subject=node.metadata.name)
        if not verdict.ok:
            self.last_holds[node.metadata.name] = (
                verdict.rule, verdict.detail)
            return VERDICT_PARK
        if verdict.value is not True:
            return VERDICT_FAIL
        return None

    def canary_verdict(self, node: "Node", revision: str,
                       pod: "Pod") -> bool:
        """The RolloutGuard ``extra_verdict`` seam (observation: a
        failing program contributes NO verdict — fail open)."""
        if not self.registry.has("canary.verdict"):
            return False
        verdict = self.registry.evaluate(
            "canary.verdict",
            {"node": node_env(node), "revision": revision,
             "pod": _pod_env(pod)},
            subject=node.metadata.name)
        return verdict.ok and verdict.value is True

    def observe_abort(self, kind: str, node: str, now: float,
                      reason: str) -> None:
        """The abort-audit seam (observation, fail open)."""
        if not self.registry.has("abort.audit"):
            return
        self.registry.evaluate(
            "abort.audit",
            {"kind": kind, "node": node, "now": now, "reason": reason},
            subject=node)

    # ------------------------------------------------------------------
    # status
    # ------------------------------------------------------------------
    def status(self) -> dict:
        """JSON-able block for cluster_status["policy"]."""
        out = self.registry.stats()
        out["holdsTotal"] = self.holds_total
        if self.last_holds:
            out["holds"] = {name: rule for name, (rule, _)
                            in sorted(self.last_holds.items())}
        return out
