"""Declarative policy engine: sandboxed hooks + multi-artifact DAGs.

- :mod:`tpu_operator_libs.policy.expr` — the CEL-style sandboxed
  expression language (parse once, evaluate under step/wall budgets).
- :mod:`tpu_operator_libs.policy.hooks` — the unified hook-point
  catalog + registry (Python callables and CRD programs behind one
  named, versioned surface; fail-closed admission / fail-open
  observation).
- :mod:`tpu_operator_libs.policy.engine` — binds a
  :class:`~tpu_operator_libs.api.policy_spec.PolicyHooksSpec` into the
  state manager's seams.
- :mod:`tpu_operator_libs.policy.dag` — the
  :class:`ArtifactDAGCoordinator` driving dependency-ordered
  multi-artifact upgrades through one shared cordon/drain cycle per
  node.

See docs/policy-engine.md.
"""

from tpu_operator_libs.policy.dag import ArtifactDAGCoordinator
from tpu_operator_libs.policy.engine import (
    PolicyAdmissionPlanner,
    PolicyEngine,
    PolicyEvictionGate,
)
from tpu_operator_libs.policy.expr import (
    EvalBudgetExceeded,
    PolicyEvalError,
    PolicyExprError,
    Program,
    parse,
)
from tpu_operator_libs.policy.hooks import (
    HOOK_POINTS,
    HookPoint,
    HookVerdict,
    PolicyHookRegistry,
    UnknownHookError,
)

__all__ = [
    "ArtifactDAGCoordinator",
    "PolicyAdmissionPlanner",
    "PolicyEngine",
    "PolicyEvictionGate",
    "EvalBudgetExceeded",
    "PolicyEvalError",
    "PolicyExprError",
    "Program",
    "parse",
    "HOOK_POINTS",
    "HookPoint",
    "HookVerdict",
    "PolicyHookRegistry",
    "UnknownHookError",
]
