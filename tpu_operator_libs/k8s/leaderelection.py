"""Lease-based leader election for HA operator deployments.

The reference library runs inside a controller-runtime manager, which
provides leader election out of the box (the consumer enables it with
``LeaderElection: true`` — SURVEY.md §1 L5); a complete operator stack must
own the equivalent. This is a re-design of client-go's
``tools/leaderelection`` + ``resourcelock`` pair on coordination.k8s.io/v1
Leases:

- :class:`LeaseLockClient` is the narrow resource-lock protocol
  (``resourcelock.Interface`` analogue). FakeCluster and RealCluster both
  satisfy it; it is deliberately NOT part of :class:`K8sClient` — leader
  election is an optional, separate concern, as it is upstream.
- :class:`LeaderElector` implements acquire/renew with the same
  observed-time expiry rule as client-go: a lease is considered expired
  ``lease_duration`` after *this process last observed the record change*,
  not after the renew timestamp inside the record — so wall-clock skew
  between contenders never causes double-leadership.

Unlike the upstream loop, the decision step
(:meth:`LeaderElector.try_acquire_or_renew`) is a pure, non-blocking state
transition driven by the injectable Clock, so tests (and the rolling-upgrade
simulator) exercise election races deterministically; :meth:`run` is the
thin blocking driver for production.
"""

from __future__ import annotations

import logging
import random
import threading
from dataclasses import dataclass
from typing import Callable, Optional, Protocol

from tpu_operator_libs.k8s.client import (
    AlreadyExistsError,
    ConflictError,
    NotFoundError,
)
from tpu_operator_libs.k8s.objects import Lease, ObjectMeta
from tpu_operator_libs.util import Clock

logger = logging.getLogger(__name__)

# client-go defaults (leaderelection.go): LeaseDuration 15s,
# RenewDeadline 10s, RetryPeriod 2s.
DEFAULT_LEASE_DURATION = 15.0
DEFAULT_RENEW_DEADLINE = 10.0
DEFAULT_RETRY_PERIOD = 2.0


class LeaseLockClient(Protocol):
    """The three operations leader election needs from the cluster."""

    def get_lease(self, namespace: str, name: str) -> Lease: ...

    def create_lease(self, lease: Lease) -> Lease: ...

    def update_lease(self, lease: Lease) -> Lease: ...


@dataclass
class LeaderElectionConfig:
    namespace: str
    name: str
    identity: str
    lease_duration: float = DEFAULT_LEASE_DURATION
    renew_deadline: float = DEFAULT_RENEW_DEADLINE
    retry_period: float = DEFAULT_RETRY_PERIOD
    # Upstream's ReleaseOnCancel: on a clean stop, write holder="" so the
    # next contender doesn't wait out the lease.
    release_on_stop: bool = True
    # Fraction of retry_period added as deterministic per-identity
    # jitter to the run() loop's sleeps: with N replicas (a sharded
    # control plane runs one elector per shard lock) synchronized
    # renewals would herd the apiserver every retry_period. 0 keeps the
    # exact upstream cadence (and the deterministic tests).
    renew_jitter: float = 0.0

    def __post_init__(self) -> None:
        if self.lease_duration <= self.renew_deadline:
            raise ValueError("lease_duration must exceed renew_deadline")
        if self.renew_deadline <= self.retry_period:
            raise ValueError("renew_deadline must exceed retry_period")
        if not self.identity:
            raise ValueError("identity must be non-empty")
        if not 0.0 <= self.renew_jitter <= 1.0:
            raise ValueError("renew_jitter must be in [0, 1]")


class LeaderElector:
    """One contender for a named Lease.

    Callbacks (all optional, invoked from the thread driving the elector):

    - ``on_started_leading()`` — acquired the lease.
    - ``on_stopped_leading()`` — lost or released it. Always follows a
      prior ``on_started_leading``.
    - ``on_new_leader(identity)`` — observed leadership change, including
      ourselves; fired once per distinct holder.
    """

    def __init__(self, client: LeaseLockClient,
                 config: LeaderElectionConfig,
                 clock: Optional[Clock] = None,
                 on_started_leading: Optional[Callable[[], None]] = None,
                 on_stopped_leading: Optional[Callable[[], None]] = None,
                 on_new_leader: Optional[Callable[[str], None]] = None) -> None:
        self._client = client
        self._config = config
        self._clock = clock or Clock()
        self._on_started_leading = on_started_leading
        self._on_stopped_leading = on_stopped_leading
        self._on_new_leader = on_new_leader
        self._leading = False
        # Local observation of the remote record: expiry is judged from
        # _observed_at (when *we* saw it change), never from the record's
        # own renew_time — clock-skew tolerance, as upstream.
        self._observed: Optional[Lease] = None
        self._observed_at = 0.0
        self._last_reported_leader: Optional[str] = None
        self._last_renew_success = 0.0
        # Serializes the two write paths (try_acquire_or_renew vs
        # release): without it a release racing a renew reads a stale
        # observation, its update conflicts, and the lease is left HELD
        # at shutdown — the successor then waits out the full lease
        # duration (regression-pinned in tests/test_leader_election.py).
        self._op_lock = threading.Lock()
        # deterministic per-identity jitter stream for run()'s sleeps
        self._jitter_rng = random.Random(
            f"leader-election:{config.identity}")
        #: Lifetime leadership transitions (metrics surface).
        self.acquires_total = 0
        self.losses_total = 0

    # -- inspection --------------------------------------------------------
    @property
    def is_leader(self) -> bool:
        return self._leading

    @property
    def observed_leader(self) -> str:
        return self._observed.holder_identity if self._observed else ""

    # -- the decision step -------------------------------------------------
    def try_acquire_or_renew(self) -> bool:
        """One acquire-or-renew attempt; returns True iff this attempt
        SUCCEEDED (we wrote the lease). Non-blocking and idempotent
        (leaderelection.go tryAcquireOrRenew).

        Transient failures (apiserver error, write conflict, lost create
        race) return False WITHOUT dropping leadership: ``run`` keeps a
        current leader through outages until ``renew_deadline`` — the same
        grace client-go gives. Only the definitive observation of another
        live holder steps us down immediately.
        """
        with self._op_lock:
            return self._try_acquire_or_renew()

    def _try_acquire_or_renew(self) -> bool:
        config = self._config
        now = self._clock.now()
        try:
            current = self._client.get_lease(config.namespace, config.name)
        except NotFoundError:
            fresh = Lease(
                metadata=ObjectMeta(name=config.name,
                                    namespace=config.namespace),
                holder_identity=config.identity,
                lease_duration_seconds=int(config.lease_duration),
                acquire_time=now, renew_time=now, lease_transitions=0)
            try:
                created = self._client.create_lease(fresh)
            except AlreadyExistsError:
                return False  # lost the create race; observe next tick
            except Exception:
                logger.warning("leader election: create %s/%s failed",
                               config.namespace, config.name, exc_info=True)
                return False
            self._observe(created, now)
            self._set_leading(True)
            return True
        except Exception:
            logger.warning("leader election: get %s/%s failed",
                           config.namespace, config.name, exc_info=True)
            return False

        if self._record_changed(current):
            self._observe(current, now)
        holder = current.holder_identity
        # Expiry honors the HOLDER's advertised duration from the record
        # (that is why the field is stored in the lease at all) — judging
        # by our own config would let a short-configured follower depose a
        # long-configured leader mid-outage (client-go parity).
        holder_duration = (self._observed.lease_duration_seconds
                           if self._observed
                           and self._observed.lease_duration_seconds > 0
                           else config.lease_duration)
        expired = self._observed_at + holder_duration <= now
        if holder and holder != config.identity and not expired:
            self._set_leading(False)  # held by a live leader
            return False

        # Our lease (renew), expired (take over) or released (holder "").
        updated = current.clone()
        updated.holder_identity = config.identity
        updated.lease_duration_seconds = int(config.lease_duration)
        updated.renew_time = now
        if holder != config.identity:
            updated.acquire_time = now
            updated.lease_transitions = current.lease_transitions + 1
        try:
            stored = self._client.update_lease(updated)
        except ConflictError:
            return False  # someone else moved it; re-observe next tick
        except Exception:
            logger.warning("leader election: update %s/%s failed",
                           config.namespace, config.name, exc_info=True)
            return False
        self._observe(stored, now)
        self._set_leading(True)
        return True

    # -- the blocking driver -------------------------------------------------
    def run(self, stop: Optional[threading.Event] = None) -> None:
        """Acquire, then renew until leadership is lost or ``stop`` is set.
        Returns after ``on_stopped_leading`` (if we ever led)."""
        stop = stop or threading.Event()
        config = self._config

        def pace() -> None:
            # jittered renewal cadence: each sleep stretches by up to
            # renew_jitter * retry_period, drawn from a per-identity
            # deterministic stream — N replicas spread out instead of
            # herding the apiserver on synchronized ticks
            self._clock.sleep(config.retry_period * (
                1.0 + config.renew_jitter * self._jitter_rng.random()))

        try:
            while not stop.is_set():
                if self.try_acquire_or_renew():
                    self._last_renew_success = self._clock.now()
                    break
                pace()
            if stop.is_set():
                return
            logger.info("leader election: %s acquired %s/%s",
                        config.identity, config.namespace, config.name)
            while not stop.is_set():
                pace()
                if stop.is_set():
                    break
                if self.try_acquire_or_renew():
                    self._last_renew_success = self._clock.now()
                elif not self._leading:
                    # another contender holds a live lease: definitive loss
                    # (on_stopped_leading already fired); no deadline grace
                    logger.info(
                        "leader election: %s lost %s/%s to %s",
                        config.identity, config.namespace, config.name,
                        self.observed_leader)
                    return
                elif (self._clock.now() - self._last_renew_success
                        >= config.renew_deadline):
                    logger.warning(
                        "leader election: %s failed to renew %s/%s within "
                        "%.0fs; stepping down", config.identity,
                        config.namespace, config.name, config.renew_deadline)
                    self._set_leading(False)
                    return
        finally:
            if self._leading:
                if config.release_on_stop:
                    self.release()
                self._set_leading(False)

    def release(self) -> bool:
        """Write holder="" so successors need not wait out the lease.

        Serialized against :meth:`try_acquire_or_renew` and based on a
        FRESH read of the record, not the local observation: a release
        racing a concurrent renew used to clone a stale
        resourceVersion, conflict, and silently leave the lease HELD at
        shutdown — the successor then waited out the whole lease
        duration. The fresh read also refuses to release a lease some
        other contender has already taken over.
        """
        with self._op_lock:
            if not self._leading:
                return False
            try:
                current = self._client.get_lease(
                    self._config.namespace, self._config.name)
            except Exception:  # noqa: BLE001 — any read failure means
                # nothing releasable we can prove we still hold
                return False
            if current.holder_identity != self._config.identity:
                return False  # already taken over; not ours to release
            released = current.clone()
            released.holder_identity = ""
            released.renew_time = self._clock.now()
            try:
                stored = self._client.update_lease(released)
            except (ConflictError, NotFoundError):
                return False
            except Exception:
                logger.warning("leader election: release %s/%s failed",
                               self._config.namespace, self._config.name,
                               exc_info=True)
                return False
            self._observe(stored, self._clock.now())
            return True

    def step_down(self) -> None:
        """Drop leadership LOCALLY without touching the record (the
        record was already released, stolen, or fenced away). Fires
        ``on_stopped_leading`` if we were leading."""
        self._set_leading(False)

    def observe(self) -> None:
        """Refresh the local observation of the record WITHOUT
        contending for it. A contender that keeps observing a lease it
        may later need (a sharded replica watching shards a peer owns)
        has a warm expiry clock the moment the assignment hands it the
        shard — without this, the observed-time expiry rule makes every
        preference change cost a full extra lease duration before
        takeover."""
        with self._op_lock:
            now = self._clock.now()
            try:
                current = self._client.get_lease(
                    self._config.namespace, self._config.name)
            except NotFoundError:
                return  # absent records are immediately claimable
            except Exception:  # noqa: BLE001 — observation is best-effort
                return
            if self._record_changed(current):
                self._observe(current, now)

    # -- internals -----------------------------------------------------------
    def _record_changed(self, current: Lease) -> bool:
        return (self._observed is None
                or current.metadata.resource_version
                != self._observed.metadata.resource_version)

    def _observe(self, lease: Lease, now: float) -> None:
        self._observed = lease.clone()
        self._observed_at = now
        holder = lease.holder_identity
        if holder and holder != self._last_reported_leader:
            self._last_reported_leader = holder
            if self._on_new_leader is not None:
                self._on_new_leader(holder)

    def _set_leading(self, leading: bool) -> bool:
        if leading and not self._leading:
            self._leading = True
            self.acquires_total += 1
            if self._on_started_leading is not None:
                self._on_started_leading()
        elif not leading and self._leading:
            self._leading = False
            self.losses_total += 1
            if self._on_stopped_leading is not None:
                self._on_stopped_leading()
        return self._leading
