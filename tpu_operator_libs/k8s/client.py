"""Abstract cluster client — the seam every manager talks through.

The reference splits cluster access between a controller-runtime cached
``client.Client`` and a typed clientset ``kubernetes.Interface``
(upgrade_state.go:104-108). Here a single narrow interface covers the union
of operations the upgrade flow actually performs, so it can be backed by:

- :class:`tpu_operator_libs.k8s.fake.FakeCluster` (tests / simulation), or
- :class:`tpu_operator_libs.k8s.real.RealCluster` (live cluster via the
  ``kubernetes`` Python client, import-gated).

All mutating label/annotation operations use merge-patch semantics with
``None`` meaning "delete the key", mirroring the reference's raw merge
patches (node_upgrade_state_provider.go:80-82,147-151).
"""

from __future__ import annotations

import abc
from typing import TYPE_CHECKING, Mapping, Optional

if TYPE_CHECKING:  # pragma: no cover - import cycle guard, types only
    from tpu_operator_libs.k8s.watch import Watch

from tpu_operator_libs.k8s.objects import (
    ControllerRevision,
    DaemonSet,
    Node,
    Pod,
)


class NotFoundError(KeyError):
    """Object does not exist (client-go apierrors.IsNotFound analogue)."""


class K8sClient(abc.ABC):
    """The cluster operations required by the upgrade state machine."""

    # -- nodes ------------------------------------------------------------
    @abc.abstractmethod
    def get_node(self, name: str) -> Node:
        """Return a snapshot copy of the node; raises NotFoundError."""

    @abc.abstractmethod
    def list_nodes(self, label_selector: str = "") -> list[Node]:
        ...

    @abc.abstractmethod
    def patch_node_labels(self, name: str,
                          labels: Mapping[str, Optional[str]]) -> Node:
        """Merge-patch node labels; value None deletes the key."""

    @abc.abstractmethod
    def patch_node_annotations(self, name: str,
                               annotations: Mapping[str, Optional[str]]) -> Node:
        """Merge-patch node annotations; value None deletes the key."""

    @abc.abstractmethod
    def set_node_unschedulable(self, name: str, unschedulable: bool) -> Node:
        """Cordon (True) or uncordon (False) the node."""

    def patch_node_meta(self, name: str,
                        labels: Optional[Mapping[str, Optional[str]]] = None,
                        annotations: Optional[Mapping[str, Optional[str]]]
                        = None) -> Node:
        """Merge-patch labels AND annotations in one write (value None
        deletes the key). The coalesced form of the two patches the
        upgrade flow otherwise issues back to back per transition — one
        wire round-trip instead of two, and crash-atomic where the
        backend patches metadata in a single request (FakeCluster,
        HttpCluster, RealCluster all do). This default falls back to
        two sequential patches so narrow test stubs keep working."""
        node: Optional[Node] = None
        if labels:
            node = self.patch_node_labels(name, labels)
        if annotations:
            node = self.patch_node_annotations(name, annotations)
        if node is None:
            node = self.get_node(name)
        return node

    # -- pods -------------------------------------------------------------
    @abc.abstractmethod
    def list_pods(self, namespace: Optional[str] = None,
                  label_selector: str = "",
                  field_selector: str = "") -> list[Pod]:
        """List pods; ``namespace=None`` means all namespaces
        (pod_manager.go:323-331 lists with Pods(""))."""

    @abc.abstractmethod
    def delete_pod(self, namespace: str, name: str) -> None:
        """Delete a pod; raises NotFoundError if absent."""

    def patch_pod_labels(self, namespace: str, name: str,
                         labels: "Mapping[str, Optional[str]]") -> Pod:
        """Merge-patch pod labels (None deletes a key); returns the
        patched pod. Optional capability (shard-selector stamping):
        implemented by FakeCluster and RealCluster."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support pod label patches")

    @abc.abstractmethod
    def evict_pod(self, namespace: str, name: str) -> None:
        """Evict a pod via the eviction subresource (drain path). May raise
        EvictionBlockedError when a disruption budget forbids it."""

    # -- watches ----------------------------------------------------------
    def watch(self, kinds: Optional[set[str]] = None,
              namespace: Optional[str] = None,
              label_selector: str = "") -> "Watch":
        """Stream change events (k8s.watch.WatchEvent) for Nodes / Pods /
        DaemonSets, optionally filtered by kind set and (for namespaced
        kinds) namespace. ``label_selector`` filters server side: only
        matching objects' events arrive, and an already-delivered object
        that stops matching is surfaced as DELETED on this stream (the
        apiserver's selector-scoped view semantics). Returns a
        k8s.watch.Watch. Optional capability: implemented by FakeCluster
        and RealCluster; other backends may leave it unsupported and
        drive reconciles by polling."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support watches")

    # -- events -----------------------------------------------------------
    def upsert_event(self, namespace: str, name: str,
                     event: object) -> None:
        """Record a v1 Event for ``event``'s involved object: create the
        named Event, or — when it already exists (duplicate-counting via
        a correlator) — patch its count/message/lastTimestamp, the way
        client-go's broadcaster PATCHes recurring events. ``event`` is a
        :class:`tpu_operator_libs.util.Event`. Optional capability:
        implemented by FakeCluster and RealCluster; a backend without it
        leaves events in-memory only (the recorder still records)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support the Events API")

    # -- daemonsets & revisions ------------------------------------------
    @abc.abstractmethod
    def list_daemon_sets(self, namespace: str,
                         label_selector: str = "") -> list[DaemonSet]:
        ...

    def patch_daemon_set_annotations(
            self, namespace: str, name: str,
            annotations: Mapping[str, Optional[str]]) -> DaemonSet:
        """Merge-patch DaemonSet annotations; value None deletes the key.
        The RolloutGuard's durable store (quarantined revision, canary
        bake stamp) — fleet-level facts belong on the fleet object, not
        fanned out across node annotations. Optional capability:
        implemented by FakeCluster, HttpCluster and RealCluster; a
        backend without it cannot run canary-gated rollouts."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support DaemonSet "
            f"annotation patches")

    def rollback_daemon_set(self, namespace: str, name: str,
                            revision_hash: str) -> None:
        """Re-pin the DaemonSet's pod template to the ControllerRevision
        carrying ``revision_hash`` (``kubectl rollout undo`` semantics:
        the old revision is re-numbered newest and subsequent pod
        recreations use it). Raises NotFoundError when the DS or the
        revision does not exist. Optional capability: implemented by
        FakeCluster; live backends need the revision's stored template
        data, which this object model does not carry yet."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support DaemonSet rollback")

    @abc.abstractmethod
    def list_controller_revisions(self, namespace: str,
                                  label_selector: str = "") -> list[ControllerRevision]:
        ...


class ApiServerError(RuntimeError):
    """Transient apiserver failure (5xx / non-eviction 429 /
    connection-reset analogue). Retryable: the reference aborts the
    ApplyState pass and relies on re-reconcile (upgrade_state.go:420-423).

    ``retry_after``: seconds the server asked the client to wait before
    retrying (a 429/503 ``Retry-After`` header), or None. Retry loops
    honor it as a floor on their backoff delay
    (controller.Controller._worker)."""

    def __init__(self, *args: object,
                 retry_after: "Optional[float]" = None) -> None:
        super().__init__(*args)
        self.retry_after = retry_after


class EvictionBlockedError(RuntimeError):
    """Eviction rejected (e.g. by a PodDisruptionBudget)."""


class ConflictError(RuntimeError):
    """Optimistic-concurrency failure: the object's resourceVersion moved
    between read and write (apierrors.IsConflict analogue)."""


class GoneError(ApiServerError):
    """410 Gone: the requested resourceVersion fell out of the
    apiserver's watch cache / etcd compaction window
    (apierrors.IsResourceExpired analogue). Subclasses
    :class:`ApiServerError` deliberately — a caller that only knows
    "transient, retry the pass" stays correct — but informers catch it
    specifically: the ONLY sound recovery is a fresh LIST (relist) and
    a new watch from the returned resourceVersion; re-watching from the
    expired cursor would loop 410 forever."""


class AlreadyExistsError(RuntimeError):
    """Create of an object that already exists (apierrors.IsAlreadyExists
    analogue)."""
