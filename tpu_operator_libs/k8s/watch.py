"""Watch plumbing: typed change events streamed from the cluster store.

The reference never implements watches itself — it inherits them from
controller-runtime, whose cached client is fed by list+watch informers and
whose manager triggers the consumer's reconcile on every Node/DaemonSet/Pod
event. Owning the substrate in this build (SURVEY.md §2 "L0") means owning
that machinery too: this module defines the wire-shaped event type and the
subscription object; :class:`tpu_operator_libs.k8s.fake.FakeCluster` emits
events on every mutation, and :mod:`tpu_operator_libs.controller` builds
informers and the watch-driven reconcile loop on top.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass
from typing import Callable, Iterator, Optional

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"
#: Synthetic resync marker: delivered by a BOUNDED Watch after it had to
#: drop events on overflow (apiserver watches use BOOKMARK events to
#: carry resourceVersion checkpoints; here the marker means "events were
#: lost — relist to repair"). ``WatchEvent.object`` is None and ``kind``
#: is empty for these.
BOOKMARK = "BOOKMARK"
#: Synthetic 410-Gone marker: the stream's resourceVersion cursor fell
#: out of the server's watch cache (etcd compaction / cache eviction).
#: Unlike :data:`BOOKMARK` (events were dropped client-side, relist
#: repairs), EXPIRED means the SERVER can no longer replay the gap —
#: the stream is dead after the marker and the consumer must relist and
#: start a fresh watch. ``WatchEvent.object`` is None and ``kind`` is
#: empty for these.
EXPIRED = "EXPIRED"

#: Sentinel object kinds, matching the reference's watched types
#: (Nodes + driver DaemonSets + their pods).
KIND_NODE = "Node"
KIND_POD = "Pod"
KIND_DAEMON_SET = "DaemonSet"


@dataclass(frozen=True)
class WatchEvent:
    """One change notification.

    ``object`` is a snapshot copy (value semantics, like objects that
    crossed the wire) — mutating it never affects the store. For
    :data:`BOOKMARK` resync markers ``object`` is None.
    """

    type: str          # ADDED | MODIFIED | DELETED | BOOKMARK | EXPIRED
    kind: str          # KIND_NODE | KIND_POD | KIND_DAEMON_SET | ""
    object: object     # Node | Pod | DaemonSet snapshot | None


class Watch:
    """A single subscriber's event stream.

    Iterating blocks until the next event or :meth:`stop`.

    Unbounded by default: a subscriber that stops draining leaks memory,
    not deadlocks — the same trade client-go's watch buffers make. Pass
    ``max_queue`` to bound the buffer instead: overflowing events are
    DROPPED (counted in :attr:`overflow_dropped`) and the next
    :meth:`get` returns a single :data:`BOOKMARK` marker telling the
    consumer to relist — a slow consumer degrades observably instead of
    growing the heap forever.
    """

    _STOP = object()

    def __init__(self, on_stop: Optional[Callable[["Watch"], None]] = None,
                 max_queue: Optional[int] = None) -> None:
        if max_queue is not None and max_queue < 1:
            raise ValueError("max_queue must be >= 1 (or None = unbounded)")
        self._queue: "queue.Queue[object]" = queue.Queue(
            maxsize=max_queue or 0)
        self._on_stop = on_stop
        self._stopped = threading.Event()
        self._overflow_lock = threading.Lock()
        self._overflow_pending = False
        #: Events dropped on a full bounded queue (observability; 0 on
        #: unbounded watches).
        self.overflow_dropped = 0

    # -- producer side ---------------------------------------------------
    def _deliver(self, event: WatchEvent) -> None:
        if self._stopped.is_set():
            return
        try:
            self._queue.put_nowait(event)
        except queue.Full:
            # Bounded watch overflow: the event is lost; record the loss
            # and arrange for the consumer to see one BOOKMARK marker so
            # it knows a relist is required (dropping silently would
            # leave its derived state stale forever).
            with self._overflow_lock:
                self.overflow_dropped += 1
                self._overflow_pending = True

    def _take_overflow_marker(self) -> bool:
        with self._overflow_lock:
            if self._overflow_pending:
                self._overflow_pending = False
                return True
            return False

    # -- consumer side ---------------------------------------------------
    def get(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        """Next event, or None on timeout / after stop."""
        if self._take_overflow_marker():
            return WatchEvent(BOOKMARK, "", None)
        if self._stopped.is_set() and self._queue.empty():
            return None
        try:
            item = self._queue.get(timeout=timeout)
        except queue.Empty:
            # the overflow may have been recorded while we blocked
            if self._take_overflow_marker():
                return WatchEvent(BOOKMARK, "", None)
            return None
        if item is Watch._STOP:
            return None
        assert isinstance(item, WatchEvent)
        return item

    def __iter__(self) -> Iterator[WatchEvent]:
        while True:
            event = self.get()
            if event is None and self._stopped.is_set():
                return
            if event is not None:
                yield event

    def expire(self) -> None:
        """Fault injection: the server declares this stream's cursor
        expired (410 Gone). One :data:`EXPIRED` marker is enqueued and
        the stream stops — the consumer drains the backlog, sees the
        marker, and must relist + rewatch. Delivery uses the normal
        queue so events already in flight are not reordered past the
        marker."""
        if self._stopped.is_set():
            return
        try:
            self._queue.put_nowait(WatchEvent(EXPIRED, "", None))
        except queue.Full:
            # a full bounded queue already owes the consumer a relist
            # (BOOKMARK overflow path); losing the marker is safe
            # because stop() below still forces the rewatch
            with self._overflow_lock:
                self._overflow_pending = True
        self.stop()

    def stop(self) -> None:
        if self._stopped.is_set():
            return
        self._stopped.set()
        try:
            self._queue.put_nowait(Watch._STOP)
        except queue.Full:
            # a full bounded queue still wakes the consumer: get() checks
            # the stopped flag once the backlog drains
            pass
        if self._on_stop is not None:
            self._on_stop(self)

    @property
    def stopped(self) -> bool:
        return self._stopped.is_set()


class WatchBroadcaster:
    """Fan-out of cluster change events to any number of subscribers.

    The store (FakeCluster) calls :meth:`notify` on each mutation;
    subscribers register via :meth:`subscribe`, optionally filtered by
    kind. Delivery is synchronous enqueue — subscribers consume on their
    own threads.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._subs: list[tuple[Optional[frozenset[str]],
                               Optional[Callable[[WatchEvent], bool]],
                               Watch, bool,
                               Optional[Callable[[WatchEvent],
                                                 Optional[WatchEvent]]]]] = []

    def subscribe(self, kinds: Optional[set[str]] = None,
                  predicate: Optional[Callable[[WatchEvent], bool]] = None,
                  max_queue: Optional[int] = None,
                  delay_exempt: bool = False,
                  transform: Optional[Callable[
                      [WatchEvent], Optional[WatchEvent]]] = None) -> Watch:
        """``delay_exempt`` marks a subscriber that keeps receiving
        events in real time while a watch-delay fault buffers delivery
        to everyone else — the invariant monitor's stream (the auditor
        must see ground truth; the system under test sees the lag).

        ``transform`` is a per-subscription event rewriter applied
        after the kind/predicate filters: return the event (possibly
        replaced) to deliver, or None to suppress. This is the seam
        server-side label selectors ride on — the apiserver turns a
        MODIFIED that stops matching the selector into a DELETED on
        that watch, which is a per-subscriber rewrite, not a global
        predicate."""
        watch = Watch(on_stop=self._unsubscribe, max_queue=max_queue)
        kindset = frozenset(kinds) if kinds is not None else None
        with self._lock:
            self._subs.append(
                (kindset, predicate, watch, delay_exempt, transform))
        return watch

    def _unsubscribe(self, watch: Watch) -> None:
        with self._lock:
            self._subs = [row for row in self._subs
                          if row[2] is not watch]

    def notify(self, event_type: str, kind: str, obj: object,
               exempt_only: Optional[bool] = None) -> None:
        """Deliver one event. ``exempt_only`` restricts the fan-out:
        True delivers only to delay-exempt subscribers (live delivery
        while a delay fault buffers), False only to the non-exempt
        ones (the buffered backlog's release), None to everyone."""
        event = WatchEvent(event_type, kind, obj)
        with self._lock:
            subs = list(self._subs)
        for kindset, predicate, watch, exempt, transform in subs:
            if exempt_only is not None and exempt != exempt_only:
                continue
            if kindset is not None and kind not in kindset:
                continue
            if predicate is not None and not predicate(event):
                continue
            delivered = event
            if transform is not None:
                delivered = transform(event)
                if delivered is None:
                    continue
            watch._deliver(delivered)

    def drop_all(self) -> int:
        """Fault injection: terminate every subscriber's stream (the
        apiserver closing watch connections). Consumers observe their
        Watch as stopped and must resubscribe + relist — exactly the
        informer relist path a real stream drop forces. Returns the
        number of streams dropped."""
        with self._lock:
            subs = [row[2] for row in self._subs]
            self._subs = []
        for watch in subs:
            watch.stop()
        return len(subs)

    def expire_all(self) -> int:
        """Fault injection: 410-expire every subscriber's stream (an
        etcd compaction invalidating all outstanding watch cursors at
        once). Each consumer receives one :data:`EXPIRED` marker, then
        its stream is stopped. Returns the number of streams expired."""
        with self._lock:
            subs = [row[2] for row in self._subs]
            self._subs = []
        for watch in subs:
            watch.expire()
        return len(subs)

    def subscriber_count(self) -> int:
        with self._lock:
            return len(self._subs)
