"""FakeCluster: a thread-safe in-memory Kubernetes API server.

This is the build's envtest substitute (SURVEY.md §4 / BASELINE config #1:
"single-node UpgradeStateManager reconcile via envtest + fake clientset").
The reference test suite boots a real etcd+apiserver via envtest
(upgrade_suit_test.go:73-97); we model the same observable semantics in
memory:

- Value semantics: every read returns a deep copy, every write goes through
  an explicit API call — callers can never mutate the store through a
  returned object, exactly like objects that crossed the wire.
- Merge-patch label/annotation updates with ``None`` ⇒ delete, matching the
  raw patches the reference issues (node_upgrade_state_provider.go:80-82,
  147-151).
- Label/field selector list semantics via tpu_operator_libs.k8s.selectors.
- No kubelet and no controllers by default: deleting a pod just deletes it —
  the property the reference's drain tests rely on (SURVEY.md §4 caveat).

Beyond envtest, an optional **DaemonSet controller simulation**
(:meth:`FakeCluster.enable_ds_controller`) recreates deleted DS-owned pods
with the newest ControllerRevision hash after a configurable (virtual) delay
and marks them Ready after another delay. Combined with the injectable Clock
this turns the fake into a discrete-event simulator of a rolling upgrade —
the engine behind ``bench.py`` and the e2e tests (BASELINE configs #2-#4).
"""

from __future__ import annotations

import heapq
import random
import threading
from dataclasses import dataclass, field
from typing import Callable, Mapping, Optional

from tpu_operator_libs.consts import POD_CONTROLLER_REVISION_HASH_LABEL
from tpu_operator_libs.k8s.client import (
    AlreadyExistsError,
    ApiServerError,
    ConflictError,
    EvictionBlockedError,
    K8sClient,
    NotFoundError,
)
from tpu_operator_libs.k8s.objects import (
    ContainerStatus,
    ControllerRevision,
    DaemonSet,
    Lease,
    Node,
    ObjectMeta,
    OwnerReference,
    Pod,
    PodDisruptionBudget,
    PodPhase,
    PodSpec,
    PodStatus,
    new_uid,
)
from tpu_operator_libs.k8s.selectors import (
    exact_field_requirement,
    parse_field_selector,
    parse_label_selector,
)
from tpu_operator_libs.k8s.watch import (
    ADDED,
    DELETED,
    KIND_DAEMON_SET,
    KIND_NODE,
    KIND_POD,
    MODIFIED,
    Watch,
    WatchBroadcaster,
    WatchEvent,
)
from tpu_operator_libs.util import Clock, FakeClock


class FrozenClusterError(RuntimeError):
    """A mutating call reached a frozen (read-only) FakeCluster.

    Deliberately NOT an :class:`ApiServerError` subclass: transient
    apiserver errors are retried/absorbed by the reconcile machinery,
    but a write against a preflight clone is a logic bug that must
    fail loudly, never be silently retried away.
    """


@dataclass
class _DsControllerConfig:
    recreate_delay: float = 5.0
    ready_delay: float = 10.0
    pod_gc_delay: float = 30.0
    enabled: bool = True


@dataclass(order=True)
class _ScheduledAction:
    due: float
    seq: int
    action: Callable[[], None] = field(compare=False)


class FakeCluster(K8sClient):
    """In-memory cluster store implementing :class:`K8sClient`."""

    def __init__(self, clock: Optional[Clock] = None) -> None:
        self._clock = clock or Clock()
        self._lock = threading.RLock()
        self._nodes: dict[str, Node] = {}
        self._pods: dict[tuple[str, str], Pod] = {}
        # v1 Events written through the recorder sink, keyed (ns, name)
        self._cluster_events: dict[tuple[str, str], object] = {}
        # policy/v1 PodDisruptionBudgets, keyed (ns, name)
        self._pdbs: dict[tuple[str, str], PodDisruptionBudget] = {}
        # spec.nodeName index over _pods, maintained by _pod_put/_pod_pop
        # (pod nodeName is immutable once bound, as in Kubernetes, so
        # membership never changes in place). Serves the apiserver's
        # indexed spec.nodeName field-selector path at fleet scale: a
        # drain wave issues one pods-on-node LIST per node, and a full
        # scan per LIST makes the wave O(pods^2).
        self._pods_by_node: dict[str, set[tuple[str, str]]] = {}
        self._daemon_sets: dict[tuple[str, str], DaemonSet] = {}
        self._revisions: dict[tuple[str, str], ControllerRevision] = {}
        # Revision ownership by DS identity, so DaemonSets whose names share
        # a prefix (e.g. "tpu" / "tpu-plugin") never see each other's
        # revisions. (The reference's prefix-scan, pod_manager.go:104-109,
        # has exactly that collision; the fake must not inherit it.)
        self._revision_owner: dict[tuple[str, str], tuple[str, str]] = {}
        self._leases: dict[tuple[str, str], Lease] = {}
        self._scheduled: list[_ScheduledAction] = []
        self._seq = 0
        self._ds_controller: Optional[_DsControllerConfig] = None
        # Optional per-node (recreate_delay, ready_delay) override for the
        # DS-controller sim — models heterogeneous hosts / stragglers so
        # simulated latency distributions have a real tail.
        self._ds_delay_fn: Optional[
            Callable[[str], tuple[float, float]]] = None
        self._eviction_blockers: list[Callable[[Pod], bool]] = []
        # Health gate consulted by the DS-controller simulation before
        # marking a recreated pod Ready. Returning False models a
        # crash-looping runtime: the pod stays not-ready with a
        # crash-loop restart count and readiness is retried later.
        self._pod_ready_gate: Optional[Callable[[Pod], bool]] = None
        # Per-node count of reads that should return a stale copy, to
        # exercise the provider's cache-sync poll loop
        # (node_upgrade_state_provider.go:100-117).
        self._stale_reads: dict[str, tuple[int, Node]] = {}
        # Per-operation count of every API call served — the wire-cost
        # instrumentation tools/reconcile_bench.py diffs to prove the
        # watch-indexed read path actually eliminates per-pass LISTs.
        self._api_call_counts: dict[str, int] = {}
        # Per-operation budget of injected transient API failures
        # (apiserver 5xx / connection-reset modeling); consumed one per
        # call. The reference's answer to such errors is abort-the-pass +
        # re-reconcile (upgrade_state.go:420-423), so tests assert the
        # machine converges through them.
        self._api_errors: dict[str, int] = {}
        self._api_error_exc: dict[str, Callable[[], Exception]] = {}
        # Watch fan-out: every mutation below emits a typed event so
        # informers/controllers (tpu_operator_libs.controller) can drive
        # reconciles the way controller-runtime does for the reference.
        self._broadcaster = WatchBroadcaster()
        # Watch-delay fault state (delay_watch_events): while a window
        # is active, events for non-exempt subscribers buffer here.
        self._watch_delay_buffer: Optional[list] = None
        self._watch_delay_until = 0.0
        self._watch_delay_seed = 0
        #: Events released from delay buffers (observability/tests).
        self.watch_delay_released = 0
        # Freeze tripwire (preflight read-only clones): while set, every
        # mutating entry point raises FrozenClusterError AND increments
        # the attempt counter — the counter is the invariant monitor's
        # evidence that a forecast pass tried to write.
        self._frozen: Optional[str] = None
        #: Mutating calls rejected while frozen (tripwire evidence).
        self.frozen_write_attempts = 0
        # Admission mutators (kind -> [fn(obj)]): applied to the STORED
        # copy of every object of that kind as it enters the store —
        # creation helpers AND controller-sim recreations — before its
        # watch event fires. The mutating-webhook seam: shard-selector
        # stamping uses it so recreated pods are born carrying their
        # partition label and a server-side-filtered watch never
        # misses the recreation.
        self._admission_mutators: dict[str, list] = {}

    def freeze(self, reason: str = "preflight") -> None:
        """Flip the store read-only: every subsequent mutating call —
        API writes AND test/sim helpers alike — raises
        :class:`FrozenClusterError` and increments
        :attr:`frozen_write_attempts`. There is deliberately no thaw:
        a preflight clone stays frozen for its whole life, so a zero
        counter at the end of a forecast proves computational purity."""
        with self._lock:
            self._frozen = reason

    @property
    def frozen(self) -> bool:
        return self._frozen is not None

    def _check_frozen(self, operation: str) -> None:
        with self._lock:
            if self._frozen is None:
                return
            self.frozen_write_attempts += 1
            reason = self._frozen
        raise FrozenClusterError(
            f"{operation} rejected: cluster is frozen ({reason}) — "
            f"preflight clones are read-only")

    def snapshot(self, frozen: bool = True) -> "FakeCluster":
        """Deep-copy the object store into an independent FakeCluster
        pinned at the current virtual time. Scheduled actions,
        controller sims, fault state, watch subscribers, and call
        counters do NOT carry over — the clone is a pure picture of
        cluster state, frozen by default (the preflight substrate)."""
        import copy

        with self._lock:
            clone = FakeCluster(clock=FakeClock(start=self._clock.now()))
            clone._nodes = {k: v.clone() for k, v in self._nodes.items()}
            for pod in self._pods.values():
                clone._pod_put(pod.clone())
            clone._daemon_sets = {
                k: v.clone() for k, v in self._daemon_sets.items()}
            clone._revisions = {
                k: v.clone() for k, v in self._revisions.items()}
            clone._revision_owner = dict(self._revision_owner)
            clone._pdbs = {k: v.clone() for k, v in self._pdbs.items()}
            clone._leases = {k: v.clone() for k, v in self._leases.items()}
            clone._cluster_events = {
                k: copy.copy(v) for k, v in self._cluster_events.items()}
        if frozen:
            clone.freeze()
        return clone

    def watch(self, kinds: Optional[set[str]] = None,
              namespace: Optional[str] = None,
              max_queue: Optional[int] = None,
              delay_exempt: bool = False,
              label_selector: str = "") -> Watch:
        """Subscribe to change events, optionally filtered to a kind set
        ({"Node", "Pod", "DaemonSet"}) and — for namespaced kinds — a
        namespace. Snapshot copies only. Signature matches
        RealCluster.watch so consumers are backend-agnostic.
        ``max_queue`` bounds the subscriber's buffer (overflow drops
        events and delivers a BOOKMARK resync marker, k8s.watch.Watch);
        ``delay_exempt`` keeps the stream live through a watch-delay
        fault window (harness/auditor streams only).

        ``label_selector`` server-side filters the stream with the
        apiserver's exact semantics: only events for matching objects
        are delivered, and an object this stream HAS delivered that
        stops matching (label change mid-watch) is surfaced as a
        synthetic DELETED — the selector-scoped view genuinely lost
        the object, and a consumer that cached it must evict it."""
        predicate = None
        if namespace:
            def predicate(event):
                meta = getattr(event.object, "metadata", None)
                ns = getattr(meta, "namespace", "")
                return not ns or ns == namespace
        transform = (self._selector_transform(label_selector)
                     if label_selector else None)
        return self._broadcaster.subscribe(kinds, predicate,
                                           max_queue=max_queue,
                                           delay_exempt=delay_exempt,
                                           transform=transform)

    def _selector_transform(self, label_selector: str):
        """Per-subscription server-side selector state machine. The
        ``seen`` set (primed from the live store under the lock, so a
        subscriber that LISTs right after subscribing agrees with its
        stream) tracks which objects this stream's view contains;
        membership decides whether a stops-matching MODIFIED becomes a
        retiring DELETED or is silently suppressed."""
        match = parse_label_selector(label_selector)
        seen: set[tuple[str, str, str]] = set()
        with self._lock:
            for node in self._nodes.values():
                if match(node.metadata.labels):
                    seen.add((KIND_NODE, "", node.metadata.name))
            for (ns, name), pod in self._pods.items():
                if match(pod.metadata.labels):
                    seen.add((KIND_POD, ns, name))
            for (ns, name), ds in self._daemon_sets.items():
                if match(ds.metadata.labels):
                    seen.add((KIND_DAEMON_SET, ns, name))

        def transform(event: WatchEvent) -> Optional[WatchEvent]:
            meta = getattr(event.object, "metadata", None)
            if meta is None:
                return event  # BOOKMARK-style markers pass through
            key = (event.kind, getattr(meta, "namespace", "") or "",
                   meta.name)
            if event.type == DELETED:
                was_seen = key in seen
                seen.discard(key)
                return event if (was_seen or match(meta.labels)) else None
            if match(meta.labels):
                seen.add(key)
                return event
            if key in seen:
                # stopped matching mid-watch: this selector's view lost
                # the object — the apiserver emits DELETED here
                seen.discard(key)
                return WatchEvent(DELETED, event.kind, event.object)
            return None

        return transform

    def drop_watch_streams(self) -> int:
        """Fault injection: close every open watch stream, the way a real
        apiserver drops watch connections (timeouts, resourceVersion
        compaction). Each consumer observes its Watch as stopped and must
        resubscribe + relist — the informer-relist path. Returns the
        number of streams dropped."""
        return self._broadcaster.drop_all()

    def expire_watch_streams(self) -> int:
        """Fault injection: 410-expire every open watch stream — an etcd
        compaction invalidating all outstanding cursors at once. Unlike
        :meth:`drop_watch_streams` (silent close, consumers infer the
        relist from a stopped stream), each consumer first receives one
        EXPIRED marker, the in-band "410 Gone" the apiserver sends
        before closing; informers must relist and start a fresh watch on
        seeing it. Returns the number of streams expired."""
        return self._broadcaster.expire_all()

    def inject_conflict_storm(self, operation: str, count: int) -> None:
        """Fault injection: the next ``count`` calls of ``operation``
        fail 409 Conflict (the object's resourceVersion moved between
        the caller's read and its write — a hot controller peer racing
        every patch). Sugar over :meth:`inject_api_errors` with a
        :class:`ConflictError` factory; unlike the default transient
        ApiServerError, 409 signals a LOST RACE, so callers must
        refetch + recheck their precondition before reissuing, and park
        rather than spin when the storm outlasts their retry budget."""
        self.inject_api_errors(
            operation, count,
            exc_factory=lambda: ConflictError(
                f"injected conflict storm on {operation}: object "
                f"modified, resourceVersion mismatch"))

    def delay_watch_events(self, start: float, until: float,
                           seed: int = 0) -> None:
        """Fault injection: from ``start`` to ``until`` (virtual
        seconds), watch event delivery to non-exempt subscribers is
        BUFFERED — their informer caches go stale with no relist
        signal (distinct from :meth:`drop_watch_streams`, which stops
        the stream and forces a relist). At the window close the
        backlog is released with deterministic, seed-pure reordering
        ACROSS kinds: per-object (and per-kind) event order is
        preserved — an apiserver never reorders one connection's
        stream — but the separate per-kind streams an informer runs
        genuinely race each other, so the release interleaves the
        kind buffers in a seed-chosen order. Exempt subscribers (the
        invariant monitor) keep receiving events live throughout."""
        if until <= start:
            raise ValueError("until must be after start")
        self.schedule_at(
            start, lambda: self._begin_watch_delay(until, seed))

    def _begin_watch_delay(self, until: float, seed: int) -> None:
        if self._watch_delay_buffer is not None:
            # overlapping windows: extend the active one
            self._watch_delay_until = max(self._watch_delay_until, until)
            return
        self._watch_delay_buffer = []
        self._watch_delay_until = until
        self._watch_delay_seed = seed
        self.schedule_at(until, self._flush_watch_delay)

    def _flush_watch_delay(self) -> None:
        if self._watch_delay_buffer is None:
            return
        if self._clock.now() < self._watch_delay_until:
            return  # window was extended; the later flush releases
        buffered, self._watch_delay_buffer = \
            self._watch_delay_buffer, None
        by_kind: dict[str, list] = {}
        for event_type, kind, obj in buffered:
            by_kind.setdefault(kind, []).append((event_type, kind, obj))
        kinds = sorted(by_kind)
        random.Random(
            f"watch-delay:{self._watch_delay_seed}").shuffle(kinds)
        self.watch_delay_released += len(buffered)
        for kind in kinds:
            for event_type, _, obj in by_kind[kind]:
                self._broadcaster.notify(event_type, kind, obj,
                                         exempt_only=False)

    def _notify(self, event_type: str, kind: str, obj) -> None:
        if self._watch_delay_buffer is not None \
                and self._clock.now() < self._watch_delay_until:
            # delay window active: exempt streams get the event live,
            # everyone else sees it only at the flush
            snapshot = obj.clone()
            self._watch_delay_buffer.append((event_type, kind, snapshot))
            self._broadcaster.notify(event_type, kind, snapshot,
                                     exempt_only=True)
            return
        self._broadcaster.notify(event_type, kind, obj.clone())

    # ------------------------------------------------------------------
    # test/simulation helpers
    # ------------------------------------------------------------------
    @property
    def clock(self) -> Clock:
        return self._clock

    def add_admission_mutator(self, kind: str,
                              fn: Callable[[object], None]) -> None:
        """Register a mutating-admission hook for ``kind`` ("Node" /
        "Pod" / ...): applied to the stored copy of every object of
        that kind entering the store — test helpers and controller-sim
        recreations alike — before its watch event is emitted. Hooks
        must be idempotent (replacement writes re-run them, like a
        real mutating webhook on UPDATE)."""
        with self._lock:
            self._admission_mutators.setdefault(kind, []).append(fn)

    def _admit(self, kind: str, obj: object) -> None:
        for fn in self._admission_mutators.get(kind, ()):
            fn(obj)

    def add_node(self, node: Node) -> Node:
        self._check_frozen("add_node")
        with self._lock:
            stored = node.clone()
            self._admit(KIND_NODE, stored)
            self._nodes[node.metadata.name] = stored
            self._notify(ADDED, KIND_NODE, stored)
        return node

    def delete_node(self, name: str) -> None:
        """Remove a node (scale-down / repair events in tests and sims).

        With the DS controller sim enabled this models the real control
        plane's follow-through: desired counts of DaemonSets that had a
        pod on the node drop immediately, and the node's pods linger
        until pod GC deletes them ``pod_gc_delay`` virtual seconds later
        — exactly the window the state machine's vanished-node skip
        covers.
        """
        self._check_frozen("delete_node")
        with self._lock:
            node = self._nodes.pop(name, None)
            if node is None:
                raise NotFoundError(f"node {name!r} not found")
            self._notify(DELETED, KIND_NODE, node)
            cfg = self._ds_controller
            if cfg is None or not cfg.enabled:
                return
            stranded = [self._pods[k] for k in sorted(
                self._pods_by_node.get(name, ()))]
            for pod in stranded:
                owner = pod.controller_owner()
                if owner is not None and owner.kind == "DaemonSet":
                    ds_key = self._ds_key_by_owner_uid(owner.uid)
                    if ds_key is not None:
                        ds = self._daemon_sets[ds_key]
                        ds.status.desired_number_scheduled = max(
                            0, ds.status.desired_number_scheduled - 1)
                        self._notify(MODIFIED, KIND_DAEMON_SET, ds)
                key = (pod.metadata.namespace, pod.metadata.name)

                def gc(pod_key=key) -> None:
                    with self._lock:
                        gone = self._pod_pop(pod_key)
                        if gone is not None:
                            self._notify(DELETED, KIND_POD, gone)
                        # no recreate: the node is gone

                self._schedule(cfg.pod_gc_delay, gc)

    def _pod_put(self, pod: Pod) -> None:
        """Insert/replace a pod in the store + nodeName index (lock held).
        Admission mutators run here — the single choke point every pod
        insertion (helpers AND DS-controller recreations) flows
        through, so a recreated pod is stamped before its ADDED event."""
        self._admit(KIND_POD, pod)
        key = (pod.metadata.namespace, pod.metadata.name)
        if key in self._pods:
            # replacing an existing pod: drop its old index entry, which
            # may live under a different node
            self._pod_pop(key)
        self._pods[key] = pod
        if pod.spec.node_name:
            self._pods_by_node.setdefault(
                pod.spec.node_name, set()).add(key)

    def _pod_pop(self, key: tuple[str, str]) -> Optional[Pod]:
        """Remove a pod from the store + nodeName index (lock held)."""
        pod = self._pods.pop(key, None)
        if pod is not None and pod.spec.node_name:
            members = self._pods_by_node.get(pod.spec.node_name)
            if members is not None:
                members.discard(key)
                if not members:
                    del self._pods_by_node[pod.spec.node_name]
        return pod

    def add_pod(self, pod: Pod) -> Pod:
        self._check_frozen("add_pod")
        with self._lock:
            stored = pod.clone()
            self._pod_put(stored)
            # notify with the stored copy: admission mutators ran on it
            self._notify(ADDED, KIND_POD, stored)
        return pod

    @staticmethod
    def _check_revision_hash(revision_hash: str) -> None:
        """Controller-generated revision hashes are single dash-free
        segments; enforcing that here keeps the '<ds-name>-<hash>' naming
        scheme reversible (pod_manager.go:118-119)."""
        if not revision_hash or "-" in revision_hash:
            raise ValueError(
                f"revision hash must be a non-empty dash-free segment, "
                f"got {revision_hash!r}")

    def add_daemon_set(self, ds: DaemonSet,
                       revision_hash: str = "rev1",
                       revision: int = 1) -> DaemonSet:
        """Register a DaemonSet plus its current ControllerRevision.

        The revision object is named ``<ds-name>-<hash>`` so the hash can be
        recovered as the name suffix (pod_manager.go:118-119).
        """
        self._check_frozen("add_daemon_set")
        self._check_revision_hash(revision_hash)
        with self._lock:
            self._daemon_sets[(ds.metadata.namespace, ds.metadata.name)] = (
                ds.clone())
            rev_name = f"{ds.metadata.name}-{revision_hash}"
            rev = ControllerRevision(
                metadata=ObjectMeta(name=rev_name,
                                    namespace=ds.metadata.namespace,
                                    labels=dict(ds.spec.selector)),
                revision=revision)
            self._revisions[(ds.metadata.namespace, rev_name)] = rev
            self._revision_owner[(ds.metadata.namespace, rev_name)] = (
                ds.metadata.namespace, ds.metadata.name)
            self._notify(ADDED, KIND_DAEMON_SET, ds)
        return ds

    def _revisions_of(self, namespace: str, ds_name: str) -> list[ControllerRevision]:
        """Revisions owned by exactly this DaemonSet (lock must be held)."""
        return [rev for key, rev in self._revisions.items()
                if self._revision_owner.get(key) == (namespace, ds_name)]

    def set_daemon_set_desired(self, namespace: str, name: str,
                               desired: int) -> None:
        """Adjust a DaemonSet's desired count (scale-up/down events in
        tests — the real DS controller recomputes this from the node
        list)."""
        self._check_frozen("set_daemon_set_desired")
        with self._lock:
            ds = self._daemon_sets.get((namespace, name))
            if ds is None:
                raise NotFoundError(f"daemonset {namespace}/{name} not found")
            ds.status.desired_number_scheduled = desired
            self._notify(MODIFIED, KIND_DAEMON_SET, ds)

    def bump_daemon_set_revision(self, namespace: str, name: str,
                                 revision_hash: str) -> None:
        """Roll the DS template: add a newer ControllerRevision.

        Existing pods keep their old ``controller-revision-hash`` label and
        are therefore out of sync — the trigger condition for an upgrade
        (upgrade_state.go:558-578).
        """
        self._check_frozen("bump_daemon_set_revision")
        self._check_revision_hash(revision_hash)
        with self._lock:
            ds = self._daemon_sets.get((namespace, name))
            if ds is None:
                raise NotFoundError(f"daemonset {namespace}/{name} not found")
            ds.spec.template_generation += 1
            latest = max((r.revision for r in self._revisions_of(namespace, name)),
                         default=0)
            rev_name = f"{name}-{revision_hash}"
            self._revisions[(namespace, rev_name)] = ControllerRevision(
                metadata=ObjectMeta(name=rev_name, namespace=namespace,
                                    labels=dict(ds.spec.selector)),
                revision=latest + 1)
            self._revision_owner[(namespace, rev_name)] = (namespace, name)
            self._notify(MODIFIED, KIND_DAEMON_SET, ds)

    def latest_revision_hash(self, namespace: str, name: str) -> str:
        with self._lock:
            revs = self._revisions_of(namespace, name)
            if not revs:
                raise NotFoundError(f"no revisions for daemonset {name}")
            return max(revs, key=lambda r: r.revision).hash

    def seed_revision_history(self, namespace: str, name: str,
                              hashes: "list[str]") -> None:
        """Seed PRIOR ControllerRevisions for a DaemonSet — oldest first,
        all numbered beneath the current newest revision — so rollback
        paths are testable without hand-building revision objects.
        Existing revisions are re-numbered upward to make room; their
        relative order (and therefore the newest hash) is unchanged."""
        self._check_frozen("seed_revision_history")
        for revision_hash in hashes:
            self._check_revision_hash(revision_hash)
        with self._lock:
            ds = self._daemon_sets.get((namespace, name))
            if ds is None:
                raise NotFoundError(f"daemonset {namespace}/{name} not found")
            for rev in self._revisions_of(namespace, name):
                rev.revision += len(hashes)
            for index, revision_hash in enumerate(hashes, start=1):
                rev_name = f"{name}-{revision_hash}"
                key = (namespace, rev_name)
                if key in self._revisions:
                    raise ValueError(
                        f"revision hash {revision_hash!r} already exists "
                        f"for daemonset {name}")
                self._revisions[key] = ControllerRevision(
                    metadata=ObjectMeta(name=rev_name, namespace=namespace,
                                        labels=dict(ds.spec.selector)),
                    revision=index)
                self._revision_owner[key] = (namespace, name)

    def rollback_daemon_set(self, namespace: str, name: str,
                            revision_hash: str) -> None:
        """Re-pin an EXISTING revision as the DS's update revision
        (``kubectl rollout undo --to-revision`` semantics: the chosen
        revision is re-numbered newest; subsequent DS-controller pod
        recreations carry its hash). Works backward or forward across
        the seeded history. No-op when the hash is already newest."""
        self._check_frozen("rollback_daemon_set")
        self._maybe_api_error("rollback_daemon_set")
        with self._lock:
            ds = self._daemon_sets.get((namespace, name))
            if ds is None:
                raise NotFoundError(f"daemonset {namespace}/{name} not found")
            revs = self._revisions_of(namespace, name)
            target = next((r for r in revs if r.hash == revision_hash), None)
            if target is None:
                raise NotFoundError(
                    f"daemonset {name} has no revision {revision_hash!r}")
            newest = max(revs, key=lambda r: r.revision)
            if newest.hash == revision_hash:
                return
            target.revision = newest.revision + 1
            # the template changed back: a real rollout undo bumps the
            # template generation too
            ds.spec.template_generation += 1
            self._notify(MODIFIED, KIND_DAEMON_SET, ds)

    def patch_daemon_set_annotations(
            self, namespace: str, name: str,
            annotations: Mapping[str, Optional[str]]) -> DaemonSet:
        self._check_frozen("patch_daemon_set_annotations")
        self._maybe_api_error("patch_daemon_set_annotations")
        with self._lock:
            ds = self._daemon_sets.get((namespace, name))
            if ds is None:
                raise NotFoundError(f"daemonset {namespace}/{name} not found")
            for key, value in annotations.items():
                if value is None:
                    ds.metadata.annotations.pop(key, None)
                else:
                    ds.metadata.annotations[key] = value
            ds.metadata.resource_version += 1
            self._notify(MODIFIED, KIND_DAEMON_SET, ds)
            return ds.clone()

    def enable_ds_controller(self, recreate_delay: float = 5.0,
                             ready_delay: float = 10.0,
                             pod_gc_delay: float = 30.0) -> None:
        """Simulate the DaemonSet controller + kubelet: deleted DS pods are
        recreated with the newest revision hash after ``recreate_delay``
        (virtual) seconds and become Ready ``ready_delay`` seconds later.
        When a NODE is deleted, its DaemonSets' desired counts drop
        immediately (the real DS controller reacts to the node list) and
        the node's pods are garbage-collected after ``pod_gc_delay``."""
        self._check_frozen("enable_ds_controller")
        with self._lock:
            self._ds_controller = _DsControllerConfig(
                recreate_delay=recreate_delay, ready_delay=ready_delay,
                pod_gc_delay=pod_gc_delay)

    def set_per_node_ds_delays(
            self, fn: Optional[Callable[[str], tuple[float, float]]]) -> None:
        """Per-node ``(recreate_delay, ready_delay)`` override for the DS
        controller sim; ``fn(node_name)`` wins over the global delays.
        Models heterogeneous hosts and stragglers."""
        self._check_frozen("set_per_node_ds_delays")
        with self._lock:
            self._ds_delay_fn = fn

    def add_eviction_blocker(self, blocker: Callable[[Pod], bool]) -> None:
        """Register a predicate that vetoes evictions (PDB analogue)."""
        self._check_frozen("add_eviction_blocker")
        with self._lock:
            self._eviction_blockers.append(blocker)

    def set_pod_ready_gate(self, gate: Optional[Callable[[Pod], bool]]) -> None:
        """Fault injection: recreated DS pods become Ready only when
        ``gate(pod)`` returns True; until then they crash-loop (not ready,
        restart count above the failure threshold). Replaces any gate
        already installed; use :meth:`add_pod_ready_gate` to compose."""
        self._check_frozen("set_pod_ready_gate")
        with self._lock:
            self._pod_ready_gate = gate

    def add_pod_ready_gate(self, gate: Callable[[Pod], bool]) -> None:
        """Compose ``gate`` with any existing readiness gate (logical
        AND): a recreated pod becomes Ready only when every installed
        gate approves. Lets independent fault sources (a FleetSpec
        crashloop window and a chaos injector, say) coexist without
        silently replacing each other."""
        self._check_frozen("add_pod_ready_gate")
        with self._lock:
            existing = self._pod_ready_gate
            if existing is None:
                self._pod_ready_gate = gate
            else:
                self._pod_ready_gate = (
                    lambda pod, a=existing, b=gate: a(pod) and b(pod))

    def gate_pod_ready_on_node_ready(self) -> None:
        """Compose a readiness gate tying recreated DS pods to their
        node's Ready condition: a pod recreated on a NotReady node
        crash-loops (restart count past the failure threshold) until the
        node comes back. Models a dead host's kubelet never reporting a
        healthy container — the signal the node-kill chaos fault needs
        so a mid-upgrade kill lands in ``upgrade-failed`` instead of
        waiting forever in pod-restart."""
        def gate(pod: Pod) -> bool:
            # called under self._lock (make_ready); read the store
            # directly instead of re-locking through get_node
            node = self._nodes.get(pod.spec.node_name)
            return node is None or node.is_ready()

        self.add_pod_ready_gate(gate)

    def seed_node_with_ds_pod(self, node: Node, ds_namespace: str,
                              ds_name: str,
                              revision_hash: Optional[str] = None,
                              ready: bool = True) -> Node:
        """Test/sim helper: add ``node`` plus a Ready runtime pod owned
        by an existing DaemonSet, bumping the DS desired count to match
        (build_state's completeness guard requires desired == scheduled).
        The spare-pool seeding path for reconfiguration tests: label the
        node as a spare and this wires everything else."""
        self._check_frozen("seed_node_with_ds_pod")
        with self._lock:
            ds = self._daemon_sets.get((ds_namespace, ds_name))
            if ds is None:
                raise NotFoundError(
                    f"daemonset {ds_namespace}/{ds_name} not found")
        if revision_hash is None:
            revision_hash = self.latest_revision_hash(ds_namespace, ds_name)
        self.add_node(node)
        labels = dict(ds.spec.selector)
        labels[POD_CONTROLLER_REVISION_HASH_LABEL] = revision_hash
        self.add_pod(Pod(
            metadata=ObjectMeta(
                name=f"{ds_name}-{node.metadata.name}",
                namespace=ds_namespace, labels=labels,
                owner_references=[OwnerReference(
                    kind="DaemonSet", name=ds_name, uid=ds.metadata.uid)]),
            spec=PodSpec(node_name=node.metadata.name),
            status=PodStatus(
                phase=PodPhase.RUNNING,
                container_statuses=[
                    ContainerStatus(name="runtime", ready=ready)])))
        with self._lock:
            live = self._daemon_sets[(ds_namespace, ds_name)]
            live.status.desired_number_scheduled += 1
            self._notify(MODIFIED, KIND_DAEMON_SET, live)
        return node

    def inject_api_errors(self, operation: str, count: int,
                          exc_factory: Optional[Callable[[], Exception]]
                          = None) -> None:
        """The next ``count`` calls of ``operation`` (a K8sClient method
        name, e.g. ``"patch_node_labels"``) raise a transient
        :class:`ApiServerError` (or ``exc_factory()``). Each call sets the
        factory for the whole outstanding budget — passing None restores
        the default ApiServerError."""
        self._check_frozen("inject_api_errors")
        with self._lock:
            self._api_errors[operation] = (
                self._api_errors.get(operation, 0) + count)
            if exc_factory is not None:
                self._api_error_exc[operation] = exc_factory
            else:
                self._api_error_exc.pop(operation, None)

    def api_call_counts(self) -> dict[str, int]:
        """Snapshot of API calls served per operation (every K8sClient
        entry point counts itself on entry, successes and injected
        failures alike — a failed wire call still cost a round trip)."""
        with self._lock:
            return dict(self._api_call_counts)

    def reset_api_call_counts(self) -> None:
        with self._lock:
            self._api_call_counts.clear()

    def _maybe_api_error(self, operation: str) -> None:
        with self._lock:
            self._api_call_counts[operation] = (
                self._api_call_counts.get(operation, 0) + 1)
        self._consume_injected_error(operation)

    def _consume_injected_error(self, operation: str) -> None:
        with self._lock:
            remaining = self._api_errors.get(operation, 0)
            if remaining <= 0:
                return
            self._api_errors[operation] = remaining - 1
            factory = self._api_error_exc.get(operation)
            if remaining == 1:
                # budget exhausted: a later injection without a factory
                # must get the documented default, not this leftover
                self._api_error_exc.pop(operation, None)
        raise factory() if factory else ApiServerError(
            f"injected transient apiserver error on {operation}")

    def inject_stale_node_reads(self, name: str, reads: int) -> None:
        """Make the next ``reads`` get_node() calls return the current
        (pre-future-patch) snapshot, emulating controller-runtime cache lag
        that the provider's poll loop exists to absorb
        (node_upgrade_state_provider.go:92-99)."""
        self._check_frozen("inject_stale_node_reads")
        if reads <= 0:
            return
        with self._lock:
            node = self._nodes.get(name)
            if node is None:
                raise NotFoundError(name)
            self._stale_reads[name] = (reads, node.clone())

    def step(self, until: Optional[float] = None) -> int:
        """Run scheduled simulation actions due at or before ``until``
        (defaults to the clock's current time), in (due, insertion)
        order. Returns actions run. The queue is a heap: a fleet-wide
        drain wave schedules thousands of recreation/ready actions, and
        the previous scan-filter-sort-remove loop made draining the
        queue O(n^2 log n) in wave size."""
        now = self._clock.now() if until is None else until
        ran = 0
        while True:
            with self._lock:
                if not self._scheduled or self._scheduled[0].due > now:
                    return ran
                action = heapq.heappop(self._scheduled)
            action.action()
            ran += 1

    def pending_actions(self) -> int:
        with self._lock:
            return len(self._scheduled)

    def next_action_due(self) -> Optional[float]:
        with self._lock:
            if not self._scheduled:
                return None
            return self._scheduled[0].due

    def _schedule(self, delay: float, action: Callable[[], None]) -> float:
        return self.schedule_at(self._clock.now() + delay, action)

    def schedule_at(self, due: float, action: Callable[[], None]) -> float:
        """Public scheduler hook: run ``action`` once the virtual clock
        reaches ``due`` and :meth:`step` is called. Used by fault
        injection (tpu_operator_libs.simulate) and available to tests."""
        self._check_frozen("schedule_at")
        with self._lock:
            self._seq += 1
            heapq.heappush(self._scheduled,
                           _ScheduledAction(due, self._seq, action))
            return due

    # ------------------------------------------------------------------
    # K8sClient: nodes
    # ------------------------------------------------------------------
    def get_node(self, name: str) -> Node:
        self._maybe_api_error("get_node")
        with self._lock:
            stale = self._stale_reads.get(name)
            if stale is not None:
                remaining, snapshot = stale
                if remaining > 1:
                    self._stale_reads[name] = (remaining - 1, snapshot)
                else:
                    del self._stale_reads[name]
                return snapshot.clone()
            node = self._nodes.get(name)
            if node is None:
                raise NotFoundError(f"node {name!r} not found")
            return node.clone()

    def list_nodes(self, label_selector: str = "") -> list[Node]:
        self._maybe_api_error("list_nodes")
        match = parse_label_selector(label_selector)
        with self._lock:
            return [n.clone() for n in self._nodes.values()
                    if match(n.metadata.labels)]

    def _mutate_node(self, name: str) -> Node:
        node = self._nodes.get(name)
        if node is None:
            raise NotFoundError(f"node {name!r} not found")
        node.metadata.resource_version += 1
        return node

    def patch_node_labels(self, name: str,
                          labels: Mapping[str, Optional[str]]) -> Node:
        self._check_frozen("patch_node_labels")
        self._maybe_api_error("patch_node_labels")
        with self._lock:
            node = self._mutate_node(name)
            for key, value in labels.items():
                if value is None:
                    node.metadata.labels.pop(key, None)
                else:
                    node.metadata.labels[key] = value
            self._notify(MODIFIED, KIND_NODE, node)
            return node.clone()

    def patch_pod_labels(self, namespace: str, name: str,
                         labels: Mapping[str, Optional[str]]) -> Pod:
        self._check_frozen("patch_pod_labels")
        self._maybe_api_error("patch_pod_labels")
        with self._lock:
            pod = self._pods.get((namespace, name))
            if pod is None:
                raise NotFoundError(f"pod {namespace}/{name} not found")
            for key, value in labels.items():
                if value is None:
                    pod.metadata.labels.pop(key, None)
                else:
                    pod.metadata.labels[key] = value
            pod.metadata.resource_version += 1
            self._notify(MODIFIED, KIND_POD, pod)
            return pod.clone()

    def patch_node_annotations(self, name: str,
                               annotations: Mapping[str, Optional[str]]) -> Node:
        self._check_frozen("patch_node_annotations")
        self._maybe_api_error("patch_node_annotations")
        with self._lock:
            node = self._mutate_node(name)
            for key, value in annotations.items():
                if value is None:
                    node.metadata.annotations.pop(key, None)
                else:
                    node.metadata.annotations[key] = value
            self._notify(MODIFIED, KIND_NODE, node)
            return node.clone()

    def patch_node_meta(self, name: str,
                        labels: Optional[Mapping[str, Optional[str]]] = None,
                        annotations: Optional[Mapping[str, Optional[str]]]
                        = None) -> Node:
        """One atomic metadata merge patch (labels + annotations, one
        watch event) — the coalesced-write path. Consumes the SAME
        injected-error budgets as the split patches so fault schedules
        targeting patch_node_labels / patch_node_annotations still bite
        coalesced writers."""
        self._check_frozen("patch_node_meta")
        with self._lock:
            # one wire request, one count (the split ops' injected-error
            # budgets are still consumed below)
            self._api_call_counts["patch_node_meta"] = (
                self._api_call_counts.get("patch_node_meta", 0) + 1)
        if labels:
            self._consume_injected_error("patch_node_labels")
        if annotations:
            self._consume_injected_error("patch_node_annotations")
        with self._lock:
            node = self._mutate_node(name)
            for key, value in (labels or {}).items():
                if value is None:
                    node.metadata.labels.pop(key, None)
                else:
                    node.metadata.labels[key] = value
            for key, value in (annotations or {}).items():
                if value is None:
                    node.metadata.annotations.pop(key, None)
                else:
                    node.metadata.annotations[key] = value
            self._notify(MODIFIED, KIND_NODE, node)
            return node.clone()

    def set_node_unschedulable(self, name: str, unschedulable: bool) -> Node:
        self._check_frozen("set_node_unschedulable")
        self._maybe_api_error("set_node_unschedulable")
        with self._lock:
            node = self._mutate_node(name)
            node.spec.unschedulable = unschedulable
            self._notify(MODIFIED, KIND_NODE, node)
            return node.clone()

    def set_node_ready(self, name: str, ready: bool) -> Node:
        """Test helper: flip the node Ready condition."""
        self._check_frozen("set_node_ready")
        with self._lock:
            node = self._mutate_node(name)
            for cond in node.status.conditions:
                if cond.type == "Ready":
                    cond.status = "True" if ready else "False"
                    break
            else:
                from tpu_operator_libs.k8s.objects import NodeCondition
                node.status.conditions.append(
                    NodeCondition("Ready", "True" if ready else "False"))
            self._notify(MODIFIED, KIND_NODE, node)
            return node.clone()

    def flap_node_ready(self, name: str, down_at: float,
                        up_at: float) -> None:
        """Fault injection: schedule a NotReady flap — the node's Ready
        condition flips False at ``down_at`` and back True at ``up_at``
        (virtual seconds, fired by :meth:`step`)."""
        self._check_frozen("flap_node_ready")
        if up_at <= down_at:
            raise ValueError("up_at must be after down_at")
        self.schedule_at(down_at, lambda: self.set_node_ready(name, False))
        self.schedule_at(up_at, lambda: self.set_node_ready(name, True))

    def set_node_condition(self, name: str, condition_type: str,
                           status: str) -> Node:
        """Test helper: set an arbitrary node condition (the
        node-problem-detector seam the remediation wedge detectors
        watch, e.g. ``TpuHealthy=False``)."""
        self._check_frozen("set_node_condition")
        with self._lock:
            node = self._mutate_node(name)
            for cond in node.status.conditions:
                if cond.type == condition_type:
                    cond.status = status
                    break
            else:
                from tpu_operator_libs.k8s.objects import NodeCondition
                node.status.conditions.append(
                    NodeCondition(condition_type, status))
            self._notify(MODIFIED, KIND_NODE, node)
            return node.clone()

    # ------------------------------------------------------------------
    # K8sClient: pods
    # ------------------------------------------------------------------
    def list_pods(self, namespace: Optional[str] = None,
                  label_selector: str = "",
                  field_selector: str = "") -> list[Pod]:
        self._maybe_api_error("list_pods")
        label_match = parse_label_selector(label_selector)
        has_fields = bool((field_selector or "").strip())
        field_match = parse_field_selector(field_selector)
        node = exact_field_requirement(field_selector, "spec.nodeName")
        with self._lock:
            # truthiness matters: "spec.nodeName=" selects UNSCHEDULED
            # pods, which the index (bound pods only) cannot serve
            if node:
                # indexed path (narrows candidates; full matchers still
                # apply below, so semantics are unchanged)
                candidates = [self._pods[k] for k in sorted(
                    self._pods_by_node.get(node, ()))]
            else:
                candidates = list(self._pods.values())
            out = []
            for pod in candidates:
                ns = pod.metadata.namespace
                if namespace is not None and namespace != "" and ns != namespace:
                    continue
                if not label_match(pod.metadata.labels):
                    continue
                # field_map() allocates a fresh dict per pod; only pay
                # for it when a field selector is actually present
                if has_fields and not field_match(pod.field_map()):
                    continue
                out.append(pod.clone())
            return out

    def get_pod(self, namespace: str, name: str) -> Pod:
        self._maybe_api_error("get_pod")
        with self._lock:
            pod = self._pods.get((namespace, name))
            if pod is None:
                raise NotFoundError(f"pod {namespace}/{name} not found")
            return pod.clone()

    def set_pod_status(self, namespace: str, name: str,
                       phase: Optional[PodPhase] = None,
                       ready: Optional[bool] = None,
                       restart_count: Optional[int] = None) -> Pod:
        """Test helper: status subresource update (the builders in the
        reference suite force Running+Ready the same way,
        upgrade_suit_test.go:311-329)."""
        self._check_frozen("set_pod_status")
        with self._lock:
            pod = self._pods.get((namespace, name))
            if pod is None:
                raise NotFoundError(f"pod {namespace}/{name} not found")
            if phase is not None:
                pod.status.phase = phase
            if ready is not None or restart_count is not None:
                if not pod.status.container_statuses:
                    pod.status.container_statuses = [
                        ContainerStatus(name="main")]
            if ready is not None:
                for c in pod.status.container_statuses:
                    c.ready = ready
            if restart_count is not None:
                for c in pod.status.container_statuses:
                    c.restart_count = restart_count
            pod.metadata.resource_version += 1
            self._notify(MODIFIED, KIND_POD, pod)
            return pod.clone()

    def delete_pod(self, namespace: str, name: str) -> None:
        self._check_frozen("delete_pod")
        self._maybe_api_error("delete_pod")
        with self._lock:
            pod = self._pod_pop((namespace, name))
            if pod is None:
                raise NotFoundError(f"pod {namespace}/{name} not found")
            self._notify(DELETED, KIND_POD, pod)
            self._maybe_recreate_ds_pod(pod)

    def evict_pod(self, namespace: str, name: str) -> None:
        self._check_frozen("evict_pod")
        self._maybe_api_error("evict_pod")
        with self._lock:
            pod = self._pods.get((namespace, name))
            if pod is None:
                raise NotFoundError(f"pod {namespace}/{name} not found")
            for blocker in self._eviction_blockers:
                if blocker(pod):
                    raise EvictionBlockedError(
                        f"eviction of {namespace}/{name} blocked by "
                        f"disruption budget")
            self._check_pdbs(pod)
            self._pod_pop((namespace, name))
            self._notify(DELETED, KIND_POD, pod)
            self._maybe_recreate_ds_pod(pod)

    # ------------------------------------------------------------------
    # policy/v1 PodDisruptionBudgets (eviction-subresource enforcement)
    # ------------------------------------------------------------------
    def add_pod_disruption_budget(self, pdb: PodDisruptionBudget) \
            -> PodDisruptionBudget:
        """Install a PDB; subsequent evictions of selector-matching pods
        in its namespace are admitted only while disruptionsAllowed > 0,
        exactly the apiserver check that surfaces as HTTP 429."""
        self._check_frozen("add_pod_disruption_budget")
        with self._lock:
            self._pdbs[(pdb.metadata.namespace, pdb.metadata.name)] = \
                pdb.clone()
        return pdb

    def delete_pod_disruption_budget(self, namespace: str,
                                     name: str) -> None:
        self._check_frozen("delete_pod_disruption_budget")
        with self._lock:
            if self._pdbs.pop((namespace, name), None) is None:
                raise NotFoundError(
                    f"pdb {namespace}/{name} not found")

    @staticmethod
    def _scaled(value: object, total: int) -> int:
        """int, or "N%" rounded the way the apiserver rounds:
        minAvailable percents round UP (conservative toward keeping
        pods), which is also safe for maxUnavailable here because the
        caller subtracts."""
        if isinstance(value, str) and value.endswith("%"):
            import math

            return math.ceil(total * int(value[:-1]) / 100.0)
        return int(value)  # type: ignore[arg-type]

    def _check_pdbs(self, pod: Pod) -> None:
        """Raise EvictionBlockedError when any matching PDB has no
        disruptions left (lock held).

        Threshold base: when every matching pod belongs to one
        DaemonSet in this store, the DECLARED desired_number_scheduled
        (the disruption controller's expectedPods) — so percent
        budgets hold through a drain wave. Unowned/mixed pods fall
        back to the live matching count, the envtest-grade
        approximation for controllers this store does not model: there
        an evicted-but-not-yet-recreated pod shrinks the base, which
        admits evictions a real apiserver would block (see the inline
        note below)."""
        def matches(labels: Mapping[str, str], selector: dict) -> bool:
            # policy/v1 semantics: an EMPTY selector selects every pod
            # in the namespace (v1beta1's match-nothing was reversed)
            return all(labels.get(k) == v for k, v in selector.items())

        relevant = [pdb for pdb in self._pdbs.values()
                    if pdb.metadata.namespace == pod.metadata.namespace
                    and matches(pod.metadata.labels, pdb.selector)]
        if len(relevant) > 1:
            # the real apiserver refuses outright when a pod is covered
            # by more than one PDB
            raise EvictionBlockedError(
                f"pod {pod.metadata.namespace}/{pod.metadata.name} is "
                f"covered by more than one PodDisruptionBudget")
        for pdb in relevant:
            matching = [p for p in self._pods.values()
                        if p.metadata.namespace == pdb.metadata.namespace
                        and matches(p.metadata.labels, pdb.selector)]
            healthy = sum(1 for p in matching if p.is_ready())
            # Percent-threshold base: the real disruption controller
            # scales against the owning controller's DECLARED count
            # (expectedPods), not the live pod count. When every
            # matching pod belongs to one DaemonSet in this store, use
            # its desired_number_scheduled — so a budget like
            # minAvailable "N%" holds through a drain wave instead of
            # decaying with the evictions. Mixed/unowned pods fall
            # back to the live matching count (envtest-grade
            # approximation: no Deployment/ReplicaSet objects here;
            # the bases agree at steady state, but a sequential drain
            # against the decaying live base admits evictions — e.g.
            # integer max_unavailable re-derived per step — that a
            # real apiserver would block).
            expected = len(matching)
            owners = [p.controller_owner() for p in matching]
            owner_uids = {o.uid for o in owners if o is not None}
            if len(owner_uids) == 1 and None not in owners:
                ds_key = self._ds_key_by_owner_uid(next(iter(owner_uids)))
                if ds_key is not None:
                    # A DS whose status was never populated reports
                    # desired_number_scheduled=0; taking that at face
                    # value would make every percent threshold compute
                    # desired=0 and the budget silently never block.
                    # The declared base exists to be STRONGER than the
                    # decaying live count, so never let it be weaker.
                    declared = self._daemon_sets[
                        ds_key].status.desired_number_scheduled
                    expected = max(declared, len(matching))
            if pdb.min_available is not None:
                desired = self._scaled(pdb.min_available, expected)
            elif pdb.max_unavailable is not None:
                desired = expected - self._scaled(
                    pdb.max_unavailable, expected)
            else:
                continue
            # IfHealthyBudget (the policy/v1 default): evicting an
            # UNHEALTHY pod does not reduce currentHealthy and is
            # admitted while the budget holds
            delta = 1 if pod.is_ready() else 0
            if healthy - delta < desired:
                raise EvictionBlockedError(
                    f"eviction of {pod.metadata.namespace}/"
                    f"{pod.metadata.name} violates PodDisruptionBudget "
                    f"{pdb.metadata.name} (healthy={healthy}, "
                    f"required={desired})")

    def _ds_key_by_owner_uid(self, uid: str) -> Optional[tuple[str, str]]:
        """(namespace, name) of the DaemonSet with this UID, or None.
        Call with the lock held."""
        return next((k for k, ds in self._daemon_sets.items()
                     if ds.metadata.uid == uid), None)

    def _maybe_recreate_ds_pod(self, pod: Pod) -> None:
        """DS controller simulation: recreate a deleted DS-owned pod with the
        newest revision hash (must be called with the lock held)."""
        cfg = self._ds_controller
        if cfg is None or not cfg.enabled:
            return
        owner = pod.controller_owner()
        if owner is None or owner.kind != "DaemonSet":
            return
        ds_key = self._ds_key_by_owner_uid(owner.uid)
        if ds_key is None:
            return
        namespace, ds_name = ds_key
        node_name = pod.spec.node_name
        if node_name not in self._nodes:
            # The pod's node is ALREADY gone (a stranded pod deleted or
            # evicted during the GC window): no recreation, and no
            # accounting either — delete_node already decremented
            # desired for every pod present at node-deletion time. The
            # closure-side decrement below covers only the node
            # vanishing BETWEEN this scheduling and the recreate firing.
            return
        recreate_delay, ready_delay = cfg.recreate_delay, cfg.ready_delay
        if self._ds_delay_fn is not None:
            recreate_delay, ready_delay = self._ds_delay_fn(node_name)
        recreate_due = self._clock.now() + recreate_delay

        def recreate() -> None:
            with self._lock:
                ds = self._daemon_sets.get(ds_key)
                if ds is None:
                    return
                if node_name not in self._nodes:
                    # the node vanished while the pod was between
                    # deletion and recreation: the real DS controller
                    # drops its desired count for the gone node (the
                    # delete_node path handled pods that still existed;
                    # this closure owns the in-flight-recreation case)
                    ds.status.desired_number_scheduled = max(
                        0, ds.status.desired_number_scheduled - 1)
                    self._notify(MODIFIED, KIND_DAEMON_SET, ds)
                    return
                new_hash = self.latest_revision_hash(namespace, ds_name)
                labels = dict(ds.spec.selector)
                labels[POD_CONTROLLER_REVISION_HASH_LABEL] = new_hash
                pod_name = f"{ds_name}-{node_name}-{new_uid('p')}"
                new_pod = Pod(
                    metadata=ObjectMeta(
                        name=pod_name, namespace=namespace, labels=labels,
                        owner_references=[OwnerReference(
                            kind="DaemonSet", name=ds_name,
                            uid=ds.metadata.uid)]),
                    spec=PodSpec(node_name=node_name),
                    status=PodStatus(
                        phase=PodPhase.RUNNING,
                        container_statuses=[
                            ContainerStatus(name="runtime", ready=False)]))
                self._pod_put(new_pod)
                self._notify(ADDED, KIND_POD, new_pod)

                def make_ready(due: float) -> None:
                    with self._lock:
                        p = self._pods.get((namespace, pod_name))
                        if p is None:
                            return
                        gate = self._pod_ready_gate
                        if gate is not None and not gate(p):
                            # crash-looping: stay not-ready, accumulate
                            # restarts past the failure threshold, retry.
                            # The retry is anchored to this action's OWN
                            # due time (not clock.now()): step(until=T)
                            # with a frozen clock must terminate, and
                            # coarse step() calls must not skew timing.
                            for c in p.status.container_statuses:
                                c.ready = False
                                c.restart_count = max(c.restart_count, 11)
                            p.metadata.resource_version += 1
                            self._notify(MODIFIED, KIND_POD, p)
                            retry_due = due + 5.0
                            self.schedule_at(
                                retry_due, lambda: make_ready(retry_due))
                            return
                        for c in p.status.container_statuses:
                            c.ready = True
                            c.restart_count = 0
                        p.metadata.resource_version += 1
                        self._notify(MODIFIED, KIND_POD, p)

                # Anchor readiness to the recreation's due time, not to
                # whenever step() happened to execute the action, so coarse
                # step() calls don't inflate pod-ready latencies.
                ready_due = recreate_due + ready_delay
                self.schedule_at(ready_due, lambda: make_ready(ready_due))

        self.schedule_at(recreate_due, recreate)

    # ------------------------------------------------------------------
    # K8sClient: daemonsets & revisions
    # ------------------------------------------------------------------
    def list_daemon_sets(self, namespace: str,
                         label_selector: str = "") -> list[DaemonSet]:
        self._maybe_api_error("list_daemon_sets")
        match = parse_label_selector(label_selector)
        with self._lock:
            return [ds.clone()
                    for (ns, _), ds in self._daemon_sets.items()
                    if ns == namespace and match(ds.metadata.labels)]

    def list_controller_revisions(self, namespace: str,
                                  label_selector: str = "") -> list[ControllerRevision]:
        self._maybe_api_error("list_controller_revisions")
        match = parse_label_selector(label_selector)
        with self._lock:
            return [rev.clone()
                    for (ns, _), rev in self._revisions.items()
                    if ns == namespace and match(rev.metadata.labels)]

    # ------------------------------------------------------------------
    # v1 Events (recorder sink target)
    # ------------------------------------------------------------------
    def create_event(self, namespace: str, name: str,
                     event: object) -> None:
        """POST semantics: raises AlreadyExistsError on a name clash."""
        self._check_frozen("create_event")
        self._maybe_api_error("create_event")
        import copy

        with self._lock:
            key = (namespace, name)
            if key in self._cluster_events:
                raise AlreadyExistsError(
                    f"event {namespace}/{name} already exists")
            self._cluster_events[key] = copy.copy(event)

    def patch_event(self, namespace: str, name: str,
                    event: object) -> None:
        """PATCH semantics: refresh count/message/lastTimestamp of an
        existing Event; raises NotFoundError when absent."""
        self._check_frozen("patch_event")
        self._maybe_api_error("patch_event")
        with self._lock:
            stored = self._cluster_events.get((namespace, name))
            if stored is None:
                raise NotFoundError(f"event {namespace}/{name} not found")
            stored.count = event.count
            stored.message = event.message
            stored.last_seen = event.last_seen

    def upsert_event(self, namespace: str, name: str,
                     event: object) -> None:
        try:
            self.create_event(namespace, name, event)
        except AlreadyExistsError:
            self.patch_event(namespace, name, event)

    def list_events(self, namespace: str) -> list:
        """Test helper: recorded cluster Events in the namespace."""
        import copy

        with self._lock:
            return [copy.copy(e) for (ns, _), e in
                    sorted(self._cluster_events.items()) if ns == namespace]

    # ------------------------------------------------------------------
    # coordination.k8s.io Leases (leader-election lock objects)
    # ------------------------------------------------------------------
    def get_lease(self, namespace: str, name: str) -> Lease:
        with self._lock:
            lease = self._leases.get((namespace, name))
            if lease is None:
                raise NotFoundError(f"lease {namespace}/{name} not found")
            return lease.clone()

    def create_lease(self, lease: Lease) -> Lease:
        self._check_frozen("create_lease")
        key = (lease.metadata.namespace, lease.metadata.name)
        with self._lock:
            if key in self._leases:
                raise AlreadyExistsError(
                    f"lease {key[0]}/{key[1]} already exists")
            stored = lease.clone()
            stored.metadata.resource_version = 1
            self._leases[key] = stored
            return stored.clone()

    def steal_lease(self, namespace: str, name: str, holder: str,
                    lease_duration_seconds: int = 15) -> Lease:
        """Fault injection: overwrite the lease holder server-side,
        bypassing the optimistic-concurrency check — what the current
        leader observes when another contender legitimately won the lock
        during a partition it could not see. Creates the lease when
        absent. The victim's next renew hits a ConflictError (its
        resourceVersion is stale) and it steps down."""
        self._check_frozen("steal_lease")
        with self._lock:
            stored = self._leases.get((namespace, name))
            now = self._clock.now()
            if stored is None:
                stored = Lease(
                    metadata=ObjectMeta(name=name, namespace=namespace),
                    holder_identity=holder,
                    lease_duration_seconds=lease_duration_seconds,
                    acquire_time=now, renew_time=now, lease_transitions=0)
                stored.metadata.resource_version = 1
                self._leases[(namespace, name)] = stored
            else:
                stored.holder_identity = holder
                stored.lease_duration_seconds = lease_duration_seconds
                stored.acquire_time = now
                stored.renew_time = now
                stored.lease_transitions += 1
                stored.metadata.resource_version += 1
            return stored.clone()

    def update_lease(self, lease: Lease) -> Lease:
        """Replace with optimistic concurrency: the caller's
        resourceVersion must match the stored one or ConflictError is
        raised — exactly the apiserver contract leader election's
        acquire race depends on."""
        self._check_frozen("update_lease")
        key = (lease.metadata.namespace, lease.metadata.name)
        with self._lock:
            stored = self._leases.get(key)
            if stored is None:
                raise NotFoundError(f"lease {key[0]}/{key[1]} not found")
            if lease.metadata.resource_version \
                    != stored.metadata.resource_version:
                raise ConflictError(
                    f"lease {key[0]}/{key[1]}: resourceVersion "
                    f"{lease.metadata.resource_version} != "
                    f"{stored.metadata.resource_version}")
            updated = lease.clone()
            updated.metadata.resource_version = (
                stored.metadata.resource_version + 1)
            self._leases[key] = updated
            return updated.clone()
