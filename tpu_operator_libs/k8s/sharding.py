"""Sharded HA control plane: per-shard Leases, fencing and budget shares.

One operator process holding one Lease tops out well below TPU-supercomputer
fleet sizes, and killing it freezes every subsystem until restart. This
module generalizes :mod:`tpu_operator_libs.k8s.leaderelection` from one
global lock to a **consistent-hash ring of shard locks**:

- :class:`ShardRing` maps every node to one of ``num_shards`` shards by a
  stable hash. Nodes that belong to an ICI slice hash by their *slice*
  (nodepool label), so a slice is never split across owners and
  slice-atomic planning keeps working under sharding.
- :class:`ShardElector` is one replica's contender: it claims a **member
  slot** Lease (the replica registry — membership is discoverable with R
  GETs, no LIST needed) plus the per-shard Leases the deterministic
  slot-to-shard assignment prefers it for. When a peer's slot Lease
  expires, the survivors recompute the assignment and **adopt the orphaned
  shards** the moment their Leases expire — mid-rollout, from durable
  cluster state alone. A late-joining replica claims a free slot, the
  incumbents observe the membership change and *release* the shards the
  new assignment hands over.
- :meth:`ShardElector.fence` is the split-brain gate: immediately before
  every durable write the state provider asks the elector to prove — by
  local belief AND a server-side Lease read — that this replica still owns
  the target node's shard. A deposed replica's queued transition writes
  raise :class:`ShardFencedError` (a hard error the per-node transient
  isolation must NOT swallow) instead of landing outside its partition.
- :func:`split_budget` + :class:`ShardBudgetLedger` turn the global
  maxUnavailable budget into **durable budget shares** recorded on the
  runtime DaemonSet annotation (the RolloutGuard bake-stamp idiom): each
  shard's share lives under its own annotation key, so concurrent owners
  never clobber each other's claims (RFC 7386 merge of distinct keys), and
  the spend rule — decreases take effect immediately, increases only one
  pass after they are durably recorded and read back — means two shards
  can never jointly overdraw the fleet budget, even across a takeover.

Everything durable lives on the cluster (slot Leases, shard Leases, the
budget-share annotations); the elector object carries only observations
and counters, so replica crash–restart loses nothing the successor cannot
re-derive.
"""

from __future__ import annotations

import hashlib
import logging
from dataclasses import dataclass
from typing import Callable, Optional

from tpu_operator_libs.k8s.leaderelection import (
    LeaderElectionConfig,
    LeaderElector,
    LeaseLockClient,
)
from tpu_operator_libs.util import Clock

logger = logging.getLogger(__name__)

#: Sharded deployments default to a longer lease than single-lock leader
#: election: a takeover re-runs a whole partition's reconcile, so
#: flapping ownership on a transient renewal hiccup costs more than a
#: few extra seconds of orphan time.
DEFAULT_SHARD_LEASE_DURATION = 30.0
DEFAULT_SHARD_RENEW_DEADLINE = 20.0


class ShardFencedError(RuntimeError):
    """A durable write was attempted for a node outside the replica's
    owned partition (or after its shard lease was lost/stolen).

    Deliberately NOT an ApiServerError/ConflictError/NotFoundError: the
    state machines' per-node transient isolation must not swallow it —
    a fenced replica must abort its pass and re-derive ownership, the
    same way an operator crash aborts a pass.
    """


class ShardRing:
    """Stable node-to-shard mapping.

    Hashing is keyed by the node's *slice* (nodepool label) when one is
    present, else by the node name — so multi-host ICI slices always land
    whole on one shard and the slice planner's atomicity survives
    sharding. The map depends only on ``num_shards`` and the key, never
    on replica membership: replicas claim *shards*, nodes never migrate
    between shards when replicas come and go.
    """

    def __init__(self, num_shards: int) -> None:
        if num_shards < 1:
            raise ValueError("num_shards must be >= 1")
        self.num_shards = num_shards
        # Hash memo: shard_for sits on the watch-ingest hot path of the
        # partition-filtered read client (one lookup per pod event) and
        # in the per-pass census maintenance — at 100k nodes the sha256
        # per call dominates. Keys are hash keys (pool or node name),
        # whose population is bounded by the fleet size. dict get/set
        # are atomic in CPython, so concurrent informer threads at
        # worst duplicate a computation.
        self._memo: dict[str, int] = {}

    def shard_for(self, node_name: str, pool: str = "") -> int:
        key = pool or node_name
        shard = self._memo.get(key)
        if shard is None:
            digest = hashlib.sha256(key.encode("utf-8")).digest()
            shard = int.from_bytes(digest[:8], "big") % self.num_shards
            self._memo[key] = shard
        return shard


def split_budget(total_budget: int,
                 shard_counts: "dict") -> "dict":
    """Deterministically split ``total_budget`` across partition keys
    proportionally to their node counts (largest-remainder method, ties
    broken by key order). Every computer of the split derives the
    identical answer from the same census, and the shares sum to
    exactly ``total_budget`` — the arithmetic half of the
    never-jointly-overdraw guarantee (the durable ledger is the
    crash/skew half). Keys are shard ids for the in-cluster sharded
    control plane and region names for the federation layer — any
    sortable key type works."""
    shards = sorted(shard_counts)
    total_nodes = sum(shard_counts[s] for s in shards)
    if total_nodes <= 0 or total_budget <= 0:
        return {s: 0 for s in shards}
    quotas = {s: total_budget * shard_counts[s] / total_nodes
              for s in shards}
    shares = {s: int(quotas[s]) for s in shards}
    remainder = total_budget - sum(shares.values())
    by_fraction = sorted(shards, key=lambda s: (-(quotas[s] - shares[s]), s))
    for s in by_fraction[:remainder]:
        shares[s] += 1
    return shares


def ledger_spend_cap(owned: "frozenset | set", entitled: "dict",
                     recorded: "dict", global_budget: int) -> int:
    """The durable share ledger's spend rule, factored once for every
    layer that partitions one global disruption budget (the in-cluster
    shard ledger and the federation's per-region ledger):

    - **decrease-immediate**: an owner spends ``min(entitlement,
      recorded share)`` — a shrunk entitlement bites this pass, before
      it is ever stamped;
    - **increase-next-pass**: a grown entitlement only counts once it
      is durably recorded AND read back, so until then the owner keeps
      spending against the old stamp;
    - **global clamp**: everyone else's recorded claim (their
      entitlement while unrecorded) must still fit next to ours — two
      owners acting on skewed censuses can never jointly exceed
      ``global_budget``, even across takeovers.
    """
    cap = sum(min(entitled[key], recorded.get(key, entitled[key]))
              for key in owned)
    others = sum(recorded.get(key, entitled[key])
                 for key in entitled if key not in owned)
    return max(0, min(cap, global_budget - others))


class ShardBudgetLedger:
    """Encode/decode the durable per-shard budget shares on the runtime
    DaemonSet's annotations.

    One annotation key PER SHARD (``...upgrade.budget-share.<shard>``):
    concurrent owners patch disjoint keys, which an RFC 7386 merge patch
    composes without clobbering — the same reason the RolloutGuard's
    quarantine/bake stamps are safe to write from any incarnation.
    """

    def __init__(self, keys: "object") -> None:
        # UpgradeKeys-shaped: domain + driver build the key family.
        self._prefix = (f"{keys.domain}/{keys.driver}"
                        f"-upgrade.budget-share.")

    def annotation_key(self, shard: int) -> str:
        return f"{self._prefix}{shard}"

    def shares_from(self,
                    annotations: "dict[str, str]") -> "dict[int, int]":
        """All recorded shares found in a DaemonSet's annotations."""
        out: dict[int, int] = {}
        for key, value in annotations.items():
            if not key.startswith(self._prefix):
                continue
            try:
                out[int(key[len(self._prefix):])] = int(value)
            except ValueError:
                logger.warning("ignoring malformed budget share %r=%r",
                               key, value)
        return out


@dataclass
class ShardElectionConfig:
    """Knobs of one replica's sharded election.

    ``replicas`` is the expected replica count (the number of member
    slots contended for); ``num_shards`` the ring size. A replica may
    own MORE than ``num_shards // replicas`` shards while peers are
    down — orphan adoption is what keeps a dead peer's partition
    moving — and hands the excess back when the peer (or a fresh
    replacement) claims a slot again.
    """

    namespace: str
    identity: str
    num_shards: int
    replicas: int = 2
    lease_prefix: str = "tpu-operator"
    lease_duration: float = DEFAULT_SHARD_LEASE_DURATION
    renew_deadline: float = DEFAULT_SHARD_RENEW_DEADLINE
    retry_period: float = 2.0
    #: Fraction of retry_period added as per-replica deterministic
    #: jitter so N replicas' renewals do not herd the apiserver.
    renew_jitter: float = 0.2

    def __post_init__(self) -> None:
        if self.num_shards < 1:
            raise ValueError("num_shards must be >= 1")
        if self.replicas < 1:
            raise ValueError("replicas must be >= 1")
        if not self.identity:
            raise ValueError("identity must be non-empty")

    @classmethod
    def from_policy(cls, spec: "object", namespace: str, identity: str,
                    lease_prefix: str = "tpu-operator",
                    ) -> "ShardElectionConfig":
        """Build the election config from a
        :class:`~tpu_operator_libs.api.upgrade_policy.ShardingPolicySpec`
        (the CRD surface): ring size and replica count come from the
        policy; renew deadline and retry period derive from the lease
        duration with the client-go 15:10:2 proportions."""
        duration = float(spec.lease_duration_seconds)
        return cls(namespace=namespace, identity=identity,
                   num_shards=spec.num_shards, replicas=spec.replicas,
                   lease_prefix=lease_prefix,
                   lease_duration=duration,
                   renew_deadline=duration * 2.0 / 3.0,
                   retry_period=max(0.5, duration * 2.0 / 15.0))

    def slot_lease_name(self, slot: int) -> str:
        return f"{self.lease_prefix}-member-{slot:02d}"

    def shard_lease_name(self, shard: int) -> str:
        return f"{self.lease_prefix}-shard-{shard:02d}"


@dataclass
class _SlotObservation:
    """Local observation of one member-slot Lease (client-go expiry
    semantics: judged from when WE saw the record change, so wall-clock
    skew between replicas never fabricates membership)."""

    holder: str = ""
    resource_version: str = ""
    duration: float = DEFAULT_SHARD_LEASE_DURATION
    observed_at: float = 0.0


class ShardElector:
    """One replica of the sharded control plane.

    Drive it with :meth:`tick` (non-blocking, clock-injectable — the
    chaos soaks and benches interleave replicas deterministically) or
    :meth:`run_step` + a sleep loop for production. The elector exposes
    the ownership surface the state machines consume:

    - :meth:`owns` / :attr:`owned_shards` — the ownership filter for
      ``build_state``;
    - :meth:`fence` — the write-time split-brain gate;
    - :attr:`ring` — the node-to-shard map (shared by every replica).
    """

    def __init__(self, client: LeaseLockClient,
                 config: ShardElectionConfig,
                 clock: Optional[Clock] = None) -> None:
        self._client = client
        self.config = config
        self._clock = clock or Clock()
        self.ring = ShardRing(config.num_shards)
        self.identity = config.identity
        # one LeaderElector per shard lock; per-slot electors are built
        # lazily for the slot this replica actually contends for
        self._shard_electors = {
            shard: self._elector(config.shard_lease_name(shard))
            for shard in range(config.num_shards)}
        self._slot_electors = {
            slot: self._elector(config.slot_lease_name(slot))
            for slot in range(config.replicas)}
        self._slot: Optional[int] = None
        # observations of EVERY slot lease (membership registry)
        self._slot_obs: dict[int, _SlotObservation] = {}
        # lifetime counters (metrics.observe_shard_election)
        self.acquires_total = 0
        self.losses_total = 0
        #: Shards adopted from another (expired) holder's partition.
        self.takeovers_total = 0
        #: Shards released to hand ownership to a preferred peer.
        self.handovers_total = 0
        #: fence() rejections (split-brain writes refused).
        self.fence_rejections_total = 0

    def _elector(self, name: str) -> LeaderElector:
        config = self.config
        return LeaderElector(
            self._client,
            LeaderElectionConfig(
                namespace=config.namespace, name=name,
                identity=config.identity,
                lease_duration=config.lease_duration,
                renew_deadline=config.renew_deadline,
                retry_period=config.retry_period,
                renew_jitter=config.renew_jitter),
            clock=self._clock)

    # -- inspection -------------------------------------------------------
    @property
    def num_shards(self) -> int:
        return self.config.num_shards

    @property
    def slot(self) -> Optional[int]:
        """The member slot this replica holds (None while unslotted)."""
        return self._slot

    def owned_shards(self) -> frozenset[int]:
        return frozenset(
            shard for shard, elector in self._shard_electors.items()
            if elector.is_leader)

    def owns(self, node_name: str, pool: str = "") -> bool:
        return self.ring.shard_for(node_name, pool) in self.owned_shards()

    def live_members(self) -> "dict[int, str]":
        """slot -> holder identity for every UNEXPIRED member slot, by
        this replica's own observations."""
        now = self._clock.now()
        live: dict[int, str] = {}
        for slot, obs in self._slot_obs.items():
            if obs.holder and obs.observed_at + obs.duration > now:
                live[slot] = obs.holder
        return live

    def preferred_assignment(self) -> "dict[int, int]":
        """shard -> preferred member SLOT, derived deterministically
        from the live membership (round-robin over sorted live slots).
        Every replica with the same observations computes the same
        assignment — no coordination message exists anywhere."""
        live = sorted(self.live_members())
        if not live:
            return {}
        return {shard: live[shard % len(live)]
                for shard in range(self.config.num_shards)}

    # -- the decision step -------------------------------------------------
    def tick(self) -> frozenset[int]:
        """One non-blocking election round: claim/renew the member slot,
        refresh membership observations, then renew / adopt / release
        shard Leases per the preferred assignment. Returns the shards
        owned after the round."""
        self._tick_slot()
        self._observe_slots()
        assignment = self.preferred_assignment()
        live_idents = set(self.live_members().values())
        for shard, elector in self._shard_electors.items():
            preferred = assignment.get(shard)
            if elector.is_leader:
                if preferred is not None and preferred != self._slot:
                    # membership changed (a peer joined or we lost our
                    # slot): hand the shard over instead of making the
                    # peer wait out our lease
                    if elector.release():
                        self.handovers_total += 1
                        elector.step_down()
                        self.losses_total += 1
                        logger.info(
                            "shard elector %s: released shard %d to "
                            "slot %s", self.identity, shard, preferred)
                    continue
                before = elector.is_leader
                elector.try_acquire_or_renew()
                if before and not elector.is_leader:
                    self.losses_total += 1  # stolen/expired under us
                continue
            if preferred != self._slot or self._slot is None:
                # not ours to claim — but keep the expiry clock warm:
                # if membership changes and the assignment hands us
                # this shard, a cold first observation would cost an
                # extra full lease duration before takeover
                elector.observe()
                continue
            previous = elector.observed_leader
            if elector.try_acquire_or_renew():
                self.acquires_total += 1
                if previous and previous != self.identity \
                        and previous not in live_idents:
                    # the lease's last holder is no longer a live
                    # member: an orphaned-shard takeover, not a first
                    # claim or a handed-over lease from a live peer
                    self.takeovers_total += 1
                    logger.info(
                        "shard elector %s: took over orphaned shard %d "
                        "from %s", self.identity, shard, previous)
        return self.owned_shards()

    def _tick_slot(self) -> None:
        if self._slot is not None:
            elector = self._slot_electors[self._slot]
            elector.try_acquire_or_renew()
            if not elector.is_leader:
                logger.warning("shard elector %s: lost member slot %d",
                               self.identity, self._slot)
                self._slot = None
        if self._slot is None:
            for slot, elector in sorted(self._slot_electors.items()):
                if elector.try_acquire_or_renew():
                    self._slot = slot
                    logger.info("shard elector %s: claimed member "
                                "slot %d", self.identity, slot)
                    break

    def _observe_slots(self) -> None:
        from tpu_operator_libs.k8s.client import NotFoundError

        now = self._clock.now()
        for slot in range(self.config.replicas):
            try:
                lease = self._client.get_lease(
                    self.config.namespace,
                    self.config.slot_lease_name(slot))
            except NotFoundError:
                self._slot_obs[slot] = _SlotObservation(observed_at=now)
                continue
            except Exception:  # noqa: BLE001 — transient apiserver error
                # keep the previous observation; expiry math will age it
                # out if the outage persists past the lease duration
                logger.warning("shard elector %s: slot %d lease read "
                               "failed", self.identity, slot,
                               exc_info=True)
                continue
            obs = self._slot_obs.get(slot)
            if (obs is None or obs.resource_version
                    != lease.metadata.resource_version):
                self._slot_obs[slot] = _SlotObservation(
                    holder=lease.holder_identity,
                    resource_version=lease.metadata.resource_version,
                    duration=(lease.lease_duration_seconds
                              or self.config.lease_duration),
                    observed_at=now)

    # -- the write-time gate ----------------------------------------------
    def fence(self, node_name: str, pool: str = "") -> None:
        """Refuse a durable write for a node this replica does not own.

        Two checks, both mandatory: the local belief (cheap, catches a
        pass iterating a stale snapshot) and a server-side Lease read
        (catches a mid-pass steal/expiry the next tick has not observed
        yet — the split-brain seam). Raises :class:`ShardFencedError`;
        a transient apiserver error on the Lease read propagates as-is,
        so the per-node transient isolation defers the node instead of
        letting an unverified write through (fail closed).
        """
        shard = self.ring.shard_for(node_name, pool)
        elector = self._shard_electors[shard]
        if not elector.is_leader:
            self.fence_rejections_total += 1
            raise ShardFencedError(
                f"replica {self.identity} does not own shard {shard} "
                f"(node {node_name}); write refused")
        from tpu_operator_libs.k8s.client import NotFoundError

        try:
            lease = self._client.get_lease(
                self.config.namespace,
                self.config.shard_lease_name(shard))
        except NotFoundError:
            lease = None
        if lease is None or lease.holder_identity != self.identity:
            # deposed mid-pass: step down locally so every queued write
            # of this pass is rejected too, not just this one
            elector.step_down()
            self.losses_total += 1
            self.fence_rejections_total += 1
            holder = lease.holder_identity if lease else "<gone>"
            raise ShardFencedError(
                f"replica {self.identity} was deposed from shard "
                f"{shard} (lease now held by {holder!r}); write for "
                f"node {node_name} refused")

    # -- lifecycle ---------------------------------------------------------
    def release_all(self) -> None:
        """Clean shutdown: release every held shard Lease and the member
        slot, so successors take over immediately instead of waiting
        out the lease durations."""
        for elector in self._shard_electors.values():
            if elector.is_leader:
                elector.release()
                elector.step_down()
        if self._slot is not None:
            elector = self._slot_electors[self._slot]
            if elector.is_leader:
                elector.release()
                elector.step_down()
            self._slot = None

    def run_step(self) -> float:
        """One production-driver step: tick, then return how long the
        caller should sleep before the next tick (retry period plus the
        per-replica deterministic jitter)."""
        self.tick()
        return self.config.retry_period * (
            1.0 + self.config.renew_jitter
            * self._jitter_fraction())

    def _jitter_fraction(self) -> float:
        # deterministic per identity: stable spacing between replicas
        # without shared state (herding is the enemy, not randomness)
        digest = hashlib.sha256(self.identity.encode()).digest()
        return digest[0] / 255.0


@dataclass
class StaticShardView:
    """Fixed ownership for tests and single-process benches: the
    ownership/fence surface of :class:`ShardElector` without Leases.
    ``owned`` is the set of shards this view claims; fencing rejects
    writes outside it (no server round-trip — there is no server-side
    contention in a static split)."""

    ring: ShardRing
    owned: frozenset = frozenset()
    identity: str = "static"
    fence_rejections_total: int = 0
    takeovers_total: int = 0
    acquires_total: int = 0
    losses_total: int = 0
    handovers_total: int = 0
    slot: Optional[int] = None

    @property
    def num_shards(self) -> int:
        return self.ring.num_shards

    def owned_shards(self) -> frozenset:
        return frozenset(self.owned)

    def owns(self, node_name: str, pool: str = "") -> bool:
        return self.ring.shard_for(node_name, pool) in self.owned

    def fence(self, node_name: str, pool: str = "") -> None:
        if not self.owns(node_name, pool):
            self.fence_rejections_total += 1
            raise ShardFencedError(
                f"static view {self.identity} does not own node "
                f"{node_name}; write refused")

    def tick(self) -> frozenset:
        return self.owned_shards()

    def release_all(self) -> None:
        pass

    def live_members(self) -> "dict[int, str]":
        return {0: self.identity}


class ShardLabelStamper:
    """Stamp ring-derived shard ids onto nodes and runtime pods so a
    replica's LIST/WATCH can be **server-side filtered** to its owned
    partition (``CachedReadClient(shard_selector_fn=...)``).

    The stamp value is pure ring output — ``shard_for(name, pool)`` —
    so it is idempotent and concurrent-owner safe (any number of
    stampers write the identical value; merge patches compose), and it
    NEVER changes on shard handover: ownership moves are a watcher-side
    selector change only, which is what makes the crash ordering
    simple — re-evaluate the selector (``refresh_partition``) after
    ownership settles, and the stamps were already correct.

    Two stamping surfaces:

    - :meth:`install_admission` registers FakeCluster mutating-admission
      hooks, so every node/pod — including DS-controller recreations
      mid-upgrade — is **born** stamped (the mutating-webhook idiom a
      real deployment would use; a pod recreated without its stamp
      would be invisible to its owner's filtered watch).
    - :meth:`stamp_existing` bootstraps a brownfield cluster: one LIST
      of nodes + pods, patching only objects whose stamp is missing or
      wrong. Run it BEFORE any replica narrows its watch to a selector
      (the crash-ordered admission rule: stamp first, filter second).
    """

    def __init__(self, ring: ShardRing, keys: "Optional[object]" = None,
                 ) -> None:
        from tpu_operator_libs.consts import (
            GKE_NODEPOOL_LABEL,
            UpgradeKeys,
        )

        self.ring = ring
        self.keys = keys or UpgradeKeys()
        self.label_key = self.keys.shard_label
        self._pool_label = GKE_NODEPOOL_LABEL
        #: Objects patched by stamp_existing (bootstrap evidence).
        self.stamped_nodes_total = 0
        self.stamped_pods_total = 0

    # -- values & selectors ----------------------------------------------
    def value_for(self, node_name: str, pool: str = "") -> str:
        return str(self.ring.shard_for(node_name, pool))

    def selector(self, owned: "frozenset | set | list") -> str:
        """Label selector matching exactly the owned shards' objects.
        An empty ownership set yields a selector that matches nothing
        (a replica between elections watches an empty partition, not
        the fleet)."""
        shards = sorted(int(s) for s in owned)
        if not shards:
            return f"{self.label_key} in (none)"
        values = ",".join(str(s) for s in shards)
        return f"{self.label_key} in ({values})"

    # -- in-place stamping (admission hooks) ------------------------------
    def stamp_node(self, node: "object") -> None:
        labels = node.metadata.labels
        pool = labels.get(self._pool_label, "")
        labels[self.label_key] = self.value_for(node.metadata.name, pool)

    def stamp_pod(self, pod: "object",
                  pool_of: "Callable[[str], str]") -> None:
        """Stamp one pod from its bound node's identity. ``pool_of``
        maps node name -> nodepool label value (the ring's slice key).
        Unbound pods are left unstamped — they are stamped by the
        UPDATE admission pass when the binding lands."""
        node_name = pod.spec.node_name
        if not node_name:
            return
        pod.metadata.labels[self.label_key] = self.value_for(
            node_name, pool_of(node_name))

    def install_admission(self, cluster: "object") -> None:
        """Register mutating-admission hooks on a FakeCluster: every
        node and (bound) pod enters the store already stamped."""
        from tpu_operator_libs.k8s.client import NotFoundError
        from tpu_operator_libs.k8s.watch import KIND_NODE, KIND_POD

        def pool_of(node_name: str) -> str:
            try:
                node = cluster.get_node(node_name)
            except NotFoundError:
                return ""
            return node.metadata.labels.get(self._pool_label, "")

        cluster.add_admission_mutator(KIND_NODE, self.stamp_node)
        cluster.add_admission_mutator(
            KIND_POD, lambda pod: self.stamp_pod(pod, pool_of))

    # -- bootstrap stamping (brownfield clusters) --------------------------
    def stamp_existing(self, client: "object", namespace: str,
                       label_selector: str = "") -> int:
        """One-shot bootstrap: LIST nodes + pods and patch every object
        whose shard stamp is missing or wrong. Idempotent (second run
        patches nothing). Returns the number of objects patched."""
        patched = 0
        pools: dict[str, str] = {}
        for node in client.list_nodes():
            name = node.metadata.name
            pool = node.metadata.labels.get(self._pool_label, "")
            pools[name] = pool
            want = self.value_for(name, pool)
            if node.metadata.labels.get(self.label_key) != want:
                client.patch_node_labels(name, {self.label_key: want})
                self.stamped_nodes_total += 1
                patched += 1
        for pod in client.list_pods(namespace=namespace,
                                    label_selector=label_selector):
            node_name = pod.spec.node_name
            if not node_name:
                continue
            want = self.value_for(node_name, pools.get(node_name, ""))
            if pod.metadata.labels.get(self.label_key) != want:
                client.patch_pod_labels(
                    pod.metadata.namespace, pod.metadata.name,
                    {self.label_key: want})
                self.stamped_pods_total += 1
                patched += 1
        return patched
