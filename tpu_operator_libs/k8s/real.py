"""Live-cluster adapter backed by the official ``kubernetes`` Python client.

Fills the role of the reference's client-go/clientset pair
(upgrade_state.go:127-132) for real GKE clusters. Import-gated: the
``kubernetes`` package is an optional dependency — everything else in this
library (tests, simulation, bench) runs without it, and constructing
:class:`RealCluster` without the package raises a clear error.

Mapping to API calls:

- nodes: ``CoreV1Api.read_node`` / ``list_node`` / ``patch_node``
  (merge-patch with ``None`` values deleting keys, the same semantics the
  reference's raw patches rely on, node_upgrade_state_provider.go:147-151)
- pods: ``list_pod_for_all_namespaces`` / ``list_namespaced_pod`` with
  label+field selectors; ``delete_namespaced_pod``;
  ``create_namespaced_pod_eviction`` for the eviction subresource
- daemonsets/revisions: ``AppsV1Api.list_namespaced_daemon_set`` /
  ``list_namespaced_controller_revision``
"""

from __future__ import annotations

import functools
from typing import Mapping, Optional

from tpu_operator_libs.k8s.client import (
    AlreadyExistsError,
    ApiServerError,
    ConflictError,
    EvictionBlockedError,
    K8sClient,
    NotFoundError,
)
from tpu_operator_libs.k8s.objects import (
    ContainerStatus,
    ControllerRevision,
    DaemonSet,
    DaemonSetSpec,
    DaemonSetStatus,
    Lease,
    Node,
    NodeCondition,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    OwnerReference,
    Pod,
    PodPhase,
    PodSpec,
    PodStatus,
    Volume,
)


def _require_kubernetes():
    try:
        import kubernetes  # noqa: F401
        from kubernetes import client as k8s_client
        return k8s_client
    except ImportError as exc:  # pragma: no cover - exercised via test stub
        raise ImportError(
            "the 'kubernetes' package is required for RealCluster; "
            "install it in the operator image (everything else in "
            "tpu_operator_libs works without it)") from exc


def _meta_from(obj) -> ObjectMeta:
    meta = obj.metadata
    owners = []
    for ref in (getattr(meta, "owner_references", None) or []):
        owners.append(OwnerReference(
            kind=ref.kind, name=ref.name, uid=ref.uid,
            controller=bool(getattr(ref, "controller", False))))
    ts = getattr(meta, "deletion_timestamp", None)
    return ObjectMeta(
        name=meta.name,
        namespace=meta.namespace or "",
        uid=meta.uid or "",
        labels=dict(meta.labels or {}),
        annotations=dict(meta.annotations or {}),
        owner_references=owners,
        deletion_timestamp=ts.timestamp() if ts is not None else None)


def _node_from(obj) -> Node:
    conditions = [NodeCondition(type=c.type, status=c.status)
                  for c in (obj.status.conditions or [])]
    return Node(
        metadata=_meta_from(obj),
        spec=NodeSpec(unschedulable=bool(obj.spec.unschedulable)),
        status=NodeStatus(conditions=conditions
                          or [NodeCondition("Ready", "True")]))


def _container_statuses(statuses) -> list[ContainerStatus]:
    return [ContainerStatus(name=s.name, ready=bool(s.ready),
                            restart_count=int(s.restart_count or 0))
            for s in (statuses or [])]


def _pod_from(obj) -> Pod:
    volumes = []
    for v in (obj.spec.volumes or []):
        volumes.append(Volume(
            name=v.name, empty_dir=getattr(v, "empty_dir", None) is not None))
    phase = obj.status.phase or "Pending"
    return Pod(
        metadata=_meta_from(obj),
        spec=PodSpec(node_name=obj.spec.node_name or "", volumes=volumes),
        status=PodStatus(
            phase=PodPhase(phase),
            container_statuses=_container_statuses(
                obj.status.container_statuses),
            init_container_statuses=_container_statuses(
                obj.status.init_container_statuses)))


def _daemon_set_from(obj) -> DaemonSet:
    selector = dict((obj.spec.selector.match_labels or {})
                    if obj.spec.selector else {})
    return DaemonSet(
        metadata=_meta_from(obj),
        spec=DaemonSetSpec(selector=selector),
        status=DaemonSetStatus(
            desired_number_scheduled=int(
                obj.status.desired_number_scheduled or 0)))


def _revision_from(obj) -> ControllerRevision:
    return ControllerRevision(metadata=_meta_from(obj),
                              revision=int(obj.revision))


class _ThrottledApi:
    """Charge one rate-limiter token per API method invocation.

    Wraps a kubernetes API object (CoreV1Api etc.) at the transport
    level, which is where client-go's rest.Config limiter lives: every
    HTTP request — including each page of a chunked LIST and each watch
    stream (re-)establishment — acquires a token, not just each
    top-level K8sClient call."""

    def __init__(self, api: object, limiter: object) -> None:
        self._api = api
        self._limiter = limiter

    def __getattr__(self, name: str) -> object:
        attr = getattr(self._api, name)
        if not callable(attr):
            return attr
        limiter = self._limiter

        @functools.wraps(attr)
        def call(*args, **kwargs):
            limiter.wait()
            return attr(*args, **kwargs)

        # The watch plumbing introspects the bound method it is handed:
        # kubernetes.watch.Watch.stream reads __doc__ (return-type
        # discovery) and __self__ (api_client access) — wraps() covers
        # the former, __self__ must be restored by hand or every watch
        # breaks the moment a limiter is mounted.
        call.__self__ = getattr(attr, "__self__", self._api)  # type: ignore[attr-defined]
        return call


def _throttled(api: object, limiter: Optional[object]) -> object:
    return api if limiter is None else _ThrottledApi(api, limiter)


class RealCluster(K8sClient):
    """K8sClient against a live API server."""

    def __init__(self, api_client: Optional[object] = None,
                 list_page_size: int = 500,
                 rate_limiter: Optional[object] = None) -> None:
        # api_client: an optional kubernetes.client.ApiClient;
        # typed as object because the kubernetes package is an
        # import-gated optional dependency.
        # rate_limiter: an optional
        # tpu_operator_libs.k8s.flowcontrol.TokenBucketRateLimiter.
        # It sits where client-go's rest.Config limiter sits — below
        # everything, charging one token per HTTP request — so paged
        # LIST chunks and watch (re-)establishment are each accounted,
        # not just top-level K8sClient calls.

        k8s = _require_kubernetes()
        self._core = _throttled(k8s.CoreV1Api(api_client), rate_limiter)
        self._apps = _throttled(k8s.AppsV1Api(api_client), rate_limiter)
        self._coordination = _throttled(
            k8s.CoordinationV1Api(api_client), rate_limiter)
        self._k8s = k8s
        # LIST chunk size (client-go pager default); <= 0 disables
        # pagination and issues single unbounded LISTs
        self._list_page_size = list_page_size
        self._rate_limiter = rate_limiter
        # last-seen raw V1ObjectMeta per lease lock (see lease section)
        self._lease_raw_meta: dict = {}
        # Event names this client has created: PATCH-first on
        # recurrence instead of POST -> 409 -> PATCH (upsert_event).
        # LRU-bounded: names embed object+reason, so a months-lived
        # operator on a churning fleet would otherwise grow this
        # forever; evicted names just pay one extra POST->409 again.
        from collections import OrderedDict

        self._created_events: "OrderedDict[tuple, None]" = OrderedDict()
        self._created_events_cap = 4096

    @property
    def rate_limiter(self) -> Optional[object]:
        """The client-side limiter, for observability (None = unthrottled)."""
        return self._rate_limiter

    def _paged_list(self, list_fn, **kwargs) -> list:
        """client-go-pager-style LIST: chunk with limit/continue and
        concatenate pages.

        Large fleets make unbounded LISTs expensive for the apiserver
        (client-go's ListPager chunks at 500 for the same reason). An
        expired continue token (410 Gone mid-pagination — etcd compacted
        the snapshot the token pinned) falls back to one full LIST, the
        pager's ``FullListIfExpired`` behavior. Other API errors get the
        same typed translation as every non-LIST call, so a transient
        5xx surfaces as a retryable ApiServerError, not a raw exception
        the manager error paths don't recognize."""
        try:
            if self._list_page_size <= 0:
                return list(list_fn(**kwargs).items)
            items: list = []
            token: Optional[str] = None
            while True:
                try:
                    result = list_fn(limit=self._list_page_size,
                                     _continue=token, **kwargs)
                except self._k8s.ApiException as exc:
                    if getattr(exc, "status", None) == 410 and token:
                        return list(list_fn(**kwargs).items)
                    raise
                items.extend(result.items)
                meta = getattr(result, "metadata", None)
                token = getattr(meta, "_continue", None) or None
                if not token:
                    return items
        except self._k8s.ApiException as exc:
            raise self._translate(exc) from exc

    @classmethod
    def from_kubeconfig(cls, context: Optional[str] = None,
                        rate_limiter: Optional[object] = None) -> "RealCluster":
        _require_kubernetes()
        from kubernetes import config

        config.load_kube_config(context=context)
        return cls(rate_limiter=rate_limiter)

    @classmethod
    def in_cluster(cls, rate_limiter: Optional[object] = None) -> "RealCluster":
        _require_kubernetes()
        from kubernetes import config

        config.load_incluster_config()
        return cls(rate_limiter=rate_limiter)

    # -- error translation -------------------------------------------------
    @staticmethod
    def _retry_after_seconds(exc) -> "Optional[float]":
        """Retry-After (seconds form) from an ApiException's response
        headers, or None."""
        headers = getattr(exc, "headers", None)
        raw = headers.get("Retry-After") if headers is not None else None
        if raw is None:
            return None
        try:
            value = float(raw)
        except (TypeError, ValueError):
            return None
        return value if value >= 0 else None

    def _translate(self, exc, eviction: bool = False):
        status = getattr(exc, "status", None)
        if status == 404:
            return NotFoundError(str(exc))
        # 429 means "blocked by a PodDisruptionBudget" ONLY on the eviction
        # subresource; everywhere else it is apiserver rate limiting —
        # typed retryable, carrying the server's Retry-After so the
        # controller's backoff honors it instead of hammering the
        # throttle (controller.Controller._worker).
        if status == 429 and eviction:
            return EvictionBlockedError(str(exc))
        if status == 429:
            return ApiServerError(
                str(exc), retry_after=self._retry_after_seconds(exc))
        if status == 409:
            return ConflictError(str(exc))
        # 5xx: retryable apiserver failure — typed so the drain/eviction
        # workers defer (retry next reconcile) instead of consuming the
        # node's failure budget on a hiccup.
        if status is not None and 500 <= status < 600:
            return ApiServerError(str(exc))
        return exc

    # -- nodes -------------------------------------------------------------
    def get_node(self, name: str) -> Node:
        try:
            return _node_from(self._core.read_node(name))
        except self._k8s.ApiException as exc:
            raise self._translate(exc) from exc

    def list_nodes(self, label_selector: str = "") -> list[Node]:
        items = self._paged_list(
            self._core.list_node, label_selector=label_selector or None)
        return [_node_from(item) for item in items]

    def patch_node_labels(self, name: str,
                          labels: Mapping[str, Optional[str]]) -> Node:
        body = {"metadata": {"labels": dict(labels)}}
        try:
            return _node_from(self._core.patch_node(name, body))
        except self._k8s.ApiException as exc:
            raise self._translate(exc) from exc

    def patch_node_annotations(self, name: str,
                               annotations: Mapping[str, Optional[str]]) -> Node:
        body = {"metadata": {"annotations": dict(annotations)}}
        try:
            return _node_from(self._core.patch_node(name, body))
        except self._k8s.ApiException as exc:
            raise self._translate(exc) from exc

    def patch_node_meta(self, name: str,
                        labels: Optional[Mapping[str, Optional[str]]] = None,
                        annotations: Optional[Mapping[str, Optional[str]]]
                        = None) -> Node:
        # coalesced-write path: one strategic/merge patch carrying both
        # metadata maps instead of the base class's two requests
        meta: dict = {}
        if labels:
            meta["labels"] = dict(labels)
        if annotations:
            meta["annotations"] = dict(annotations)
        if not meta:
            return self.get_node(name)
        try:
            return _node_from(self._core.patch_node(
                name, {"metadata": meta}))
        except self._k8s.ApiException as exc:
            raise self._translate(exc) from exc

    def set_node_unschedulable(self, name: str, unschedulable: bool) -> Node:
        body = {"spec": {"unschedulable": unschedulable}}
        try:
            return _node_from(self._core.patch_node(name, body))
        except self._k8s.ApiException as exc:
            raise self._translate(exc) from exc

    # -- pods --------------------------------------------------------------
    def list_pods(self, namespace: Optional[str] = None,
                  label_selector: str = "",
                  field_selector: str = "") -> list[Pod]:
        kwargs = {"label_selector": label_selector or None,
                  "field_selector": field_selector or None}
        if namespace:
            items = self._paged_list(
                self._core.list_namespaced_pod, namespace=namespace,
                **kwargs)
        else:
            items = self._paged_list(
                self._core.list_pod_for_all_namespaces, **kwargs)
        return [_pod_from(item) for item in items]

    def delete_pod(self, namespace: str, name: str) -> None:
        try:
            self._core.delete_namespaced_pod(name, namespace)
        except self._k8s.ApiException as exc:
            raise self._translate(exc) from exc

    def patch_pod_labels(self, namespace: str, name: str,
                         labels: Mapping[str, Optional[str]]) -> Pod:
        body = {"metadata": {"labels": dict(labels)}}
        try:
            return _pod_from(self._core.patch_namespaced_pod(
                name, namespace, body))
        except self._k8s.ApiException as exc:
            raise self._translate(exc) from exc

    def evict_pod(self, namespace: str, name: str) -> None:
        eviction = self._k8s.V1Eviction(
            metadata=self._k8s.V1ObjectMeta(name=name, namespace=namespace))
        try:
            self._core.create_namespaced_pod_eviction(
                name, namespace, eviction)
        except self._k8s.ApiException as exc:
            raise self._translate(exc, eviction=True) from exc

    # -- watches -------------------------------------------------------------
    def watch(self, kinds: Optional[set[str]] = None,
              namespace: Optional[str] = None,
              label_selector: str = "") -> "watch_mod.Watch":
        """Stream Node/Pod/DaemonSet change events as
        :class:`tpu_operator_libs.k8s.watch.WatchEvent`, for driving a
        :class:`tpu_operator_libs.controller.Controller` (the live
        equivalent of FakeCluster.watch). One pump thread per kind;
        expired server watches are transparently restarted, which may
        re-deliver the current object set as ADDED events — harmless to a
        level-triggered reconcile. ``label_selector`` is pushed down to
        the server watches: the apiserver filters the stream and itself
        emits DELETED for objects that stop matching."""
        import threading

        from tpu_operator_libs.k8s import watch as watch_mod

        wanted = kinds or {watch_mod.KIND_NODE, watch_mod.KIND_POD,
                           watch_mod.KIND_DAEMON_SET}
        # stop() must actually terminate the pump threads: track each
        # pump's live kubernetes stream and stop them all on sub.stop(),
        # releasing the HTTP watch connections (client-go Stop parity).
        streams_lock = threading.Lock()
        active_streams: list = []

        def on_stop(_watch) -> None:
            with streams_lock:
                streams = list(active_streams)
            for stream in streams:
                try:
                    stream.stop()
                except Exception:
                    pass

        sub = watch_mod.Watch(on_stop=on_stop)
        selector_kwargs = (
            {"label_selector": label_selector} if label_selector else {})
        sources = []
        if watch_mod.KIND_NODE in wanted:
            sources.append((watch_mod.KIND_NODE, self._core.list_node,
                            dict(selector_kwargs), _node_from))
        if watch_mod.KIND_POD in wanted:
            if namespace:
                sources.append((watch_mod.KIND_POD,
                                self._core.list_namespaced_pod,
                                {"namespace": namespace,
                                 **selector_kwargs}, _pod_from))
            else:
                sources.append((watch_mod.KIND_POD,
                                self._core.list_pod_for_all_namespaces,
                                dict(selector_kwargs), _pod_from))
        if watch_mod.KIND_DAEMON_SET in wanted:
            if namespace:
                sources.append((watch_mod.KIND_DAEMON_SET,
                                self._apps.list_namespaced_daemon_set,
                                {"namespace": namespace,
                                 **selector_kwargs}, _daemon_set_from))
            else:
                sources.append((watch_mod.KIND_DAEMON_SET,
                                self._apps.list_daemon_set_for_all_namespaces,
                                dict(selector_kwargs), _daemon_set_from))

        def pump(kind, list_fn, kwargs, convert):
            import logging
            import random as random_mod
            import time as time_mod

            from kubernetes import watch as k8s_watch

            log = logging.getLogger(__name__)
            backoff = 0.5
            while not sub.stopped:
                stream = k8s_watch.Watch()
                with streams_lock:
                    active_streams.append(stream)
                if sub.stopped:
                    # sub.stop() may have snapshotted active_streams just
                    # before the append; re-check so this stream never
                    # opens a connection nothing will stop
                    with streams_lock:
                        active_streams.remove(stream)
                    return
                delivered = False
                try:
                    # timeout_seconds bounds how long a quiet stream blocks
                    # so a stop() is honored promptly even mid-connect
                    for raw in stream.stream(list_fn,
                                             timeout_seconds=300,
                                             **kwargs):
                        if sub.stopped:
                            return
                        event_type = raw["type"]
                        if event_type not in (watch_mod.ADDED,
                                              watch_mod.MODIFIED,
                                              watch_mod.DELETED):
                            continue  # BOOKMARK / ERROR
                        sub._deliver(watch_mod.WatchEvent(
                            event_type, kind, convert(raw["object"])))
                        delivered = True
                        backoff = 0.5
                except Exception:
                    if sub.stopped:
                        return
                    # Persistent failures (RBAC, bad namespace) would
                    # otherwise hot-loop list+watch against the API
                    # server; back off and say why.
                    log.warning("%s watch failed; restarting in %.1fs",
                                kind, backoff, exc_info=True)
                    # jittered so a fleet whose watches died together
                    # does not re-list the apiserver in lockstep
                    time_mod.sleep(backoff * random_mod.uniform(0.5, 1.0))
                    backoff = min(backoff * 2, 30.0)
                    continue
                finally:
                    stream.stop()
                    with streams_lock:
                        if stream in active_streams:
                            active_streams.remove(stream)
                if not delivered:
                    # clean-but-empty expiry loop: avoid a tight relist
                    time_mod.sleep(min(backoff, 1.0))

        for kind, list_fn, kwargs, convert in sources:
            threading.Thread(target=pump, name=f"watch-{kind}",
                             args=(kind, list_fn, kwargs, convert),
                             daemon=True).start()
        return sub

    # -- daemonsets & revisions ---------------------------------------------
    def list_daemon_sets(self, namespace: str,
                         label_selector: str = "") -> list[DaemonSet]:
        items = self._paged_list(
            self._apps.list_namespaced_daemon_set, namespace=namespace,
            label_selector=label_selector or None)
        return [_daemon_set_from(item) for item in items]

    def list_controller_revisions(self, namespace: str,
                                  label_selector: str = "") -> list[ControllerRevision]:
        items = self._paged_list(
            self._apps.list_namespaced_controller_revision,
            namespace=namespace, label_selector=label_selector or None)
        return [_revision_from(item) for item in items]

    def patch_daemon_set_annotations(
            self, namespace: str, name: str,
            annotations: Mapping[str, Optional[str]]) -> DaemonSet:
        # same merge-patch contract as the node metadata writes (None
        # deletes); carries the RolloutGuard's quarantine/bake stamps
        body = {"metadata": {"annotations": dict(annotations)}}
        try:
            return _daemon_set_from(self._apps.patch_namespaced_daemon_set(
                name, namespace, body))
        except self._k8s.ApiException as exc:
            raise self._translate(exc) from exc

    # -- leases (coordination.k8s.io, leader election) -----------------------
    # resourceVersion is opaque on the wire; it is carried through
    # ObjectMeta.resource_version verbatim (the elector only compares and
    # round-trips it, fake.py uses ints, the real server strings).
    # The raw wire metadata of the last-seen lease is cached per lock so
    # renews replace with the object's FULL metadata (labels, annotations,
    # ownerReferences for GC) rather than a reconstructed minimal one —
    # client-go's LeaseLock mutates the Get result for the same reason.
    @staticmethod
    def _lease_from(obj) -> Lease:
        meta = ObjectMeta(
            name=obj.metadata.name,
            namespace=obj.metadata.namespace or "",
            uid=obj.metadata.uid or "")
        meta.resource_version = obj.metadata.resource_version
        spec = getattr(obj, "spec", None)
        if spec is None:
            # a pre-created bare Lease manifest has no spec: an unheld lock
            return Lease(metadata=meta)
        acquire = getattr(spec, "acquire_time", None)
        renew = getattr(spec, "renew_time", None)
        return Lease(
            metadata=meta,
            holder_identity=spec.holder_identity or "",
            lease_duration_seconds=int(spec.lease_duration_seconds or 0),
            acquire_time=acquire.timestamp() if acquire else None,
            renew_time=renew.timestamp() if renew else None,
            lease_transitions=int(spec.lease_transitions or 0))

    def _lease_body(self, lease: Lease, with_version: bool):
        from datetime import datetime, timezone

        def ts(epoch):
            return (datetime.fromtimestamp(epoch, tz=timezone.utc)
                    if epoch is not None else None)

        cached = self._lease_raw_meta.get(
            (lease.metadata.namespace, lease.metadata.name))
        if with_version and cached is not None:
            # full wire metadata from the last read: labels/annotations/
            # ownerReferences survive the replace
            meta = cached
            meta.resource_version = lease.metadata.resource_version
        else:
            meta = self._k8s.V1ObjectMeta(name=lease.metadata.name,
                                          namespace=lease.metadata.namespace)
            if with_version:
                meta.resource_version = lease.metadata.resource_version
        return self._k8s.V1Lease(
            metadata=meta,
            spec=self._k8s.V1LeaseSpec(
                holder_identity=lease.holder_identity,
                lease_duration_seconds=lease.lease_duration_seconds,
                acquire_time=ts(lease.acquire_time),
                renew_time=ts(lease.renew_time),
                lease_transitions=lease.lease_transitions))

    # -- events ---------------------------------------------------------
    def _remember_created(self, key: tuple) -> None:
        self._created_events[key] = None
        self._created_events.move_to_end(key)
        while len(self._created_events) > self._created_events_cap:
            self._created_events.popitem(last=False)

    def upsert_event(self, namespace: str, name: str,
                     event: object) -> None:
        """v1 Events upsert, PATCH-first for known names: an Event this
        client already created gets a direct PATCH of count/message/
        lastTimestamp (client-go's broadcaster PATCHes known events the
        same way — POST-first would cost every recurrence two
        rate-limited API calls, POST -> 409 -> PATCH), falling back to
        POST on 404 (apiserver TTL-collected it). Unknown names POST
        first, recording the name on success OR on 409 (someone else
        created it; it exists either way)."""
        from datetime import datetime, timezone

        def ts(epoch: float):
            return datetime.fromtimestamp(epoch, tz=timezone.utc)

        key = (namespace, name)

        def body():
            return self._k8s.V1Event(
                metadata=self._k8s.V1ObjectMeta(name=name,
                                                namespace=namespace),
                involved_object=self._k8s.V1ObjectReference(
                    kind=event.kind, name=event.object_name),
                type=event.type, reason=event.reason,
                message=event.message,
                count=event.count,
                first_timestamp=ts(event.first_seen),
                last_timestamp=ts(event.last_seen))

        def post() -> bool:
            """True when a 409 indicated the Event already exists (fall
            through to PATCH); False when this POST created it."""
            try:
                self._core.create_namespaced_event(namespace, body())
                self._remember_created(key)
                return False
            except self._k8s.ApiException as exc:
                if getattr(exc, "status", None) != 409:
                    raise self._translate(exc) from exc
                self._remember_created(key)
                return True  # exists: fall through to PATCH

        def patch() -> bool:
            """True when the PATCH landed; False on 404 (TTL-collected
            — client-go's recordEvent falls back to POST the same
            way)."""
            update = {"count": event.count, "message": event.message,
                      "lastTimestamp": ts(event.last_seen).isoformat()}
            try:
                self._core.patch_namespaced_event(name, namespace, update)
                return True
            except self._k8s.ApiException as exc:
                if getattr(exc, "status", None) != 404:
                    raise self._translate(exc) from exc
                self._created_events.pop(key, None)
                return False

        if key in self._created_events:
            self._created_events.move_to_end(key)
            if patch():
                return
            if post():  # recreated... and someone else won the race
                patch()
            return
        if post():  # 409: exists from a previous process/replica
            patch()

    def _cache_lease_meta(self, raw) -> None:
        self._lease_raw_meta[(raw.metadata.namespace or "",
                              raw.metadata.name)] = raw.metadata

    def get_lease(self, namespace: str, name: str) -> Lease:
        try:
            raw = self._coordination.read_namespaced_lease(name, namespace)
        except self._k8s.ApiException as exc:
            raise self._translate(exc) from exc
        self._cache_lease_meta(raw)
        return self._lease_from(raw)

    def create_lease(self, lease: Lease) -> Lease:
        try:
            raw = self._coordination.create_namespaced_lease(
                lease.metadata.namespace,
                self._lease_body(lease, with_version=False))
        except self._k8s.ApiException as exc:
            if getattr(exc, "status", None) == 409:
                raise AlreadyExistsError(str(exc)) from exc
            raise self._translate(exc) from exc
        self._cache_lease_meta(raw)
        return self._lease_from(raw)

    def update_lease(self, lease: Lease) -> Lease:
        try:
            raw = self._coordination.replace_namespaced_lease(
                lease.metadata.name, lease.metadata.namespace,
                self._lease_body(lease, with_version=True))
        except self._k8s.ApiException as exc:
            if getattr(exc, "status", None) == 409:
                raise ConflictError(str(exc)) from exc
            raise self._translate(exc) from exc
        self._cache_lease_meta(raw)
        return self._lease_from(raw)
