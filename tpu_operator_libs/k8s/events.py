"""Forward recorder events to the cluster's v1 Events API.

The reference's state changes surface in ``kubectl describe node``
because client-go's broadcaster writes every recorded event to the
apiserver (node_upgrade_state_provider.go:87-88 emits through
record.EventRecorder). This module is that last hop for our build:

    recorder = CorrelatingEventRecorder(
        clock=clock, sink=ClusterEventSink(cluster, namespace))

Events must never break a reconcile: sink failures are logged and
swallowed, and a backend without the Events API (NotImplementedError)
disables the sink after the first attempt — the in-memory recorder
keeps recording either way.
"""

from __future__ import annotations

import logging
import threading
import uuid
from collections import OrderedDict

from tpu_operator_libs.k8s.client import K8sClient
from tpu_operator_libs.util import Event

logger = logging.getLogger(__name__)


class ClusterEventSink:
    """``CorrelatingEventRecorder`` sink writing v1 Events.

    Each distinct correlation key gets one cluster Event object named
    ``<object>.<uuid>`` — the random suffix (unlike a process-local
    counter) cannot collide with Events left behind by a previous
    operator incarnation or another replica, so the 409→PATCH path
    never grafts this run's counts onto a stale Event. Updates to the
    same correlated event re-upsert under the same name so the
    apiserver PATCHes count/lastTimestamp instead of accumulating
    copies. The key→name map is LRU-bounded.
    """

    def __init__(self, client: K8sClient, namespace: str,
                 lru_size: int = 4096) -> None:
        self._client = client
        self._namespace = namespace
        self._lock = threading.Lock()
        self._lru_size = lru_size
        self._names: "OrderedDict[tuple, str]" = OrderedDict()
        self._disabled = False

    @property
    def disabled(self) -> bool:
        """True once the backend reported it has no Events API."""
        return self._disabled

    def __call__(self, key: tuple, event: Event,
                 is_update: bool) -> None:
        if self._disabled:
            return
        with self._lock:
            name = self._names.get(key)
            if name is None:
                name = f"{event.object_name}.{uuid.uuid4().hex[:16]}"
                self._names[key] = name
            self._names.move_to_end(key)
            while len(self._names) > self._lru_size:
                self._names.popitem(last=False)
        try:
            self._client.upsert_event(self._namespace, name, event)
        except NotImplementedError:
            self._disabled = True
            logger.info(
                "cluster backend has no Events API; recorder events "
                "stay in-memory only")
        except Exception as exc:
            # an event is observability, never control flow: a failed
            # write must not fail the state transition that emitted it
            logger.warning("failed to write event %s/%s: %s",
                           self._namespace, name, exc)
