"""Typed Kubernetes object model (the slice of the API the library needs).

The reference consumes corev1.Node / corev1.Pod / appsv1.DaemonSet /
appsv1.ControllerRevision through client-go. This module models exactly the
fields the upgrade flow reads or writes — nothing more:

- Node: labels, annotations, spec.unschedulable, Ready condition
  (upgrade_state.go:980-993).
- Pod: labels, owner references, spec.nodeName, phase, container statuses
  (readiness + restart counts, upgrade_state.go:936-978), deletion timestamp
  (upgrade_state.go:779), emptyDir volume usage (drain filters).
- DaemonSet: selector labels + desired scheduled count
  (upgrade_state.go:243-246).
- ControllerRevision: name + monotonically increasing revision number, for
  the "is this pod running the newest template" oracle
  (pod_manager.go:95-121).
"""

from __future__ import annotations

import enum
import itertools
import threading
from dataclasses import dataclass, field
from typing import Optional

_uid_counter = itertools.count(1)
_uid_lock = threading.Lock()


def new_uid(prefix: str = "uid") -> str:
    with _uid_lock:
        return f"{prefix}-{next(_uid_counter)}"


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = ""
    uid: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    owner_references: list["OwnerReference"] = field(default_factory=list)
    deletion_timestamp: Optional[float] = None
    resource_version: int = 0

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = new_uid(self.name or "obj")

    def clone(self) -> "ObjectMeta":
        """Field-wise copy. The fake API server returns copies on every
        read (value semantics, like objects off the wire); the generic
        copy.deepcopy dominated simulation profiles, so cloning is
        hand-rolled over the known fields — via ``__new__`` + direct
        attribute writes, which skips dataclass argument binding and
        ``__post_init__`` (LIST-heavy reconcile passes clone every
        object in the fleet; at 4096 nodes the constructor path alone
        was ~40% of snapshot latency)."""
        new = ObjectMeta.__new__(ObjectMeta)
        new.name = self.name
        new.namespace = self.namespace
        new.uid = self.uid
        new.labels = dict(self.labels)
        new.annotations = dict(self.annotations)
        new.owner_references = [OwnerReference(r.kind, r.name, r.uid,
                                               r.controller)
                                for r in self.owner_references]
        new.deletion_timestamp = self.deletion_timestamp
        new.resource_version = self.resource_version
        return new


@dataclass
class OwnerReference:
    kind: str
    name: str
    uid: str
    controller: bool = True


class PodPhase(str, enum.Enum):
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    # Reported when the kubelet is unreachable — exactly the condition a
    # fleet upgrade provokes; parsing must not crash on it.
    UNKNOWN = "Unknown"

    def __str__(self) -> str:
        return self.value


@dataclass
class ContainerStatus:
    name: str
    ready: bool = False
    restart_count: int = 0


@dataclass
class NodeCondition:
    type: str
    status: str  # "True" / "False" / "Unknown"


@dataclass
class NodeSpec:
    unschedulable: bool = False


@dataclass
class NodeStatus:
    conditions: list[NodeCondition] = field(
        default_factory=lambda: [NodeCondition("Ready", "True")])


@dataclass
class Node:
    metadata: ObjectMeta
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

    def is_unschedulable(self) -> bool:
        """True if the node is cordoned (upgrade_state.go:980-983)."""
        return self.spec.unschedulable

    def is_ready(self) -> bool:
        """True unless an explicit Ready condition is not "True"
        (upgrade_state.go:985-993)."""
        for cond in self.status.conditions:
            if cond.type == "Ready" and cond.status != "True":
                return False
        return True

    def clone(self) -> "Node":
        new = Node.__new__(Node)
        new.metadata = self.metadata.clone()
        new.spec = NodeSpec(unschedulable=self.spec.unschedulable)
        new.status = NodeStatus(conditions=[
            NodeCondition(c.type, c.status)
            for c in self.status.conditions])
        return new


@dataclass
class Volume:
    name: str
    empty_dir: bool = False


@dataclass
class PodSpec:
    node_name: str = ""
    volumes: list[Volume] = field(default_factory=list)


@dataclass
class PodStatus:
    phase: PodPhase = PodPhase.PENDING
    container_statuses: list[ContainerStatus] = field(default_factory=list)
    init_container_statuses: list[ContainerStatus] = field(default_factory=list)


@dataclass
class Pod:
    metadata: ObjectMeta
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    def controller_owner(self) -> Optional[OwnerReference]:
        for ref in self.metadata.owner_references:
            if ref.controller:
                return ref
        if self.metadata.owner_references:
            return self.metadata.owner_references[0]
        return None

    def is_orphaned(self) -> bool:
        """Pod with no owner references — never auto-upgraded because its
        revision hash cannot be compared (upgrade_state.go:353-355)."""
        return not self.metadata.owner_references

    def is_ready(self) -> bool:
        """Running with at least one container and all containers ready
        (mirrors isDriverPodInSync's readiness arm and the validation
        manager's isPodReady, upgrade_state.go:947-960,
        validation_manager.go:118-136)."""
        if self.status.phase != PodPhase.RUNNING:
            return False
        if not self.status.container_statuses:
            return False
        return all(c.ready for c in self.status.container_statuses)

    def is_failing(self, restart_threshold: int = 10) -> bool:
        """A not-ready container restarted more than ``restart_threshold``
        times (upgrade_state.go:966-978)."""
        for status in (self.status.init_container_statuses
                       + self.status.container_statuses):
            if not status.ready and status.restart_count > restart_threshold:
                return True
        return False

    def uses_empty_dir(self) -> bool:
        return any(v.empty_dir for v in self.spec.volumes)

    def is_daemonset_pod(self) -> bool:
        owner = self.controller_owner()
        return owner is not None and owner.kind == "DaemonSet"

    def is_mirror_pod(self) -> bool:
        return "kubernetes.io/config.mirror" in self.metadata.annotations

    def field_map(self) -> dict[str, str]:
        """The pod's field-selector-addressable fields (the subset the
        apiserver supports for pods; shared by every client backend so
        field-selector semantics cannot drift between fake and cache)."""
        return {
            "metadata.name": self.metadata.name,
            "metadata.namespace": self.metadata.namespace,
            "spec.nodeName": self.spec.node_name,
            "status.phase": str(self.status.phase),
        }

    def clone(self) -> "Pod":
        new = Pod.__new__(Pod)
        new.metadata = self.metadata.clone()
        new.spec = PodSpec(node_name=self.spec.node_name,
                           volumes=[Volume(v.name, v.empty_dir)
                                    for v in self.spec.volumes])
        new.status = PodStatus(
            phase=self.status.phase,
            container_statuses=[
                ContainerStatus(c.name, c.ready, c.restart_count)
                for c in self.status.container_statuses],
            init_container_statuses=[
                ContainerStatus(c.name, c.ready, c.restart_count)
                for c in self.status.init_container_statuses])
        return new


@dataclass
class DaemonSetSpec:
    selector: dict[str, str] = field(default_factory=dict)
    # Opaque identifier of the current pod template; bumping it models a
    # rollout (the fake cluster turns it into a new ControllerRevision).
    template_generation: int = 1


@dataclass
class DaemonSetStatus:
    desired_number_scheduled: int = 0


@dataclass
class DaemonSet:
    metadata: ObjectMeta
    spec: DaemonSetSpec = field(default_factory=DaemonSetSpec)
    status: DaemonSetStatus = field(default_factory=DaemonSetStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    def clone(self) -> "DaemonSet":
        return DaemonSet(
            metadata=self.metadata.clone(),
            spec=DaemonSetSpec(
                selector=dict(self.spec.selector),
                template_generation=self.spec.template_generation),
            status=DaemonSetStatus(
                desired_number_scheduled=self.status.desired_number_scheduled))


@dataclass
class ControllerRevision:
    metadata: ObjectMeta
    revision: int = 1

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def hash(self) -> str:
        """The revision hash is the name suffix after '<ds-name>-'
        (pod_manager.go:118-119). Controller-generated hashes never contain
        hyphens (FakeCluster enforces this for injected hashes), so the last
        segment is always the full hash."""
        return self.metadata.name.rsplit("-", 1)[-1]

    def clone(self) -> "ControllerRevision":
        return ControllerRevision(metadata=self.metadata.clone(),
                                  revision=self.revision)


@dataclass
class PodDisruptionBudget:
    """A policy/v1 PodDisruptionBudget (the object behind eviction 429s).

    Exactly one of ``min_available`` / ``max_unavailable`` should be
    set; each accepts an int or a percent string ("50%"), scaled
    against the count of selector-matching pods (the apiserver scales
    against the controller's expected replicas; matching-pod count is
    the envtest-grade approximation — with no controllers, they agree).
    """

    metadata: ObjectMeta
    selector: dict = field(default_factory=dict)
    min_available: Optional[object] = None
    max_unavailable: Optional[object] = None

    def clone(self) -> "PodDisruptionBudget":
        return PodDisruptionBudget(
            metadata=self.metadata.clone(),
            selector=dict(self.selector),
            min_available=self.min_available,
            max_unavailable=self.max_unavailable)


@dataclass
class Lease:
    """A coordination.k8s.io/v1 Lease, the leader-election lock object.

    The reference library leaves leader election to its consumer's
    controller-runtime manager; a complete TPU operator stack must own it
    (see k8s/leaderelection.py). Times are epoch seconds (spec.acquireTime /
    spec.renewTime MicroTime equivalents).
    """

    metadata: ObjectMeta
    holder_identity: str = ""
    lease_duration_seconds: int = 15
    acquire_time: Optional[float] = None
    renew_time: Optional[float] = None
    lease_transitions: int = 0

    def clone(self) -> "Lease":
        return Lease(metadata=self.metadata.clone(),
                     holder_identity=self.holder_identity,
                     lease_duration_seconds=self.lease_duration_seconds,
                     acquire_time=self.acquire_time,
                     renew_time=self.renew_time,
                     lease_transitions=self.lease_transitions)
