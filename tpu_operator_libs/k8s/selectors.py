"""Label and field selectors.

The reference relies on apimachinery's selector machinery (labels.Selector in
pod_manager.go:98, metav1.ListOptions selectors in validation_manager.go:77-78).
This module implements the subset of Kubernetes selector syntax the upgrade
flow uses, faithfully enough that policy fields like
``waitForCompletion.podSelector`` accept real-world selector strings:

- equality-based: ``k=v``, ``k==v``, ``k!=v``
- set-based: ``k in (a,b)``, ``k notin (a,b)``, ``k`` (exists),
  ``!k`` (not exists)
- comma-joined conjunction of the above
- field selectors of the form ``spec.nodeName=<name>`` (consts.go:70-73)
"""

from __future__ import annotations

import re
from typing import Callable, Mapping, Optional

Matcher = Callable[[Mapping[str, str]], bool]


class SelectorParseError(ValueError):
    pass


# Label keys: [prefix/]name with alphanumerics, '-', '_', '.' (the charset
# Kubernetes accepts); field selector keys additionally use dots.
_KEY = r"[A-Za-z0-9_./-]+"
_SET_RE = re.compile(
    rf"^\s*(?P<key>{_KEY})\s+(?P<op>in|notin)\s+\((?P<vals>[^)]*)\)\s*$")
# Label values: empty or alphanumeric with '-', '_', '.' (Kubernetes charset).
_EQ_RE = re.compile(
    rf"^\s*(?P<key>{_KEY})\s*(?P<op>==|=|!=)\s*(?P<val>[A-Za-z0-9_.-]*)\s*$")
_EXISTS_RE = re.compile(rf"^\s*(?P<neg>!?)\s*(?P<key>{_KEY})\s*$")


def _split_requirements(selector: str) -> list[str]:
    """Split on commas that are not inside parentheses."""
    parts: list[str] = []
    depth = 0
    current = []
    for ch in selector:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(current))
            current = []
        else:
            current.append(ch)
    if current:
        parts.append("".join(current))
    return [p for p in (part.strip() for part in parts) if p]


def parse_label_selector(selector: str) -> Matcher:
    """Compile a label selector string into a matcher over a label dict.

    An empty selector matches everything (the semantics the reference gets
    from metav1.ListOptions with an empty LabelSelector).
    """
    selector = (selector or "").strip()
    if not selector:
        return lambda labels: True

    requirements: list[Matcher] = []
    equalities: dict[str, str] = {}  # k=v requirements, the common case
    unsatisfiable = False  # k=a,k=b with a != b: matches nothing
    for req in _split_requirements(selector):
        m = _SET_RE.match(req)
        if m:
            key = m.group("key")
            values = {v.strip() for v in m.group("vals").split(",") if v.strip()}
            if m.group("op") == "in":
                requirements.append(
                    lambda labels, k=key, vs=values: labels.get(k) in vs)
            else:
                requirements.append(
                    lambda labels, k=key, vs=values:
                        k not in labels or labels[k] not in vs)
            continue
        m = _EQ_RE.match(req)
        if m:
            key, op, val = m.group("key"), m.group("op"), m.group("val")
            if op in ("=", "=="):
                if key in equalities and equalities[key] != val:
                    # contradictory conjunction — the dict must not
                    # collapse it to last-value-wins (the apiserver
                    # ANDs the requirements and matches nothing)
                    unsatisfiable = True
                equalities[key] = val
            else:
                requirements.append(
                    lambda labels, k=key, v=val: labels.get(k) != v)
            continue
        m = _EXISTS_RE.match(req)
        if m:
            key, neg = m.group("key"), bool(m.group("neg"))
            if neg:
                requirements.append(lambda labels, k=key: k not in labels)
            else:
                requirements.append(lambda labels, k=key: k in labels)
            continue
        raise SelectorParseError(f"cannot parse selector requirement {req!r}")

    # Matchers run once per object per LIST — at fleet scale (4096 nodes,
    # ~10k pods) per-call overhead is reconcile latency, so the common
    # shapes get closures without the all()-over-genexpr indirection.
    if unsatisfiable:
        # parsed fully (malformed requirements above still raise), but
        # the conjunction can never hold
        return lambda labels: False
    if equalities:
        items = tuple(equalities.items())
        if not requirements:
            if len(items) == 1:
                (k0, v0), = items
                return lambda labels: labels.get(k0) == v0

            def eq_only(labels, _items=items):
                for k, v in _items:
                    if labels.get(k) != v:
                        return False
                return True
            return eq_only

        def eq_requirement(labels, _items=items):
            for k, v in _items:
                if labels.get(k) != v:
                    return False
            return True
        requirements.append(eq_requirement)
    if len(requirements) == 1:
        return requirements[0]
    return lambda labels: all(r(labels) for r in requirements)


def matches_labels(selector: str, labels: Mapping[str, str]) -> bool:
    return parse_label_selector(selector)(labels)


def parse_field_selector(selector: str) -> Matcher:
    """Compile a field selector into a matcher over a flat field dict.

    Objects are exposed as flat dotted field maps (e.g. pods provide
    ``spec.nodeName``, ``metadata.name``, ``metadata.namespace``,
    ``status.phase``). Supports comma-joined ``=``/``==``/``!=`` requirements,
    which is the full syntax Kubernetes itself supports for field selectors.
    """
    selector = (selector or "").strip()
    if not selector:
        return lambda fields: True
    requirements: list[Matcher] = []
    for req in _split_requirements(selector):
        m = _EQ_RE.match(req)
        if not m:
            raise SelectorParseError(f"cannot parse field selector {req!r}")
        key, op, val = m.group("key"), m.group("op"), m.group("val")
        if op in ("=", "=="):
            requirements.append(lambda fields, k=key, v=val: fields.get(k) == v)
        else:
            requirements.append(lambda fields, k=key, v=val: fields.get(k) != v)
    return lambda fields: all(r(fields) for r in requirements)


def exact_field_requirement(selector: str, key: str) -> Optional[str]:
    """The value an ``=``/``==`` requirement pins ``key`` to, or None.

    Lets a store serve an indexed fast path for common exact-match field
    selectors (the apiserver does the same for ``spec.nodeName`` on
    pods) without changing matching semantics: callers still apply the
    full compiled matcher; this only narrows the candidate set. Returns
    None for absent keys, ``!=`` requirements, and unparseable
    selectors (the caller's full matcher is the one that raises).
    """
    selector = (selector or "").strip()
    if not selector:
        return None
    for req in _split_requirements(selector):
        m = _EQ_RE.match(req)
        if m and m.group("key") == key and m.group("op") in ("=", "=="):
            return m.group("val")
    return None


def selector_from_labels(labels: Mapping[str, str]) -> str:
    """Render a label dict as an equality selector string (the inverse the
    reference gets from labels.SelectorFromSet, pod_manager.go:98)."""
    return ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
