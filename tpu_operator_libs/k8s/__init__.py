"""Minimal Kubernetes object model, selectors, client seam and fake cluster.

The reference leans on k8s.io/client-go, apimachinery and controller-runtime
(SURVEY.md L0). This package is the TPU build's equivalent substrate:

- ``objects``: typed Node / Pod / DaemonSet / ControllerRevision model.
- ``selectors``: label selectors (equality and set-based) + field selectors.
- ``client``: the abstract cluster interface every manager talks to.
- ``fake``: a thread-safe in-memory API server — the envtest substitute the
  test strategy requires (SURVEY.md §4: "fake in-memory API server fixture").
- ``drain``: cordon/uncordon + drain filter chain, replacing the reference's
  dependency on k8s.io/kubectl/pkg/drain.
- ``real``: optional adapter to a live cluster via the ``kubernetes`` client
  (import-gated; not required for tests or simulation).
- ``leaderelection``: Lease-based leader election for HA operator
  deployments (client-go tools/leaderelection analogue).
- ``flowcontrol``: client-side token-bucket QPS limiting (client-go
  ``flowcontrol`` analogue; the Python kubernetes client ships none).
- ``cached``: informer-backed read cache over any backend — the
  controller-runtime cached-client analogue the provider's read-back
  poll was designed against.
"""

from tpu_operator_libs.k8s.objects import (  # noqa: F401
    ContainerStatus,
    ControllerRevision,
    DaemonSet,
    Lease,
    Node,
    ObjectMeta,
    OwnerReference,
    Pod,
    PodPhase,
)
from tpu_operator_libs.k8s.cached import CachedReadClient  # noqa: F401
from tpu_operator_libs.k8s.client import K8sClient  # noqa: F401
from tpu_operator_libs.k8s.fake import FakeCluster  # noqa: F401
from tpu_operator_libs.k8s.events import ClusterEventSink  # noqa: F401
from tpu_operator_libs.k8s.flowcontrol import (  # noqa: F401
    TokenBucketRateLimiter,
)
from tpu_operator_libs.k8s.leaderelection import (  # noqa: F401
    LeaderElectionConfig,
    LeaderElector,
)
