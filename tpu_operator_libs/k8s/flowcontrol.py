"""Client-side API flow control: token-bucket QPS limiting.

The reference inherits this from client-go for free: every clientset
call passes through ``rest.Config``'s rate limiter
(``flowcontrol.NewTokenBucketRateLimiter``, default QPS 5 / burst 10),
which is what keeps a hot reconcile loop — or a drain wave firing a
worker per node — from hammering the apiserver. The Python ``kubernetes``
client has no such layer, so this module owns the limiter and
:class:`~tpu_operator_libs.k8s.real.RealCluster` mounts it where
client-go does — at the transport, charging one token per HTTP request
(each page of a chunked LIST, each watch re-establishment), not one per
K8sClient call:

    RealCluster(rate_limiter=TokenBucketRateLimiter(qps=20, burst=30))

Throttle the *apiserver-bound* client, never a cached read client:
informer cache hits cost the apiserver nothing and must not burn budget.
"""

from tpu_operator_libs.util import TokenBucketRateLimiter  # noqa: F401

__all__ = ["TokenBucketRateLimiter"]
