"""Client-side API flow control: token-bucket QPS limiting.

The reference inherits this from client-go for free: every clientset
call passes through ``rest.Config``'s rate limiter
(``flowcontrol.NewTokenBucketRateLimiter``, default QPS 5 / burst 10),
which is what keeps a hot reconcile loop — or a drain wave firing a
worker per node — from hammering the apiserver. The Python ``kubernetes``
client has no such layer, so this module owns the limiter and
:class:`~tpu_operator_libs.k8s.real.RealCluster` mounts it where
client-go does — at the transport, charging one token per HTTP request
(each page of a chunked LIST, each watch re-establishment), not one per
K8sClient call:

    RealCluster(rate_limiter=TokenBucketRateLimiter(qps=20, burst=30))

Throttle the *apiserver-bound* client, never a cached read client:
informer cache hits cost the apiserver nothing and must not burn budget.
"""

from __future__ import annotations

import logging
import threading
import time as _time
from typing import Callable, Optional

logger = logging.getLogger(__name__)

# client-go logs client-side throttling that delays a request by more
# than 1 s at warning level; mirror that.
_LONG_THROTTLE_WARN_S = 1.0


class TokenBucketRateLimiter:
    """Token bucket with client-go flowcontrol semantics.

    ``qps`` tokens accrue per second up to a capacity of ``burst``.
    :meth:`wait` always admits the caller, blocking until its
    reservation matures; concurrent waiters queue fairly because each
    reservation pushes the bucket further into debt (golang
    ``rate.Limiter`` reservation model). :meth:`try_accept` is the
    non-blocking form (client-go ``TryAccept``).

    ``now``/``sleep`` are injectable so tests drive time explicitly.
    """

    def __init__(self, qps: float = 5.0, burst: int = 10,
                 now: Optional[Callable[[], float]] = None,
                 sleep: Optional[Callable[[float], None]] = None) -> None:
        if qps <= 0:
            raise ValueError(f"qps must be positive, got {qps}")
        if burst < 1:
            raise ValueError(f"burst must be >= 1, got {burst}")
        self.qps = float(qps)
        self.burst = int(burst)
        self._now = now or _time.monotonic
        self._sleep = sleep or _time.sleep
        self._lock = threading.Lock()
        self._tokens = float(burst)  # may go negative: queued debt
        self._last = self._now()
        self._waited_total = 0.0

    def _refill(self, now: float) -> None:
        """Accrue tokens since the last accounting instant (lock held)."""
        elapsed = max(0.0, now - self._last)
        self._last = now
        self._tokens = min(float(self.burst),
                           self._tokens + elapsed * self.qps)

    def try_accept(self) -> bool:
        """Take a token if one is available right now; never blocks."""
        with self._lock:
            self._refill(self._now())
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    def wait(self) -> float:
        """Reserve the next token, blocking until the reservation
        matures. Returns the seconds slept (0.0 when admitted
        immediately)."""
        with self._lock:
            now = self._now()
            self._refill(now)
            self._tokens -= 1.0
            delay = 0.0 if self._tokens >= 0.0 else -self._tokens / self.qps
            self._waited_total += delay
        if delay > 0.0:
            if delay > _LONG_THROTTLE_WARN_S:
                logger.warning(
                    "client-side throttling: waiting %.2fs for an API "
                    "token (qps=%g burst=%d)", delay, self.qps, self.burst)
            self._sleep(delay)
        return delay

    @property
    def waited_seconds_total(self) -> float:
        """Cumulative seconds callers spent throttled (observability)."""
        with self._lock:
            return self._waited_total
