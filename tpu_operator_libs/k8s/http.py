"""Dependency-free HTTP client backend for the Kubernetes API.

:class:`HttpCluster` implements the same :class:`~tpu_operator_libs.k8s.
client.K8sClient` seam as FakeCluster/RealCluster, but speaks the
apiserver's REST wire protocol directly through ``urllib`` — no
``kubernetes`` package required. Two reasons this backend exists:

1. **Hermetic images.** The reference links client-go into the operator
   binary (upgrade_state.go:104-108); the Python ``kubernetes`` client
   is a heavyweight optional dependency this framework must not hard-
   require. With this module the full operator stack runs anywhere a
   Python interpreter and a kube-apiserver endpoint exist.
2. **Wire-level verification.** ``tools/wire_smoke.py`` drives the real
   upgrade flow through this adapter over actual TCP sockets against an
   independently-implemented apiserver double
   (``tools/wire_apiserver.py``), committing evidence that the
   framework's HTTP protocol behavior — merge patches, eviction
   subresource, chunked LISTs, watch streams, conflict handling — is
   correct, not just that FakeCluster agrees with itself (the
   reference's envtest runs a real apiserver for the same reason,
   upgrade_suit_test.go:73-97).

Protocol choices mirror the reference's client usage:

- Label/annotation writes are ``application/merge-patch+json`` bodies
  with ``null`` meaning delete (node_upgrade_state_provider.go:80-82),
  so concurrent writers never clobber unrelated keys.
- Evictions POST a ``policy/v1`` Eviction to the pod's ``eviction``
  subresource (drain_manager.go's drain helper does the same through
  kubectl-drain); a 429 means a PodDisruptionBudget blocked it.
- LISTs are chunked with ``limit``/``continue`` so a 4096-node fleet
  never materializes in one response (the same paging client-go's
  pager does).
- Watches stream newline-delimited JSON from ``?watch=true`` requests
  into the shared :class:`~tpu_operator_libs.k8s.watch.Watch` type the
  controller runtime consumes.
"""

from __future__ import annotations

import json
import logging
import random
import ssl
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Iterator, Mapping, Optional

from tpu_operator_libs.k8s.client import (
    AlreadyExistsError,
    ApiServerError,
    ConflictError,
    EvictionBlockedError,
    K8sClient,
    NotFoundError,
)
from tpu_operator_libs.k8s.objects import (
    ContainerStatus,
    ControllerRevision,
    DaemonSet,
    DaemonSetSpec,
    DaemonSetStatus,
    Lease,
    Node,
    NodeCondition,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    OwnerReference,
    Pod,
    PodPhase,
    PodSpec,
    PodStatus,
    Volume,
)
from tpu_operator_libs.k8s.watch import (
    ADDED,
    DELETED,
    KIND_DAEMON_SET,
    KIND_NODE,
    KIND_POD,
    MODIFIED,
    Watch,
    WatchEvent,
)

logger = logging.getLogger(__name__)

#: In-cluster service-account credential paths (what client-go's
#: rest.InClusterConfig reads).
SERVICEACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

_MERGE_PATCH = "application/merge-patch+json"
_JSON = "application/json"


# ---------------------------------------------------------------------------
# JSON <-> typed object converters
# ---------------------------------------------------------------------------

def _meta_from_json(meta: dict) -> ObjectMeta:
    out = ObjectMeta(
        name=meta.get("name") or "",
        namespace=meta.get("namespace") or "",
        uid=meta.get("uid") or "",
        labels=dict(meta.get("labels") or {}),
        annotations=dict(meta.get("annotations") or {}),
        owner_references=[
            OwnerReference(kind=ref.get("kind", ""),
                           name=ref.get("name", ""),
                           uid=ref.get("uid", ""),
                           controller=bool(ref.get("controller")))
            for ref in meta.get("ownerReferences") or []],
        deletion_timestamp=(
            0.0 if meta.get("deletionTimestamp") else None),
    )
    try:
        out.resource_version = int(meta.get("resourceVersion") or 0)
    except (TypeError, ValueError):
        # the apiserver's resourceVersion is an opaque string; a
        # non-integer one still means "some version" for snapshots
        out.resource_version = 0
    return out


def node_from_json(obj: dict) -> Node:
    spec = obj.get("spec") or {}
    status = obj.get("status") or {}
    return Node(
        metadata=_meta_from_json(obj.get("metadata") or {}),
        spec=NodeSpec(unschedulable=bool(spec.get("unschedulable"))),
        status=NodeStatus(conditions=[
            NodeCondition(c.get("type", ""), c.get("status", ""))
            for c in status.get("conditions") or []]))


def _containers_from_json(statuses: list) -> list[ContainerStatus]:
    return [ContainerStatus(name=c.get("name", ""),
                            ready=bool(c.get("ready")),
                            restart_count=int(c.get("restartCount") or 0))
            for c in statuses or []]


def pod_from_json(obj: dict) -> Pod:
    spec = obj.get("spec") or {}
    status = obj.get("status") or {}
    try:
        phase = PodPhase(status.get("phase") or "Pending")
    except ValueError:
        phase = PodPhase.UNKNOWN
    return Pod(
        metadata=_meta_from_json(obj.get("metadata") or {}),
        spec=PodSpec(
            node_name=spec.get("nodeName") or "",
            volumes=[Volume(name=v.get("name", ""),
                            empty_dir="emptyDir" in v)
                     for v in spec.get("volumes") or []]),
        status=PodStatus(
            phase=phase,
            container_statuses=_containers_from_json(
                status.get("containerStatuses")),
            init_container_statuses=_containers_from_json(
                status.get("initContainerStatuses"))))


def daemon_set_from_json(obj: dict) -> DaemonSet:
    spec = obj.get("spec") or {}
    status = obj.get("status") or {}
    selector = (spec.get("selector") or {}).get("matchLabels") or {}
    annotations = (obj.get("metadata") or {}).get("annotations") or {}
    try:
        generation = int(annotations.get(
            "deprecated.daemonset.template.generation") or 1)
    except (TypeError, ValueError):
        generation = 1
    return DaemonSet(
        metadata=_meta_from_json(obj.get("metadata") or {}),
        spec=DaemonSetSpec(selector=dict(selector),
                           template_generation=generation),
        status=DaemonSetStatus(desired_number_scheduled=int(
            status.get("desiredNumberScheduled") or 0)))


def controller_revision_from_json(obj: dict) -> ControllerRevision:
    return ControllerRevision(
        metadata=_meta_from_json(obj.get("metadata") or {}),
        revision=int(obj.get("revision") or 1))


def _micro_time_to_epoch(value) -> Optional[float]:
    """RFC3339 MicroTime -> epoch seconds (None passes through)."""
    import calendar

    if not value:
        return None
    base, _, frac = str(value).rstrip("Z").partition(".")
    try:
        parsed = time.strptime(base, "%Y-%m-%dT%H:%M:%S")
    except ValueError:
        return None
    epoch = float(calendar.timegm(parsed))
    if frac:
        try:
            epoch += float(f"0.{frac}")
        except ValueError:
            pass
    return epoch


def _epoch_to_micro_time(epoch: Optional[float]) -> Optional[str]:
    if epoch is None:
        return None
    whole = time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(epoch))
    return f"{whole}.{int((epoch % 1.0) * 1e6):06d}Z"


def lease_from_json(obj: dict) -> Lease:
    meta = _meta_from_json(obj.get("metadata") or {})
    # the apiserver's resourceVersion is an opaque string the update
    # must echo verbatim — keep it raw, like the RealCluster adapter
    meta.resource_version = (obj.get("metadata") or {}).get(
        "resourceVersion") or 0
    spec = obj.get("spec") or {}
    return Lease(
        metadata=meta,
        holder_identity=spec.get("holderIdentity") or "",
        lease_duration_seconds=int(
            spec.get("leaseDurationSeconds") or 0),
        acquire_time=_micro_time_to_epoch(spec.get("acquireTime")),
        renew_time=_micro_time_to_epoch(spec.get("renewTime")),
        lease_transitions=int(spec.get("leaseTransitions") or 0))


def _lease_to_json(lease: Lease, with_version: bool,
                   base_meta: Optional[dict] = None) -> dict:
    """``base_meta``: the raw wire metadata from the last read of this
    lease — a PUT is a REPLACE, so labels/annotations/ownerReferences
    must ride along or every renew strips them (client-go's LeaseLock
    mutates the Get result for the same reason; RealCluster caches the
    raw object identically, real.py:485-527)."""
    meta: dict = dict(base_meta or {})
    meta["name"] = lease.metadata.name
    meta["namespace"] = lease.metadata.namespace
    if with_version:
        meta["resourceVersion"] = str(lease.metadata.resource_version)
    else:
        meta.pop("resourceVersion", None)
    spec: dict = {
        "holderIdentity": lease.holder_identity,
        "leaseDurationSeconds": lease.lease_duration_seconds,
        "leaseTransitions": lease.lease_transitions,
    }
    acquire = _epoch_to_micro_time(lease.acquire_time)
    renew = _epoch_to_micro_time(lease.renew_time)
    if acquire:
        spec["acquireTime"] = acquire
    if renew:
        spec["renewTime"] = renew
    return {"apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
            "metadata": meta, "spec": spec}


_KIND_PARSERS = {
    KIND_NODE: node_from_json,
    KIND_POD: pod_from_json,
    KIND_DAEMON_SET: daemon_set_from_json,
}


# ---------------------------------------------------------------------------
# the client
# ---------------------------------------------------------------------------

class HttpCluster(K8sClient):
    """K8sClient over the apiserver REST API with zero dependencies.

    ``base_url`` like ``https://10.0.0.1:443`` or ``http://127.0.0.1:8001``
    (e.g. a ``kubectl proxy``). ``token`` adds a Bearer header;
    ``ca_file`` pins the server certificate; ``insecure`` skips TLS
    verification (test doubles only).
    """

    def __init__(self, base_url: str, token: Optional[str] = None,
                 ca_file: Optional[str] = None, insecure: bool = False,
                 timeout_s: float = 30.0, list_chunk: int = 500,
                 rate_limiter: Optional[object] = None,
                 token_file: Optional[str] = None) -> None:
        self._base = base_url.rstrip("/")
        self._static_token = token
        # token_file wins over token and is re-read (mtime-cached) per
        # request: bound service-account tokens rotate on disk (~1 h
        # lifetime) and a once-read token would 401 the long-running
        # operator after the first rotation
        self._token_file = token_file
        self._token_cache: tuple[float, str] = (-1.0, "")
        self._timeout = timeout_s
        self._chunk = list_chunk
        # client-go placement: every HTTP request (each LIST page, each
        # watch (re)establishment) charges one token at the transport
        self._rate_limiter = rate_limiter
        self._watch_threads: list[threading.Thread] = []
        self._lease_raw_meta: dict[tuple, dict] = {}
        # injectable for tests: 429-throttle and watch-reconnect sleeps
        self._sleep = time.sleep
        if ca_file:
            self._ssl = ssl.create_default_context(cafile=ca_file)
        elif insecure:
            self._ssl = ssl.create_default_context()
            self._ssl.check_hostname = False
            self._ssl.verify_mode = ssl.CERT_NONE
        else:
            self._ssl = ssl.create_default_context()

    @classmethod
    def in_cluster(cls, **kwargs: object) -> "HttpCluster":
        """Build from the pod's service-account credentials (what
        client-go's rest.InClusterConfig does). The token is wired as a
        token_file so kubelet rotations of the bound token are picked
        up live."""
        import os

        host = os.environ["KUBERNETES_SERVICE_HOST"]
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        # fail fast on missing credentials, like InClusterConfig
        with open(f"{SERVICEACCOUNT_DIR}/token") as fh:
            fh.read()
        return cls(f"https://{host}:{port}",
                   token_file=f"{SERVICEACCOUNT_DIR}/token",
                   ca_file=f"{SERVICEACCOUNT_DIR}/ca.crt", **kwargs)

    @property
    def _token(self) -> Optional[str]:
        if self._token_file is None:
            return self._static_token
        import os

        try:
            mtime = os.stat(self._token_file).st_mtime
        except OSError:
            # keep serving the last-known token through a transient
            # stat failure; auth errors will surface loudly if stale
            return self._token_cache[1] or self._static_token
        if mtime != self._token_cache[0]:
            with open(self._token_file) as fh:
                self._token_cache = (mtime, fh.read().strip())
        return self._token_cache[1]

    # -- plumbing ---------------------------------------------------------
    #: In-place retries of a non-eviction 429 before surfacing the typed
    #: ApiServerError (the server's Retry-After, when present, paces the
    #: wait). Kept small: the reconcile loop's own backoff is the real
    #: retry budget.
    RETRY_429_ATTEMPTS = 2
    #: Ceiling on a single honored Retry-After sleep — a misconfigured
    #: server must not park a reconcile for minutes.
    RETRY_AFTER_CAP_S = 10.0

    @staticmethod
    def _retry_after_seconds(headers) -> Optional[float]:
        """Parse a Retry-After header (seconds form; the HTTP-date form
        is not worth a date parser here) from an HTTPError's headers."""
        raw = headers.get("Retry-After") if headers is not None else None
        if raw is None:
            return None
        try:
            value = float(raw)
        except (TypeError, ValueError):
            return None
        return value if value >= 0 else None

    def _request(self, method: str, path: str, body: Optional[dict] = None,
                 content_type: str = _JSON,
                 timeout: Optional[float] = None,
                 eviction: bool = False):
        """One API call -> parsed JSON. Maps HTTP errors onto the
        client-seam exception types (client.py), so callers are backend
        agnostic. A 429 means "PDB-blocked" ONLY on the eviction
        subresource (``eviction=True``); anywhere else it is apiserver
        rate limiting — retried in place honoring the Retry-After header,
        then surfaced as a retryable ApiServerError carrying it."""
        attempts_429 = 0
        while True:
            if self._rate_limiter is not None:
                self._rate_limiter.wait()
            data = None if body is None else json.dumps(body).encode()
            req = urllib.request.Request(
                f"{self._base}{path}", data=data, method=method)
            req.add_header("Accept", _JSON)
            if data is not None:
                req.add_header("Content-Type", content_type)
            if self._token:
                req.add_header("Authorization", f"Bearer {self._token}")
            ctx = self._ssl if self._base.startswith("https") else None
            try:
                with urllib.request.urlopen(
                        req, timeout=timeout or self._timeout,
                        context=ctx) as resp:
                    payload = resp.read()
            except urllib.error.HTTPError as exc:
                detail = ""
                try:
                    detail = exc.read().decode(errors="replace")[:400]
                except OSError:
                    pass
                finally:
                    exc.close()  # HTTPError owns the response socket
                if exc.code == 404:
                    raise NotFoundError(
                        f"{method} {path}: not found") from exc
                if exc.code == 409:
                    raise ConflictError(
                        f"{method} {path}: conflict: {detail}") from exc
                if exc.code == 429:
                    if eviction:
                        raise EvictionBlockedError(
                            f"{method} {path}: blocked: {detail}") from exc
                    retry_after = self._retry_after_seconds(exc.headers)
                    if attempts_429 < self.RETRY_429_ATTEMPTS:
                        attempts_429 += 1
                        # server-paced when it said so, else a jittered
                        # second — never a synchronized fixed delay
                        delay = (min(retry_after, self.RETRY_AFTER_CAP_S)
                                 if retry_after is not None
                                 else random.uniform(0.2, 1.0))
                        self._sleep(delay)
                        continue
                    raise ApiServerError(
                        f"{method} {path}: HTTP 429 throttled: {detail}",
                        retry_after=retry_after) from exc
                raise ApiServerError(
                    f"{method} {path}: HTTP {exc.code}: {detail}") from exc
            except (urllib.error.URLError, OSError, TimeoutError) as exc:
                raise ApiServerError(f"{method} {path}: {exc}") from exc
            if not payload:
                return None
            try:
                return json.loads(payload)
            except json.JSONDecodeError as exc:
                raise ApiServerError(
                    f"{method} {path}: unparseable response") from exc

    def _list(self, path: str, label_selector: str = "",
              field_selector: str = "") -> Iterator[dict]:
        """Chunked LIST: follows metadata.continue until exhausted."""
        cont = ""
        while True:
            params = {"limit": str(self._chunk)}
            if label_selector:
                params["labelSelector"] = label_selector
            if field_selector:
                params["fieldSelector"] = field_selector
            if cont:
                params["continue"] = cont
            page = self._request(
                "GET", f"{path}?{urllib.parse.urlencode(params)}")
            if not isinstance(page, dict):
                raise ApiServerError(f"GET {path}: not a list response")
            yield from page.get("items") or []
            cont = (page.get("metadata") or {}).get("continue") or ""
            if not cont:
                return

    # -- nodes ------------------------------------------------------------
    def get_node(self, name: str) -> Node:
        return node_from_json(self._request("GET", f"/api/v1/nodes/{name}"))

    def list_nodes(self, label_selector: str = "") -> list[Node]:
        return [node_from_json(obj) for obj in
                self._list("/api/v1/nodes", label_selector)]

    def patch_node_labels(self, name: str,
                          labels: Mapping[str, Optional[str]]) -> Node:
        return self._patch_node_meta(name, "labels", labels)

    def patch_node_annotations(
            self, name: str,
            annotations: Mapping[str, Optional[str]]) -> Node:
        return self._patch_node_meta(name, "annotations", annotations)

    def _patch_node_meta(self, name: str, field: str,
                         values: Mapping[str, Optional[str]]) -> Node:
        # merge-patch: null deletes the key, untouched keys survive —
        # the same raw patch the reference sends
        # (node_upgrade_state_provider.go:80-82,147-151)
        body = {"metadata": {field: dict(values)}}
        return node_from_json(self._request(
            "PATCH", f"/api/v1/nodes/{name}", body, _MERGE_PATCH))

    def patch_node_meta(self, name: str,
                        labels: Optional[Mapping[str, Optional[str]]] = None,
                        annotations: Optional[Mapping[str, Optional[str]]]
                        = None) -> Node:
        # the coalesced-write path: labels + annotations in ONE
        # merge-patch request — crash-atomic and half the round trips
        # of the split patches the base-class fallback issues
        meta: dict = {}
        if labels:
            meta["labels"] = dict(labels)
        if annotations:
            meta["annotations"] = dict(annotations)
        if not meta:
            return self.get_node(name)
        return node_from_json(self._request(
            "PATCH", f"/api/v1/nodes/{name}", {"metadata": meta},
            _MERGE_PATCH))

    def set_node_unschedulable(self, name: str,
                               unschedulable: bool) -> Node:
        return node_from_json(self._request(
            "PATCH", f"/api/v1/nodes/{name}",
            {"spec": {"unschedulable": unschedulable}}, _MERGE_PATCH))

    # -- pods -------------------------------------------------------------
    def list_pods(self, namespace: Optional[str] = None,
                  label_selector: str = "",
                  field_selector: str = "") -> list[Pod]:
        path = ("/api/v1/pods" if namespace is None
                else f"/api/v1/namespaces/{namespace}/pods")
        return [pod_from_json(obj) for obj in
                self._list(path, label_selector, field_selector)]

    def delete_pod(self, namespace: str, name: str) -> None:
        self._request("DELETE",
                      f"/api/v1/namespaces/{namespace}/pods/{name}")

    def evict_pod(self, namespace: str, name: str) -> None:
        # policy/v1 Eviction subresource; the apiserver answers 429 +
        # DisruptionBudget cause when a PDB forbids the eviction — only
        # HERE does 429 mean "blocked" rather than throttling
        self._request(
            "POST",
            f"/api/v1/namespaces/{namespace}/pods/{name}/eviction",
            {"apiVersion": "policy/v1", "kind": "Eviction",
             "metadata": {"name": name, "namespace": namespace}},
            eviction=True)

    # -- daemonsets & revisions ------------------------------------------
    def list_daemon_sets(self, namespace: str,
                         label_selector: str = "") -> list[DaemonSet]:
        return [daemon_set_from_json(obj) for obj in self._list(
            f"/apis/apps/v1/namespaces/{namespace}/daemonsets",
            label_selector)]

    def list_controller_revisions(
            self, namespace: str,
            label_selector: str = "") -> list[ControllerRevision]:
        return [controller_revision_from_json(obj) for obj in self._list(
            f"/apis/apps/v1/namespaces/{namespace}/controllerrevisions",
            label_selector)]

    def patch_daemon_set_annotations(
            self, namespace: str, name: str,
            annotations: Mapping[str, Optional[str]]) -> DaemonSet:
        # same raw merge-patch shape as the node metadata writes: null
        # deletes the key, untouched keys survive (the RolloutGuard's
        # quarantine/bake stamps ride this)
        body = {"metadata": {"annotations": dict(annotations)}}
        return daemon_set_from_json(self._request(
            "PATCH",
            f"/apis/apps/v1/namespaces/{namespace}/daemonsets/{name}",
            body, _MERGE_PATCH))

    # -- events -----------------------------------------------------------
    def upsert_event(self, namespace: str, name: str,
                     event: object) -> None:
        """POST the named Event; on 409 (exists) PATCH count/message/
        lastTimestamp — client-go broadcaster semantics (the PATCH-first
        LRU optimization lives in the RealCluster adapter; this minimal
        backend favors wire simplicity)."""
        import time as _time

        def ts(epoch: float) -> str:
            return _time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                  _time.gmtime(epoch))

        path = f"/api/v1/namespaces/{namespace}/events"
        body = {
            "metadata": {"name": name, "namespace": namespace},
            "involvedObject": {"kind": event.kind,
                               "name": event.object_name},
            "type": event.type, "reason": event.reason,
            "message": event.message, "count": event.count,
            "firstTimestamp": ts(event.first_seen),
            "lastTimestamp": ts(event.last_seen),
        }
        try:
            self._request("POST", path, body)
            return
        except ConflictError:
            pass
        try:
            self._request(
                "PATCH", f"{path}/{name}",
                {"count": event.count, "message": event.message,
                 "lastTimestamp": ts(event.last_seen)}, _MERGE_PATCH)
        except NotFoundError:
            # TTL-collected between the 409 and the PATCH; re-create
            self._request("POST", path, body)

    # -- coordination.k8s.io Leases (leader election) ---------------------
    def _remember_lease_meta(self, raw: dict) -> dict:
        meta = raw.get("metadata") or {}
        self._lease_raw_meta[(meta.get("namespace", ""),
                              meta.get("name", ""))] = dict(meta)
        return raw

    def get_lease(self, namespace: str, name: str) -> Lease:
        return lease_from_json(self._remember_lease_meta(self._request(
            "GET", f"/apis/coordination.k8s.io/v1/namespaces/"
                   f"{namespace}/leases/{name}")))

    def create_lease(self, lease: Lease) -> Lease:
        try:
            return lease_from_json(self._remember_lease_meta(
                self._request(
                    "POST", f"/apis/coordination.k8s.io/v1/namespaces/"
                            f"{lease.metadata.namespace}/leases",
                    _lease_to_json(lease, with_version=False))))
        except ConflictError as exc:
            # 409 on POST = already exists (the acquire race the
            # elector retries after)
            raise AlreadyExistsError(str(exc)) from exc

    def update_lease(self, lease: Lease) -> Lease:
        """PUT with the caller's resourceVersion: the apiserver's
        optimistic-concurrency check is the entire leader-election
        safety story — a stale holder's renew must 409. The replace
        body carries the last-read wire metadata so renews never strip
        labels/annotations/ownerReferences."""
        key = (lease.metadata.namespace, lease.metadata.name)
        return lease_from_json(self._remember_lease_meta(self._request(
            "PUT", f"/apis/coordination.k8s.io/v1/namespaces/"
                   f"{lease.metadata.namespace}/leases/"
                   f"{lease.metadata.name}",
            _lease_to_json(lease, with_version=True,
                           base_meta=self._lease_raw_meta.get(key)))))

    # -- watches ----------------------------------------------------------
    def watch(self, kinds: Optional[set[str]] = None,
              namespace: Optional[str] = None) -> Watch:
        """One streaming GET per watched kind, demuxed into a single
        Watch (the controller runtime's informer source)."""
        wanted = kinds or {KIND_NODE, KIND_POD, KIND_DAEMON_SET}
        paths = {}
        if KIND_NODE in wanted:
            paths[KIND_NODE] = "/api/v1/nodes"
        if KIND_POD in wanted:
            paths[KIND_POD] = ("/api/v1/pods" if namespace is None else
                               f"/api/v1/namespaces/{namespace}/pods")
        if KIND_DAEMON_SET in wanted:
            ns = namespace or "default"
            paths[KIND_DAEMON_SET] = \
                f"/apis/apps/v1/namespaces/{ns}/daemonsets"
        watch = Watch()
        for kind, path in paths.items():
            thread = threading.Thread(
                target=self._watch_stream, args=(kind, path, watch),
                name=f"http-watch-{kind}", daemon=True)
            thread.start()
            self._watch_threads.append(thread)
        return watch

    def _watch_stream(self, kind: str, path: str, watch: Watch) -> None:
        """One kind's watch loop: stream, and RECONNECT when the server
        drops the connection.

        Real apiservers close watch streams routinely (connection
        timeouts, resourceVersion compaction); client-go's reflector
        answers by re-list + re-watch. Same here: after a drop the
        stream reconnects with capped exponential backoff, and each
        RE-connect replays a full LIST as MODIFIED events so the
        informer caches repair whatever changed during the gap (a
        silent dead watch would otherwise starve the controller of
        events forever). Limitation, by design: deletions that happened
        during the gap are not synthesized (this layer has no cache to
        diff against) — the controller's ``resync_period`` remains the
        backstop for those, exactly the role client-go gives resync.
        """
        parse = _KIND_PARSERS[kind]
        ctx = self._ssl if self._base.startswith("https") else None
        backoff = 1.0
        first = True
        while not watch.stopped:
            if self._rate_limiter is not None:
                self._rate_limiter.wait()  # charge the (re)establish
            req = urllib.request.Request(
                f"{self._base}{path}?watch=true")
            req.add_header("Accept", _JSON)
            if self._token:
                req.add_header("Authorization",
                               f"Bearer {self._token}")
            try:
                with urllib.request.urlopen(req, timeout=None,
                                            context=ctx) as resp:
                    if not first:
                        logger.info("watch stream %s reconnected; "
                                    "replaying LIST", kind)
                        for obj in self._list(path):
                            if watch.stopped:
                                return
                            watch._deliver(
                                WatchEvent(MODIFIED, kind, parse(obj)))
                    streamed = False
                    for raw in resp:
                        if watch.stopped:
                            return
                        line = raw.strip()
                        if not line:
                            continue
                        try:
                            evt = json.loads(line)
                        except json.JSONDecodeError:
                            continue
                        if evt.get("type") not in (ADDED, MODIFIED,
                                                   DELETED):
                            continue
                        if not streamed:
                            # the stream proved healthy (an actual
                            # event arrived) — only now reset backoff.
                            # Resetting on mere connect would let a
                            # server whose watch endpoint drops
                            # instantly (but serves LISTs fine) induce
                            # a full re-LIST per second forever.
                            streamed = True
                            backoff = 1.0
                        # WatchEvent carries a typed snapshot, exactly
                        # like FakeCluster's broadcaster
                        watch._deliver(WatchEvent(
                            evt["type"], kind,
                            parse(evt.get("object") or {})))
            except Exception as exc:  # noqa: BLE001 — thread boundary:
                # ANY escape kills the daemon thread and the watch goes
                # silently deaf (urllib raises URLError/OSError, the
                # chunked reader http.client.IncompleteRead, the replay
                # LIST any client-seam error incl. 429/404 mappings) —
                # every one of them must land in backoff-and-retry
                if watch.stopped:
                    return
                logger.warning("watch stream %s dropped (%s); "
                               "reconnecting in %.0fs", kind, exc,
                               backoff)
            first = False
            # jittered (uniform half-to-full) so a fleet of operators
            # whose watches died together does not re-list in lockstep
            self._sleep(backoff * random.uniform(0.5, 1.0))
            backoff = min(backoff * 2.0, 30.0)
