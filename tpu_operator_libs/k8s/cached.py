"""Informer-backed cached read client.

The reference's hot loop reads through a controller-runtime cached
``client.Client`` (created at upgrade_state.go:127) while writes go
straight to the apiserver — which is why ``ChangeNodeUpgradeState`` must
poll its own cache until a patch becomes visible
(node_upgrade_state_provider.go:100-117). This module is that substrate,
built on this repo's own informers:

- **Reads** (`get_node`, `list_nodes`, `list_pods`, `list_daemon_sets`)
  are served from list+watch :class:`~tpu_operator_libs.controller.Informer`
  caches — zero API traffic per reconcile once synced.
- **Writes** (patches, cordon, delete, evict) pass through to the
  delegate client AND apply their returned result to the cache
  immediately (read-your-writes): NodeUpgradeStateProvider's read-back
  poll degenerates to a no-wait check, so a transition wave pipelines
  instead of each write blocking on the watch round-trip. Third-party
  writes remain *eventually* consistent via the watch stream, exactly
  the staleness contract the read-back poll exists to absorb.
- **ControllerRevisions** are delegate-read but cached keyed on the
  DaemonSet cache's change generation: the watch plane does not carry
  revisions, but a new revision only ever appears alongside a DS
  update, so any DS event invalidates. The revision oracle's
  steady-state read therefore costs zero API calls.
- A **node→pods index** (:class:`NodePodIndex`) and per-consumer
  **delta views** (:meth:`CachedReadClient.delta_view`) ride the
  informer handler chain: the index serves ``spec.nodeName`` field
  selectors without scanning, and the views let ``build_state`` patch
  its previous snapshot instead of re-reading the cluster — O(delta)
  per pass instead of O(cluster), falling back to a full rebuild only
  on the first poll or after a resync.

Use :meth:`CachedReadClient.has_synced` as the start-up barrier before
the first reconcile, mirroring controller-runtime's
``mgr.GetCache().WaitForCacheSync``.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Mapping, Optional

from tpu_operator_libs.k8s.client import K8sClient, NotFoundError
from tpu_operator_libs.k8s.objects import (
    ControllerRevision,
    DaemonSet,
    Node,
    Pod,
)
from tpu_operator_libs.k8s.selectors import (
    exact_field_requirement,
    parse_field_selector,
    parse_label_selector,
)
from tpu_operator_libs.k8s.watch import (
    KIND_DAEMON_SET,
    KIND_NODE,
    KIND_POD,
    Watch,
)


logger = logging.getLogger(__name__)


class CacheNotSyncedError(RuntimeError):
    """A read was attempted before the initial list completed."""


class ShardPartitionFilter:
    """Shard-ownership ingest predicate for the pod cache.

    Applied at watch-event ingest (and to list results) by the pod
    informer, so a sharded replica's pod store, node→pods index, delta
    cursors and incremental rebuilds only ever hold the slices its
    shard view owns — the client-side stand-in for the per-partition
    LIST/watch pushdown a real deployment would express as a selector.
    The predicate consults the live shard view, so ownership changes
    take effect immediately for new events; objects dropped BEFORE an
    acquisition are repaired by the targeted re-LIST
    (:meth:`CachedReadClient.refresh_partition`).

    Fail-open by design: a pod with no node binding, or whose node the
    node cache has not seen yet (so its pool — the slice-whole hash key
    — is unknown), is KEPT. Dropping only provably-unowned pods means a
    racing node sync can cost memory, never a hole in the owned
    partition; the state manager applies the exact ownership check
    again at snapshot assembly.
    """

    def __init__(self, view: object,
                 node_lookup: Callable[[str], object],
                 pool_label: Optional[str] = None) -> None:
        from tpu_operator_libs.consts import GKE_NODEPOOL_LABEL

        #: ShardElector / StaticShardView: anything with owns(name, pool).
        self.view = view
        self._node_lookup = node_lookup
        self._pool_label = pool_label or GKE_NODEPOOL_LABEL
        #: Ingest accounting (the partition-scaling evidence): events /
        #: listed objects kept into the cache vs dropped at the door.
        self.kept_total = 0
        self.dropped_total = 0

    def __call__(self, obj: object) -> bool:
        node_name = getattr(getattr(obj, "spec", None), "node_name", "")
        if not node_name:
            self.kept_total += 1
            return True
        node = self._node_lookup(node_name)
        if node is None:
            self.kept_total += 1
            return True
        pool = node.metadata.labels.get(self._pool_label, "")
        if self.view.owns(node_name, pool):
            self.kept_total += 1
            return True
        self.dropped_total += 1
        return False


class NodePodIndex:
    """node name → pods, maintained from the pod informer's watch deltas.

    The apiserver serves ``spec.nodeName`` field selectors from an
    index; a cached client must too, or a fleet-wide drain wave's
    pods-on-node queries degenerate to O(pods) scans per node. The
    index is wired as an ordinary informer event handler, so every
    repair path the informer has (watch replay after a drop, overflow
    BOOKMARK relist, periodic relist, write-through applies) updates it
    for free — there is no second consistency protocol to get wrong.
    Pods with no ``spec.nodeName`` (unscheduled) are not indexed; node
    binding is immutable in Kubernetes, but a changed binding is
    tolerated anyway (the stale entry is unlinked first).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._by_node: dict[str, dict[tuple[str, str], Pod]] = {}
        self._node_of: dict[tuple[str, str], str] = {}

    # -- informer handlers -------------------------------------------------
    def on_add(self, obj: object) -> None:
        self._link(obj)

    def on_update(self, _old: object, new: object) -> None:
        self._link(new)

    def on_delete(self, obj: object) -> None:
        meta = getattr(obj, "metadata", None)
        if meta is None:
            return
        self._unlink((meta.namespace, meta.name))

    def _link(self, obj: object) -> None:
        pod = obj  # type: Pod
        key = (pod.metadata.namespace, pod.metadata.name)
        node = pod.spec.node_name
        with self._lock:
            previous = self._node_of.get(key)
            if previous is not None and previous != node:
                members = self._by_node.get(previous)
                if members is not None:
                    members.pop(key, None)
                    if not members:
                        del self._by_node[previous]
            if not node:
                self._node_of.pop(key, None)
                return
            self._node_of[key] = node
            self._by_node.setdefault(node, {})[key] = pod

    def _unlink(self, key: tuple[str, str]) -> None:
        with self._lock:
            node = self._node_of.pop(key, None)
            if node is None:
                return
            members = self._by_node.get(node)
            if members is not None:
                members.pop(key, None)
                if not members:
                    del self._by_node[node]

    # -- reads -------------------------------------------------------------
    def pods_on(self, node_name: str) -> list[Pod]:
        """Snapshot copies of the pods bound to ``node_name``."""
        with self._lock:
            return [p.clone()
                    for p in self._by_node.get(node_name, {}).values()]

    def node_count(self) -> int:
        with self._lock:
            return len(self._by_node)

    def __len__(self) -> int:
        with self._lock:
            return len(self._node_of)


@dataclass
class ClusterDelta:
    """What changed since a view's previous poll."""

    full: bool = False            # consumer must rebuild from scratch
    daemon_sets: bool = False     # any DaemonSet add/update/delete
    nodes: set = field(default_factory=set)            # node names
    pods: set = field(default_factory=set)             # (ns, name) keys

    def empty(self) -> bool:
        return not (self.full or self.daemon_sets
                    or self.nodes or self.pods)


class ClusterDeltaView:
    """One consumer's cursor over the cache's change stream.

    Every informer apply (watch event, relist repair, write-through)
    marks the touched object dirty in every registered view;
    :meth:`poll` hands the accumulated delta to the consumer and resets
    it. The very first poll reports ``full=True`` — the consumer has no
    prior snapshot to patch. Dirty sets are bounded by the object count
    (sets dedup), so an idle consumer cannot leak.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._delta = ClusterDelta(full=True)

    # -- producer (cache) --------------------------------------------------
    def mark_node(self, name: str) -> None:
        with self._lock:
            self._delta.nodes.add(name)

    def mark_pod(self, key: tuple[str, str]) -> None:
        with self._lock:
            self._delta.pods.add(key)

    def mark_daemon_sets(self) -> None:
        with self._lock:
            self._delta.daemon_sets = True

    def mark_full(self) -> None:
        with self._lock:
            self._delta.full = True

    # -- consumer ----------------------------------------------------------
    def poll(self) -> ClusterDelta:
        with self._lock:
            delta, self._delta = self._delta, ClusterDelta()
            return delta


class CachedReadClient(K8sClient):
    """K8sClient whose reads come from informer caches.

    ``namespace`` scopes the pod and DaemonSet caches (the upgrade flow
    is single-namespace, like the reference consumer's driver
    namespace); nodes are cluster-scoped. The delegate must support
    :meth:`K8sClient.watch`.
    """

    def __init__(self, delegate: K8sClient, namespace: str,
                 require_sync: bool = True,
                 relist_interval: Optional[float] = 300.0,
                 threaded: bool = True,
                 partition_view: Optional[object] = None,
                 shard_selector_fn: Optional[Callable[[], str]] = None,
                 ) -> None:
        # Deferred: controller.py imports k8s.watch, whose package
        # __init__ re-exports this module — a top-level import of
        # controller here would be circular for any consumer that
        # imports tpu_operator_libs.controller first.
        from tpu_operator_libs.controller import Informer

        self._delegate = delegate
        self._namespace = namespace
        self._require_sync = require_sync
        self._threaded = threaded
        self._counters_lock = threading.Lock()
        #: API calls this client actually forwarded to the delegate
        #: (cache misses + writes + informer lists); cache hits cost
        #: zero. Exported by metrics.observe_reconcile/observe_shards.
        self.api_reads_total = 0
        self.api_writes_total = 0
        #: Objects the delegate returned across every forwarded read
        #: (len of each LIST + 1 per GET): the wire-volume half of the
        #: O(partition) claim — a call count alone hides that one LIST
        #: can carry the whole fleet.
        self.read_objects_total = 0
        #: Forwarded LIST calls by cache kind, and specifically the
        #: namespace-wide pod LISTs (initial sync, relist repairs,
        #: partition refreshes): the bench pins these at ZERO in steady
        #: state — every steady-state read rides the watch stream.
        self.list_calls: dict[str, int] = {}
        self.pod_full_lists_total = 0
        #: Targeted pod-cache relists performed for shard
        #: acquisitions/handovers: the only legitimate source of a
        #: post-sync namespace-wide pod LIST — kind_smoke's per-replica
        #: read bound is ``podFullLists <= 1 (sync) + refreshes``.
        self.partition_refreshes_total = 0
        # Partition pushdown (sharded replicas): pods outside the
        # view's owned shards are dropped at ingest, so the pod store /
        # index / delta cursors are O(partition), not O(fleet). The
        # node cache stays fleet-wide — node metadata is the one
        # deliberate O(fleet) object (the cheap fleet summary feed).
        self._partition_filter: Optional[ShardPartitionFilter] = None
        if partition_view is not None:
            self._partition_filter = ShardPartitionFilter(
                partition_view,
                lambda name: self._nodes.get("", name))
        # Server-side watch sharding: with a selector factory installed
        # the POD cache's LIST and WATCH both carry the current shard
        # selector, so the apiserver filters the stream to the owned
        # partition — per-replica watch traffic and relist volume drop
        # to O(partition) instead of "ingest the fleet, drop the rest".
        # The client-side partition filter stays installed as the
        # authoritative (fail-open) backstop: a pod whose stamp lags an
        # ownership move is still judged against the live view. Pump
        # mode only — a selector swap re-subscribes the pod watch,
        # which a threaded informer's run loop cannot survive.
        self._shard_selector_fn = shard_selector_fn
        if shard_selector_fn is not None and threaded:
            raise ValueError(
                "shard_selector_fn requires threaded=False: selector "
                "handover re-subscribes the pod watch via "
                "Informer.resubscribe(), a pump-mode-only operation")
        self._pod_watch_selector = (shard_selector_fn()
                                    if shard_selector_fn is not None
                                    else "")
        self._nodes = Informer(
            self._counted_lister("nodes", delegate.list_nodes),
            delegate.watch(kinds={KIND_NODE}),
            name="node-cache", threaded=threaded,
            rewatch=lambda: delegate.watch(kinds={KIND_NODE}))
        # the lister/rewatch helpers read the CURRENT selector at call
        # time: a post-handover relist or re-subscription is filtered
        # to the new partition without rebuilding the informer
        self._pods = Informer(
            self._counted_lister(
                "pods",
                lambda: self._list_pods_for_cache(namespace)),
            self._pod_watch(namespace),
            name="pod-cache", threaded=threaded,
            ingest_filter=self._partition_filter,
            rewatch=lambda: self._pod_watch(namespace))
        self._daemon_sets = Informer(
            self._counted_lister(
                "daemon_sets",
                lambda: delegate.list_daemon_sets(namespace)),
            delegate.watch(kinds={KIND_DAEMON_SET}, namespace=namespace),
            name="ds-cache", threaded=threaded,
            rewatch=lambda: delegate.watch(kinds={KIND_DAEMON_SET},
                                           namespace=namespace))
        self._informers = (self._nodes, self._pods, self._daemon_sets)
        # node→pods index + delta fan-out ride the informer handler
        # chain, BEFORE start(): initial-sync adds must flow through
        # them too. Handler order matters — the index applies first so
        # a delta-marked pod is already resolvable through the index.
        self._pod_index = NodePodIndex()
        self._pods.add_event_handler(on_add=self._pod_index.on_add,
                                     on_update=self._pod_index.on_update,
                                     on_delete=self._pod_index.on_delete)
        self._views: list[ClusterDeltaView] = []
        self._views_lock = threading.Lock()
        # ControllerRevision lists, cached keyed on the DS cache's
        # change generation: a new revision only ever appears alongside
        # a DaemonSet template update (a MODIFIED event), so any DS
        # event invalidates. This removes the one remaining per-pass
        # LIST the revision oracle issues in steady state — and is MORE
        # snapshot-consistent than the uncached read, which could see
        # revisions newer than the DS snapshot mid-pass.
        self._revisions_gen = 0
        self._revisions_cache: dict[tuple[str, str],
                                    tuple[int, list[ControllerRevision]]] = {}
        self._nodes.add_event_handler(
            on_add=lambda obj: self._mark_node(obj),
            on_update=lambda _old, new: self._mark_node(new),
            on_delete=lambda obj: self._mark_node(obj))
        self._pods.add_event_handler(
            on_add=lambda obj: self._mark_pod(obj),
            on_update=lambda _old, new: self._mark_pod(new),
            on_delete=lambda obj: self._mark_pod(obj))
        self._daemon_sets.add_event_handler(
            on_add=lambda obj: self._mark_ds(),
            on_update=lambda _old, new: self._mark_ds(),
            on_delete=lambda obj: self._mark_ds())
        for informer in self._informers:
            informer.start()
        # A restarted live watch re-delivers current objects but never
        # DELETEDs lost in the stream gap; periodic relist (Reflector
        # Replace) prunes such ghosts so e.g. _wait_for_delete cannot
        # spin on a pod that terminated during the gap. With
        # relist_interval=None ghost objects persist until a manual
        # refresh(); deletion tombstones stay bounded either way (the
        # informer TTL-prunes them on delete, controller._TOMBSTONE_TTL).
        self._stop_relist = threading.Event()
        self._relist_thread: Optional[threading.Thread] = None
        if threaded and relist_interval is not None and relist_interval > 0:
            self._relist_thread = threading.Thread(
                target=self._relist_loop, args=(relist_interval,),
                name="cache-relist", daemon=True)
            self._relist_thread.start()

    # -- delta plumbing ---------------------------------------------------
    def _mark_node(self, obj: object) -> None:
        name = getattr(getattr(obj, "metadata", None), "name", None)
        if name is None:
            return
        with self._views_lock:
            for view in self._views:
                view.mark_node(name)

    def _mark_pod(self, obj: object) -> None:
        meta = getattr(obj, "metadata", None)
        if meta is None:
            return
        key = (meta.namespace, meta.name)
        with self._views_lock:
            for view in self._views:
                view.mark_pod(key)

    def _mark_ds(self) -> None:
        with self._views_lock:
            self._revisions_gen += 1
            self._revisions_cache.clear()
            for view in self._views:
                view.mark_daemon_sets()

    def delta_view(self) -> ClusterDeltaView:
        """Register a new change-stream cursor (first poll reports a
        full resync). The state manager's incremental build_state is
        the intended consumer; each consumer gets its own view."""
        view = ClusterDeltaView()
        with self._views_lock:
            self._views.append(view)
        return view

    @property
    def pod_index(self) -> NodePodIndex:
        """The watch-delta-maintained node→pods index."""
        return self._pod_index

    def _count_read(self, objects: int = 1) -> None:
        with self._counters_lock:
            self.api_reads_total += 1
            self.read_objects_total += objects

    def _count_write(self) -> None:
        with self._counters_lock:
            self.api_writes_total += 1

    def _counted_lister(self, kind: str,
                        fn: Callable[[], list]) -> Callable[[], list]:
        """Wrap an informer lister so the initial sync and every relist
        repair are billed like any other delegate read — the bench's
        per-replica accounting must see the O(fleet) LISTs a takeover
        costs, not just steady-state cache misses."""
        def lister() -> list:
            objects = fn()
            with self._counters_lock:
                self.api_reads_total += 1
                self.read_objects_total += len(objects)
                self.list_calls[kind] = self.list_calls.get(kind, 0) + 1
                if kind == "pods":
                    self.pod_full_lists_total += 1
            return objects
        return lister

    # -- partition pushdown (sharded replicas) ----------------------------
    def _list_pods_for_cache(self, namespace: str) -> list:
        """Pod-cache lister: shard-selector filtered when server-side
        watch sharding is on (the delegate only returns the partition),
        namespace-wide otherwise. Kwarg-gated so delegates predating
        the ``label_selector`` watch/list parameter keep working."""
        if self._shard_selector_fn is None:
            return self._delegate.list_pods(namespace=namespace)
        return self._delegate.list_pods(
            namespace=namespace,
            label_selector=self._pod_watch_selector)

    def _pod_watch(self, namespace: str):
        if self._shard_selector_fn is None:
            return self._delegate.watch(kinds={KIND_POD},
                                        namespace=namespace)
        return self._delegate.watch(
            kinds={KIND_POD}, namespace=namespace,
            label_selector=self._pod_watch_selector)

    def set_partition_filter(self, view: Optional[object]) -> None:
        """Install (or clear, with ``None``) the shard-partition filter
        on the pod cache. Prefer the ``partition_view`` constructor
        argument — installing before the initial list keeps the first
        sync O(partition) too; installing later re-LISTs the pod cache
        once to rewrite it under the new predicate."""
        if view is None:
            self._partition_filter = None
            self._pods.set_ingest_filter(None)
        else:
            self._partition_filter = ShardPartitionFilter(
                view, lambda name: self._nodes.get("", name))
            self._pods.set_ingest_filter(self._partition_filter)
        if self._pods.has_synced(timeout=0):
            self.refresh_partition()

    @property
    def partition_filter(self) -> Optional[ShardPartitionFilter]:
        return self._partition_filter

    def refresh_partition(self) -> None:
        """Targeted re-LIST after a shard acquisition/handover: only the
        POD cache is rebuilt (nodes and DaemonSets are fleet-scoped and
        never partition-filtered). Watch events for newly-acquired
        shards that arrived before the acquisition were dropped at
        ingest — gone, not replayable — so the relist is what makes a
        takeover's first snapshot bit-identical to the deposed owner's.
        The caller should also invalidate its delta cursor
        (``ClusterDeltaView.mark_full``); the relist emits add/delete
        handler events for changed keys only, and a consumer patching a
        partial previous snapshot must not trust its unchanged entries
        across an ownership move.

        Under server-side watch sharding this is also the crash-ordered
        selector-handover point: the selector factory is re-evaluated,
        and a changed selector re-subscribes the pod watch BEFORE the
        relist — the caller (the state manager's ownership-move branch)
        has already re-stamped the newly-owned partition by the time it
        calls here, so the narrowed/widened stream misses nothing and
        the relist both fills the new partition and retires the old
        one's cached pods."""
        with self._counters_lock:
            self.partition_refreshes_total += 1
        fn = self._shard_selector_fn
        if fn is not None:
            selector = fn()
            if selector != self._pod_watch_selector:
                self._pod_watch_selector = selector
                self._pods.resubscribe()
        self._pods.refresh()

    def pump(self) -> int:
        """Apply all queued watch events inline (unthreaded clients
        only) and return how many were applied. Node events first: the
        pod partition filter resolves pool labels through the node
        cache, so a pod event must never be judged against a node
        update still sitting in the queue behind it."""
        total = 0
        for informer in self._informers:
            total += informer.pump()
        return total

    def read_accounting(self) -> dict:
        """Snapshot of the per-replica read/write accounting the shard
        bench and ``cluster_status`` report."""
        with self._counters_lock:
            out = {
                "apiReadsTotal": self.api_reads_total,
                "apiWritesTotal": self.api_writes_total,
                "readObjectsTotal": self.read_objects_total,
                "podFullLists": self.pod_full_lists_total,
                "partitionRefreshes": self.partition_refreshes_total,
                "listCalls": dict(self.list_calls),
                "cachedPods": len(self._pods),
                "cachedNodes": len(self._nodes),
            }
        if self._partition_filter is not None:
            out["ingestKept"] = self._partition_filter.kept_total
            out["ingestDropped"] = self._partition_filter.dropped_total
        return out

    # -- lifecycle --------------------------------------------------------
    def has_synced(self, timeout: Optional[float] = None) -> bool:
        """True once every cache finished its initial list
        (WaitForCacheSync analogue). ``timeout`` is one shared budget
        across all caches, not per cache."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        for informer in self._informers:
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            if not informer.has_synced(timeout=remaining):
                return False
        return True

    def refresh(self) -> None:
        """Force one relist-and-replace of every cache."""
        for informer in self._informers:
            informer.refresh()

    def add_event_handler(
            self, on_change: Callable[[object], None]) -> None:
        """``on_change(obj)`` after any add/update/delete is APPLIED to a
        cache. Wiring reconcile triggers here (rather than to a raw
        watch) guarantees a triggered reconcile reads a cache that
        already contains the triggering event."""
        for informer in self._informers:
            informer.add_event_handler(
                on_add=on_change,
                on_update=lambda _old, new: on_change(new),
                on_delete=on_change)

    def _relist_loop(self, interval: float) -> None:
        while not self._stop_relist.wait(interval):
            try:
                self.refresh()
            except Exception:
                logger.exception("periodic cache relist failed; next "
                                 "interval retries")

    def stop(self) -> None:
        self._stop_relist.set()
        for informer in self._informers:
            informer.stop()
        if self._relist_thread is not None:
            self._relist_thread.join(timeout=5.0)

    def _barrier(self) -> None:
        if self._require_sync and not self.has_synced(timeout=0):
            raise CacheNotSyncedError(
                "cache read before initial sync; call has_synced() first")

    # -- cached reads -----------------------------------------------------
    def get_node(self, name: str) -> Node:
        self._barrier()
        node = self._nodes.get("", name)
        if node is None:
            raise NotFoundError(f"node {name!r} not found")
        return node.clone()

    def list_nodes(self, label_selector: str = "") -> list[Node]:
        self._barrier()
        match = parse_label_selector(label_selector)
        return [n.clone() for n in self._nodes.list()
                if match(n.metadata.labels)]

    def list_pods(self, namespace: Optional[str] = None,
                  label_selector: str = "",
                  field_selector: str = "") -> list[Pod]:
        self._barrier()
        if namespace != self._namespace:
            # None/"" mean ALL namespaces (pod_manager.go:323-331), and
            # the drain/eviction/validation paths rely on that to see
            # workload pods outside the operator namespace — the
            # single-namespace cache cannot answer those queries.
            pods = self._delegate.list_pods(namespace, label_selector,
                                            field_selector)
            self._count_read(len(pods))
            return pods
        label_match = parse_label_selector(label_selector)
        node = exact_field_requirement(field_selector, "spec.nodeName")
        if node:
            # indexed pods-on-node path (the apiserver's own indexed
            # field selector); full matchers still apply, so semantics
            # are unchanged — only the candidate set narrows
            field_match = parse_field_selector(field_selector)
            return [p for p in self._pod_index.pods_on(node)
                    if label_match(p.metadata.labels)
                    and field_match(p.field_map())]
        field_match = parse_field_selector(field_selector)
        return [p.clone() for p in self._pods.list()
                if label_match(p.metadata.labels)
                and field_match(p.field_map())]

    def get_pod(self, namespace: str, name: str) -> Pod:
        self._barrier()
        if namespace != self._namespace:
            self._count_read()
            return self._delegate.get_pod(namespace, name)
        pod = self._pods.get(namespace, name)
        if pod is None:
            raise NotFoundError(f"pod {namespace}/{name} not found")
        return pod.clone()

    def list_daemon_sets(self, namespace: str,
                         label_selector: str = "") -> list[DaemonSet]:
        self._barrier()
        if namespace != self._namespace:
            out = self._delegate.list_daemon_sets(namespace, label_selector)
            self._count_read(len(out))
            return out
        match = parse_label_selector(label_selector)
        return [d.clone() for d in self._daemon_sets.list()
                if match(d.metadata.labels)]

    # -- revision reads (delegate-backed, DS-generation cached) -----------
    def list_controller_revisions(self, namespace: str,
                                  label_selector: str = "") -> list[ControllerRevision]:
        # The watch plane does not carry ControllerRevisions, so they
        # cannot be informer-cached — but a new revision only appears
        # together with a DaemonSet update, so the result is valid for
        # as long as the DS cache sees no event. Keyed on that change
        # generation, the revision oracle's steady-state read costs
        # zero API calls; any DS event (including relist repairs after
        # a watch gap) invalidates everything.
        with self._views_lock:
            gen = self._revisions_gen
            cached = self._revisions_cache.get((namespace, label_selector))
            if cached is not None and cached[0] == gen:
                return [r.clone() for r in cached[1]]
        revisions = self._delegate.list_controller_revisions(
            namespace, label_selector)
        self._count_read(len(revisions))
        with self._views_lock:
            if self._revisions_gen == gen:
                self._revisions_cache[(namespace, label_selector)] = (
                    gen, [r.clone() for r in revisions])
        return revisions

    # -- writes (pass through + read-your-writes cache apply) -------------
    # Each write's RESULT is applied to the informer store immediately
    # (Informer.apply_external): the provider's read-back poll becomes a
    # no-wait check and a transition wave pipelines instead of each
    # write blocking on the watch round-trip. The mutation's own watch
    # event lands later as an equal-value update.
    def patch_node_labels(self, name: str,
                          labels: Mapping[str, Optional[str]]) -> Node:
        self._count_write()
        node = self._delegate.patch_node_labels(name, labels)
        self._nodes.apply_external(node.clone())
        return node

    def patch_node_annotations(self, name: str,
                               annotations: Mapping[str, Optional[str]]) -> Node:
        self._count_write()
        node = self._delegate.patch_node_annotations(name, annotations)
        self._nodes.apply_external(node.clone())
        return node

    def patch_node_meta(self, name: str,
                        labels: Optional[Mapping[str, Optional[str]]] = None,
                        annotations: Optional[Mapping[str, Optional[str]]]
                        = None) -> Node:
        self._count_write()
        node = self._delegate.patch_node_meta(
            name, labels=labels, annotations=annotations)
        self._nodes.apply_external(node.clone())
        return node

    def set_node_unschedulable(self, name: str, unschedulable: bool) -> Node:
        self._count_write()
        node = self._delegate.set_node_unschedulable(name, unschedulable)
        self._nodes.apply_external(node.clone())
        return node

    def delete_pod(self, namespace: str, name: str) -> None:
        self._count_write()
        self._delegate.delete_pod(namespace, name)
        if namespace == self._namespace:
            self._pods.apply_external_delete(namespace, name)

    def evict_pod(self, namespace: str, name: str) -> None:
        self._count_write()
        self._delegate.evict_pod(namespace, name)
        if namespace == self._namespace:
            self._pods.apply_external_delete(namespace, name)

    def patch_daemon_set_annotations(
            self, namespace: str, name: str,
            annotations: Mapping[str, Optional[str]]) -> DaemonSet:
        self._count_write()
        ds = self._delegate.patch_daemon_set_annotations(
            namespace, name, annotations)
        if namespace == self._namespace:
            self._daemon_sets.apply_external(ds.clone())
        return ds

    def rollback_daemon_set(self, namespace: str, name: str,
                            revision_hash: str) -> None:
        # invalidation rides the DS watch event the rollback emits; the
        # revision-generation cache is bumped eagerly so the very next
        # oracle read sees the re-pinned ordering
        self._count_write()
        self._delegate.rollback_daemon_set(namespace, name, revision_hash)
        with self._views_lock:
            self._revisions_gen += 1
            self._revisions_cache.clear()

    def upsert_event(self, namespace: str, name: str,
                     event: object) -> None:
        # write pass-through like every other mutation: without this
        # delegation the event sink would self-disable behind the cache
        self._delegate.upsert_event(namespace, name, event)

    @property
    def delegate(self) -> K8sClient:
        """The wrapped write client (e.g. for reading its rate-limiter
        counters)."""
        return self._delegate

    # -- watches ----------------------------------------------------------
    def watch(self, kinds: Optional[set[str]] = None,
              namespace: Optional[str] = None) -> Watch:
        return self._delegate.watch(kinds=kinds, namespace=namespace)
