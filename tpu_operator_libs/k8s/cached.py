"""Informer-backed cached read client.

The reference's hot loop reads through a controller-runtime cached
``client.Client`` (created at upgrade_state.go:127) while writes go
straight to the apiserver — which is why ``ChangeNodeUpgradeState`` must
poll its own cache until a patch becomes visible
(node_upgrade_state_provider.go:100-117). This module is that substrate,
built on this repo's own informers:

- **Reads** (`get_node`, `list_nodes`, `list_pods`, `list_daemon_sets`)
  are served from list+watch :class:`~tpu_operator_libs.controller.Informer`
  caches — zero API traffic per reconcile once synced.
- **Writes** (patches, cordon, delete, evict) pass through to the
  delegate client; the cache catches up when the resulting watch event
  lands. Reads are therefore *eventually* consistent, exactly the
  staleness contract NodeUpgradeStateProvider's read-back poll exists
  to absorb.
- **ControllerRevisions** pass through uncached: they are immutable,
  read only by the revision oracle (one list per BuildState), and the
  watch plane does not carry them — the same shape as controller-runtime
  bypassing the cache for unregistered kinds.

Use :meth:`CachedReadClient.has_synced` as the start-up barrier before
the first reconcile, mirroring controller-runtime's
``mgr.GetCache().WaitForCacheSync``.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Mapping, Optional

from tpu_operator_libs.k8s.client import K8sClient, NotFoundError
from tpu_operator_libs.k8s.objects import (
    ControllerRevision,
    DaemonSet,
    Node,
    Pod,
)
from tpu_operator_libs.k8s.selectors import (
    parse_field_selector,
    parse_label_selector,
)
from tpu_operator_libs.k8s.watch import (
    KIND_DAEMON_SET,
    KIND_NODE,
    KIND_POD,
    Watch,
)


logger = logging.getLogger(__name__)


class CacheNotSyncedError(RuntimeError):
    """A read was attempted before the initial list completed."""


class CachedReadClient(K8sClient):
    """K8sClient whose reads come from informer caches.

    ``namespace`` scopes the pod and DaemonSet caches (the upgrade flow
    is single-namespace, like the reference consumer's driver
    namespace); nodes are cluster-scoped. The delegate must support
    :meth:`K8sClient.watch`.
    """

    def __init__(self, delegate: K8sClient, namespace: str,
                 require_sync: bool = True,
                 relist_interval: Optional[float] = 300.0) -> None:
        # Deferred: controller.py imports k8s.watch, whose package
        # __init__ re-exports this module — a top-level import of
        # controller here would be circular for any consumer that
        # imports tpu_operator_libs.controller first.
        from tpu_operator_libs.controller import Informer

        self._delegate = delegate
        self._namespace = namespace
        self._require_sync = require_sync
        self._nodes = Informer(
            delegate.list_nodes,
            delegate.watch(kinds={KIND_NODE}),
            name="node-cache")
        self._pods = Informer(
            lambda: delegate.list_pods(namespace=namespace),
            delegate.watch(kinds={KIND_POD}, namespace=namespace),
            name="pod-cache")
        self._daemon_sets = Informer(
            lambda: delegate.list_daemon_sets(namespace),
            delegate.watch(kinds={KIND_DAEMON_SET}, namespace=namespace),
            name="ds-cache")
        self._informers = (self._nodes, self._pods, self._daemon_sets)
        for informer in self._informers:
            informer.start()
        # A restarted live watch re-delivers current objects but never
        # DELETEDs lost in the stream gap; periodic relist (Reflector
        # Replace) prunes such ghosts so e.g. _wait_for_delete cannot
        # spin on a pod that terminated during the gap. With
        # relist_interval=None ghost objects persist until a manual
        # refresh(); deletion tombstones stay bounded either way (the
        # informer TTL-prunes them on delete, controller._TOMBSTONE_TTL).
        self._stop_relist = threading.Event()
        self._relist_thread: Optional[threading.Thread] = None
        if relist_interval is not None and relist_interval > 0:
            self._relist_thread = threading.Thread(
                target=self._relist_loop, args=(relist_interval,),
                name="cache-relist", daemon=True)
            self._relist_thread.start()

    # -- lifecycle --------------------------------------------------------
    def has_synced(self, timeout: Optional[float] = None) -> bool:
        """True once every cache finished its initial list
        (WaitForCacheSync analogue). ``timeout`` is one shared budget
        across all caches, not per cache."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        for informer in self._informers:
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            if not informer.has_synced(timeout=remaining):
                return False
        return True

    def refresh(self) -> None:
        """Force one relist-and-replace of every cache."""
        for informer in self._informers:
            informer.refresh()

    def add_event_handler(
            self, on_change: Callable[[object], None]) -> None:
        """``on_change(obj)`` after any add/update/delete is APPLIED to a
        cache. Wiring reconcile triggers here (rather than to a raw
        watch) guarantees a triggered reconcile reads a cache that
        already contains the triggering event."""
        for informer in self._informers:
            informer.add_event_handler(
                on_add=on_change,
                on_update=lambda _old, new: on_change(new),
                on_delete=on_change)

    def _relist_loop(self, interval: float) -> None:
        while not self._stop_relist.wait(interval):
            try:
                self.refresh()
            except Exception:
                logger.exception("periodic cache relist failed; next "
                                 "interval retries")

    def stop(self) -> None:
        self._stop_relist.set()
        for informer in self._informers:
            informer.stop()
        if self._relist_thread is not None:
            self._relist_thread.join(timeout=5.0)

    def _barrier(self) -> None:
        if self._require_sync and not self.has_synced(timeout=0):
            raise CacheNotSyncedError(
                "cache read before initial sync; call has_synced() first")

    # -- cached reads -----------------------------------------------------
    def get_node(self, name: str) -> Node:
        self._barrier()
        node = self._nodes.get("", name)
        if node is None:
            raise NotFoundError(f"node {name!r} not found")
        return node.clone()

    def list_nodes(self, label_selector: str = "") -> list[Node]:
        self._barrier()
        match = parse_label_selector(label_selector)
        return [n.clone() for n in self._nodes.list()
                if match(n.metadata.labels)]

    def list_pods(self, namespace: Optional[str] = None,
                  label_selector: str = "",
                  field_selector: str = "") -> list[Pod]:
        self._barrier()
        if namespace != self._namespace:
            # None/"" mean ALL namespaces (pod_manager.go:323-331), and
            # the drain/eviction/validation paths rely on that to see
            # workload pods outside the operator namespace — the
            # single-namespace cache cannot answer those queries.
            return self._delegate.list_pods(namespace, label_selector,
                                            field_selector)
        label_match = parse_label_selector(label_selector)
        field_match = parse_field_selector(field_selector)
        return [p.clone() for p in self._pods.list()
                if label_match(p.metadata.labels)
                and field_match(p.field_map())]

    def list_daemon_sets(self, namespace: str,
                         label_selector: str = "") -> list[DaemonSet]:
        self._barrier()
        if namespace != self._namespace:
            return self._delegate.list_daemon_sets(namespace, label_selector)
        match = parse_label_selector(label_selector)
        return [d.clone() for d in self._daemon_sets.list()
                if match(d.metadata.labels)]

    # -- uncached reads ---------------------------------------------------
    def list_controller_revisions(self, namespace: str,
                                  label_selector: str = "") -> list[ControllerRevision]:
        return self._delegate.list_controller_revisions(
            namespace, label_selector)

    # -- writes (pass through; cache catches up via watch events) ---------
    def patch_node_labels(self, name: str,
                          labels: Mapping[str, Optional[str]]) -> Node:
        return self._delegate.patch_node_labels(name, labels)

    def patch_node_annotations(self, name: str,
                               annotations: Mapping[str, Optional[str]]) -> Node:
        return self._delegate.patch_node_annotations(name, annotations)

    def set_node_unschedulable(self, name: str, unschedulable: bool) -> Node:
        return self._delegate.set_node_unschedulable(name, unschedulable)

    def delete_pod(self, namespace: str, name: str) -> None:
        self._delegate.delete_pod(namespace, name)

    def evict_pod(self, namespace: str, name: str) -> None:
        self._delegate.evict_pod(namespace, name)

    def upsert_event(self, namespace: str, name: str,
                     event: object) -> None:
        # write pass-through like every other mutation: without this
        # delegation the event sink would self-disable behind the cache
        self._delegate.upsert_event(namespace, name, event)

    @property
    def delegate(self) -> K8sClient:
        """The wrapped write client (e.g. for reading its rate-limiter
        counters)."""
        return self._delegate

    # -- watches ----------------------------------------------------------
    def watch(self, kinds: Optional[set[str]] = None,
              namespace: Optional[str] = None) -> Watch:
        return self._delegate.watch(kinds=kinds, namespace=namespace)
