"""Checkpoint-durability gate for evicting live JAX training jobs.

BASELINE config #4: during a rolling libtpu upgrade on a pool running a
JAX training Job, the pod-deletion state must verify the job's (Orbax)
checkpoint is durable before evicting — eviction then costs at most the
steps since the last commit, and the job resumes from checkpoint on a
fresh node.

The reference's insertion points are the ``PodDeletionFilter`` seam
(pod_manager.go:76) and ``WaitForCompletionSpec``; this module supplies the
gate itself plus the eviction-time hook PodManager exposes
(``eviction_gate``), which — unlike the deletion *filter* — keeps the node
parked in pod-deletion-required until the gate opens instead of silently
skipping the pod.

Orbax layout knowledge (mirrors orbax.checkpoint's commit protocol):

- Each step is a numbered subdirectory of the checkpoint root.
- In-progress saves use a ``<step>.orbax-checkpoint-tmp-<ts>`` directory
  name (atomic-rename filesystems) or contain no commit-success marker
  yet (GCS-style non-atomic filesystems).
- A step directory is committed once it has its final name and, when a
  ``commit_success.txt`` marker is used at all, the marker exists.
"""

from __future__ import annotations

import logging
import os
import re
import time
from dataclasses import dataclass
from typing import TYPE_CHECKING, Optional

if TYPE_CHECKING:  # pragma: no cover - types only
    from tpu_operator_libs.k8s.objects import Node, Pod

logger = logging.getLogger(__name__)

_TMP_RE = re.compile(r"\.orbax-checkpoint-tmp-\d+$")
_STEP_RE = re.compile(r"^(?:[a-zA-Z_]*?)(\d+)$")
_COMMIT_MARKER = "commit_success.txt"


def _is_tmp_dir(name: str) -> bool:
    return bool(_TMP_RE.search(name))


def _step_of(name: str) -> Optional[int]:
    m = _STEP_RE.match(name)
    return int(m.group(1)) if m else None


def _is_committed(entries: Optional[list[str]], require_marker: bool) -> bool:
    """Committed = final name, non-empty, and — when the checkpoint root
    uses commit markers at all (GCS-style non-atomic filesystems, where
    Orbax writes the step under its final name and the marker last) — the
    marker itself. On atomic-rename filesystems the final name alone is
    the commit. ``entries`` is the step directory's listing (None when the
    path is not a listable directory)."""
    if not entries:
        return False
    if _COMMIT_MARKER in entries:
        return True
    if require_marker:
        # Sibling steps carry markers, this one doesn't: still uploading.
        return False
    return not any(e.endswith(".orbax-checkpoint-in-progress")
                   for e in entries)


def latest_committed_step(checkpoint_dir: str) -> Optional[int]:
    """Newest committed step number under ``checkpoint_dir``, or None.

    Each step directory is listed exactly once (remote LIST calls are the
    cost driver on gcsfuse-mounted roots, re-run every reconcile for every
    parked node).
    """
    try:
        names = os.listdir(checkpoint_dir)
    except (FileNotFoundError, NotADirectoryError):
        return None
    listings: list[tuple[int, Optional[list[str]]]] = []
    uses_markers = False
    for name in names:
        if _is_tmp_dir(name):
            continue
        step = _step_of(name)
        if step is None:
            continue
        path = os.path.join(checkpoint_dir, name)
        try:
            entries = os.listdir(path) if os.path.isdir(path) else None
        except OSError:
            entries = None
        listings.append((step, entries))
        if entries and _COMMIT_MARKER in entries:
            uses_markers = True
    steps = [step for step, entries in listings
             if _is_committed(entries, require_marker=uses_markers)]
    return max(steps, default=None)


@dataclass
class CheckpointDurabilityGate:
    """Eviction gate: open once a sufficiently fresh checkpoint is durable.

    Usable directly as PodManager's ``eviction_gate(node, pods)`` — it
    returns True when eviction may proceed. Policy knobs:

    - ``min_step``: require at least this step to be committed (e.g. the
      job's current step minus an acceptable loss window).
    - ``max_age_seconds``: require the newest committed step's mtime to be
      within this window (guards against a job that stopped checkpointing);
      0 disables the age check.
    """

    checkpoint_dir: str
    min_step: Optional[int] = None
    max_age_seconds: float = 0.0

    def check(self) -> bool:
        step = latest_committed_step(self.checkpoint_dir)
        if step is None:
            logger.info("checkpoint gate: no committed checkpoint in %s",
                        self.checkpoint_dir)
            return False
        if self.min_step is not None and step < self.min_step:
            logger.info("checkpoint gate: latest committed step %d < "
                        "required %d", step, self.min_step)
            return False
        if self.max_age_seconds > 0:
            age = self._age_of_step(step)
            if age is None or age > self.max_age_seconds:
                logger.info("checkpoint gate: step %d age %s exceeds %.0fs",
                            step, age, self.max_age_seconds)
                return False
        logger.info("checkpoint gate open: step %d durable in %s",
                    step, self.checkpoint_dir)
        return True

    def _age_of_step(self, step: int) -> Optional[float]:
        try:
            for name in os.listdir(self.checkpoint_dir):
                if _step_of(name) == step and not _is_tmp_dir(name):
                    mtime = os.path.getmtime(
                        os.path.join(self.checkpoint_dir, name))
                    return time.time() - mtime
        except OSError:
            return None
        return None

    def __call__(self, node: "Node",
                 pods: "list[Pod]") -> bool:  # PodManager eviction_gate
        return self.check()
