"""ICI fabric health probe — the JAX/XLA compute path of this framework.

After a libtpu rolling upgrade, a node (or slice) must not return to
service on the strength of "the pod is Ready" alone: the runtime can be
loaded while the ICI links are degraded. This probe exercises the actual
hardware paths a training step uses and verifies the numerics:

- **MXU**: a bfloat16 128×128 matmul per device (the systolic-array path).
- **ICI collectives**: ``psum`` (all-reduce), a ``ppermute`` ring pass
  (neighbor links in both directions), and ``psum_scatter``
  (reduce-scatter) over the mesh axis — the collective set a sharded
  training step rides on.

Every result is compared against a closed-form expectation computed on the
host, so a wrong answer from any link or unit fails the probe, not just a
hang. The probe is built with ``shard_map`` over a ``jax.sharding.Mesh``
and jitted once; repeated probes reuse the compiled executable.

The reference has no counterpart (its "fabric" is the k8s API); this is
the TPU-native replacement for the OFED/RDMA validation concern
(SURVEY.md §5), wired into ValidationManager's ``extra_validator`` seam.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import TYPE_CHECKING, Any, Callable, Optional

import numpy as np

if TYPE_CHECKING:  # pragma: no cover - types only; jax stays lazy
    import jax

    from tpu_operator_libs.k8s.objects import Node
    from tpu_operator_libs.util import Clock

logger = logging.getLogger(__name__)

# MXU-native tile. 128x128 matches the TPU systolic array; bfloat16 is the
# native matmul input dtype.
_TILE = 128
_AXIS = "ici"


def make_mesh(n_devices: Optional[int] = None) -> "jax.sharding.Mesh":
    """A 1-D mesh over the first ``n_devices`` local devices (the ICI
    domain of the local slice)."""
    import jax

    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return jax.sharding.Mesh(np.array(devices), (_AXIS,))


@dataclass
class FabricProbeResult:
    healthy: bool
    max_abs_error: float
    latency_s: float
    n_devices: int

    def __str__(self) -> str:
        status = "healthy" if self.healthy else "UNHEALTHY"
        return (f"ICI fabric {status}: {self.n_devices} devices, "
                f"max|err|={self.max_abs_error:.3e}, "
                f"latency={self.latency_s * 1e3:.1f} ms")


def _probe_fn(axis_size: int):
    """Build the per-device probe computation (shard_map body)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def body(x):
        # x: (1, TILE, TILE) bf16 shard, value = (axis_index + 1)
        idx = lax.axis_index(_AXIS)
        local = x[0]

        # MXU path: scale by matmul with 2*I. Result value: 2*(idx+1).
        eye2 = (2.0 * jnp.eye(_TILE, dtype=jnp.bfloat16))
        mxu = jnp.dot(local, eye2, preferred_element_type=jnp.float32)

        # all-reduce: sum over devices of 2*(i+1) = 2 * n(n+1)/2
        reduced = lax.psum(mxu, _AXIS)

        # ring pass: receive the left neighbor's value 2*((idx-1)%n + 1)
        ring = lax.ppermute(
            mxu, _AXIS,
            perm=[(i, (i + 1) % axis_size) for i in range(axis_size)])

        max_err = jnp.maximum(
            jnp.max(jnp.abs(reduced - (1.0 * axis_size * (axis_size + 1)))),
            jnp.max(jnp.abs(
                ring - 2.0 * ((idx - 1) % axis_size + 1).astype(jnp.float32))))

        if _TILE % axis_size == 0:
            # reduce-scatter: rows of the summed tile scattered across
            # devices (needs the tile to divide evenly; psum+ppermute above
            # already cover every link when it doesn't)
            scattered = lax.psum_scatter(
                mxu, _AXIS, scatter_dimension=0, tiled=True)
            max_err = jnp.maximum(
                max_err,
                jnp.max(jnp.abs(scattered - reduced[:_TILE // axis_size])))
        return max_err[None]

    return body


def fabric_probe(mesh: Optional["jax.sharding.Mesh"] = None,
                 n_devices: Optional[int] = None,
                 tolerance: float = 1e-3) -> FabricProbeResult:
    """Run the fabric probe over ``mesh`` (default: all local devices).

    Returns a :class:`FabricProbeResult`; ``healthy`` means every collective
    produced numerics within ``tolerance`` of the closed-form expectation.
    """
    import jax
    import jax.numpy as jnp
    try:
        from jax import shard_map
    except ImportError:  # pre-0.7 jax: experimental location
        from functools import partial as _partial

        from jax.experimental.shard_map import shard_map as _shard_map

        # check_rep rejects valid rep types around lax.cond on old jax
        # (the check no longer exists upstream); disable, same semantics
        shard_map = _partial(_shard_map, check_rep=False)
    from jax.sharding import PartitionSpec as P

    if mesh is None:
        mesh = make_mesh(n_devices)
    axis_size = mesh.devices.size

    # Per-device input: value (axis_index + 1), laid out so shard i holds
    # slab i of the leading axis.
    host = np.stack([np.full((_TILE, _TILE), i + 1, dtype=np.float32)
                     for i in range(axis_size)]).astype(jnp.bfloat16)
    sharding = jax.sharding.NamedSharding(mesh, P(_AXIS))
    x = jax.device_put(host, sharding)

    probed = jax.jit(shard_map(
        _probe_fn(axis_size), mesh=mesh,
        in_specs=P(_AXIS), out_specs=P(_AXIS)))

    # warm-up compile outside the timed region
    np.asarray(probed(x))
    # The host readback IS the timing fence: on tunneled/async PJRT
    # platforms block_until_ready() can return before device work
    # completes, so the materialized per-device error vector (a few
    # bytes) is what bounds the measurement, not a ready flag.
    start = time.perf_counter()
    errs = np.asarray(probed(x), dtype=np.float32)
    latency = time.perf_counter() - start

    max_err = float(np.max(errs))
    result = FabricProbeResult(
        healthy=max_err <= tolerance,
        max_abs_error=max_err,
        latency_s=latency,
        n_devices=axis_size)
    logger.info("%s", result)
    return result


@dataclass
class BandwidthProbeResult:
    """Achieved per-link ICI throughput from a timed ppermute ring.

    ``gbytes_per_s`` is giga**bytes**/s (the unit TPU ICI specs quote),
    not gigabits."""

    gbytes_per_s: float
    bytes_per_hop: int
    rounds: int
    latency_s: float
    n_devices: int
    healthy: bool = True

    def __str__(self) -> str:
        status = "ok" if self.healthy else "DEGRADED"
        return (f"ICI bandwidth {status}: "
                f"{self.gbytes_per_s:.1f} GByte/s/link "
                f"({self.n_devices} devices, "
                f"{self.bytes_per_hop >> 20} MiB x {self.rounds} hops, "
                f"{self.latency_s * 1e3:.1f} ms)")


def fabric_bandwidth_probe(mesh: Optional["jax.sharding.Mesh"] = None,
                           n_devices: Optional[int] = None,
                           payload_mib: int = 16, rounds: int = 8,
                           min_gbytes_per_s: Optional[float] = None,
                           ) -> BandwidthProbeResult:
    """Measure achieved ICI throughput with a timed neighbor-ring pass.

    The correctness battery (:func:`fabric_probe`) certifies that every
    link produces right answers; a link can still be *slow* (retraining,
    lane degradation) and silently halve step time. This probe pushes
    ``payload_mib`` of bfloat16 around the ring ``rounds`` times — each
    round moves the full payload across every link simultaneously — and
    reports bytes/wall-time as per-link unidirectional gigabytes/s.
    ``healthy`` is ``gbytes_per_s >= min_gbytes_per_s`` when a floor is
    given (deployments set it per TPU generation; v4/v5 ICI links are
    O(100) GByte/s each way).

    On a physical torus the mesh must be a real neighbor ring (one axis,
    all other coordinates fixed — see :func:`fabric_bandwidth_topology`);
    a flat ring over linear device order crosses multiple physical hops
    at row boundaries and under-reports. On a CPU mesh this measures
    memcpy, so tests assert structure, not thresholds.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    try:
        from jax import shard_map
    except ImportError:  # pre-0.7 jax: experimental location
        from functools import partial as _partial

        from jax.experimental.shard_map import shard_map as _shard_map

        # check_rep rejects valid rep types around lax.cond on old jax
        # (the check no longer exists upstream); disable, same semantics
        shard_map = _partial(_shard_map, check_rep=False)
    from jax.sharding import PartitionSpec as P

    if mesh is None:
        mesh = make_mesh(n_devices)
    axis_size = mesh.devices.size
    if axis_size < 2:
        raise ValueError("bandwidth probe needs >= 2 devices")

    elems = (payload_mib << 20) // 2  # bf16 = 2 bytes
    cols = max(elems // _TILE, 1)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def body(x):
        local = x[0]
        for _ in range(rounds):
            # data dependency between hops so XLA cannot fuse them away
            local = lax.ppermute(local, _AXIS, perm=perm) + jnp.bfloat16(0)
        # reduce to one scalar per device: the host readback of a few
        # bytes is the timing fence (block_until_ready can return early
        # on tunneled/async PJRT platforms) without adding a payload-
        # sized device->host transfer into the timed region
        return jnp.sum(local.astype(jnp.float32))[None]

    host = np.ones((axis_size, _TILE, cols), dtype=np.float32)
    sharding = jax.sharding.NamedSharding(mesh, P(_AXIS))
    x = jax.device_put(host.astype(jnp.bfloat16), sharding)
    probed = jax.jit(shard_map(body, mesh=mesh,
                               in_specs=P(_AXIS), out_specs=P(_AXIS)))
    np.asarray(probed(x))  # compile outside the timed region
    start = time.perf_counter()
    np.asarray(probed(x))
    latency = time.perf_counter() - start

    bytes_per_hop = _TILE * cols * 2
    # verdict computed from the same rounded value that is reported, so
    # result.gbytes_per_s >= floor always agrees with result.healthy
    gbytes_per_s = round((bytes_per_hop * rounds / latency) / 1e9, 2)
    result = BandwidthProbeResult(
        gbytes_per_s=gbytes_per_s,
        bytes_per_hop=bytes_per_hop,
        rounds=rounds,
        latency_s=latency,
        n_devices=axis_size,
        healthy=(min_gbytes_per_s is None
                 or gbytes_per_s >= min_gbytes_per_s))
    logger.info("%s", result)
    return result


def single_chip_probe() -> tuple[Callable[[Any, Any], Any],
                                 tuple[Any, Any]]:
    """(fn, example_args) for the single-device probe step — the jittable
    forward step exposed through ``__graft_entry__.entry()``.

    A collective-free slice of the fabric probe: bf16 MXU matmul plus a
    deterministic elementwise chain whose output the host can verify.
    """
    import jax.numpy as jnp

    def probe_step(x, w):
        y = jnp.dot(x, w, preferred_element_type=jnp.float32)
        return jnp.tanh(y) + y * 0.5

    x = jnp.full((_TILE, _TILE), 0.5, dtype=jnp.bfloat16)
    w = jnp.eye(_TILE, dtype=jnp.bfloat16)
    return probe_step, (x, w)


def fabric_probe_topology(topology: str,
                          n_devices: Optional[int] = None,
                          tolerance: float = 1e-3,
                          max_rings_per_axis: int = 4) -> list[FabricProbeResult]:
    """Probe every axis of a multi-dimensional ICI torus.

    TPU slices are 2-D/3-D tori (GKE exposes the shape via the
    ``cloud.google.com/gke-tpu-topology`` label, e.g. ``4x4`` for a v5e-16
    slice or ``4x4x8`` for v5p). A link can be healthy on one axis and
    broken on another, so the device array is reshaped to ``dims`` and,
    per axis, the *strided* rings along that axis (all other coordinates
    fixed) are each probed with the psum/ppermute/reduce-scatter battery.
    For dims (4,4), axis 0's rings are devices [0,4,8,12], [1,5,9,13], …
    — the column links a contiguous grouping would never touch.

    Probe cost is bounded at ``max_rings_per_axis`` rings per axis (the
    skipped count is logged — partial coverage is never silent). Uses as
    many local devices as the topology requires; with fewer (e.g. CI's
    virtual CPU mesh) the dims are scaled down while keeping the rank.
    """
    import jax

    rings, fitted = _torus_axis_rings(topology, n_devices,
                                      max_rings_per_axis)
    results = [
        fabric_probe(mesh=jax.sharding.Mesh(np.array(list(ring)), (_AXIS,)),
                     tolerance=tolerance)
        for _axis, ring in rings
    ]
    if not results:
        # no multi-device axis (e.g. a 1x1 single-chip slice): probe only
        # the devices the topology spans, never unrelated local devices
        results.append(fabric_probe(n_devices=fitted, tolerance=tolerance))
    return results


def _torus_axis_rings(topology: str, n_devices: Optional[int],
                      max_rings_per_axis: int,
                      warn_on_skip: bool = True,
                      ) -> tuple[list[tuple[int, tuple]], int]:
    """((axis, ring-of-devices) per strided torus ring, fitted device
    count).

    Deduplicates identical rings (square dims), caps per axis at
    ``max_rings_per_axis`` (skips logged unless the cap is the caller's
    documented coverage — ``warn_on_skip=False``), and scales the dims
    down to fit the locally visible device count while keeping the
    rank."""
    import jax

    from tpu_operator_libs.topology.slice_topology import parse_chip_topology

    dims = parse_chip_topology(topology)
    if dims is None:
        raise ValueError(f"unparseable TPU topology {topology!r}")
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    available = len(devices)
    need = 1
    for d in dims:
        need *= d
    while need > available:
        # scale the largest axis down by 2 until the shape fits locally
        dims = tuple(sorted(dims, reverse=True))
        if dims[0] == 1:
            break
        dims = (max(1, dims[0] // 2),) + dims[1:]
        need = 1
        for d in dims:
            need *= d

    grid = np.array(devices[:need], dtype=object).reshape(dims)
    out: list[tuple[int, tuple]] = []
    probed_rings: set[tuple[int, ...]] = set()
    for axis, axis_len in enumerate(dims):
        if axis_len <= 1:
            continue
        rings = np.moveaxis(grid, axis, -1).reshape(-1, axis_len)
        probed_this_axis = 0
        for ring in rings:
            if probed_this_axis >= max_rings_per_axis:
                break
            ring_key = tuple(sorted(d.id for d in ring))
            if ring_key in probed_rings:
                continue  # identical ring already certified (square dims)
            out.append((axis, tuple(ring)))
            probed_rings.add(ring_key)
            probed_this_axis += 1
        skipped = sum(
            1 for ring in rings
            if tuple(sorted(d.id for d in ring)) not in probed_rings)
        if skipped > 0 and warn_on_skip:
            logger.warning(
                "fabric probe axis %d: %d of %d rings not probed "
                "(max_rings_per_axis=%d) — coverage is partial",
                axis, skipped, len(rings), max_rings_per_axis)
    return out, min(need, available)


def fabric_bandwidth_topology(topology: str,
                              n_devices: Optional[int] = None,
                              min_gbytes_per_s: Optional[float] = None,
                              payload_mib: int = 16, rounds: int = 8,
                              max_rings_per_axis: int = 1,
                              ) -> list[BandwidthProbeResult]:
    """Per-axis bandwidth battery over a multi-dimensional ICI torus.

    Each probed ring is a true neighbor ring along one torus axis (all
    other coordinates fixed), so the measured GByte/s reflects single
    physical links — a flat ring over linear device order would cross
    multiple hops at row boundaries and under-report. One ring per axis
    (the default cap) is the documented coverage, so the per-axis skip
    warning is suppressed. Returns an empty list for a topology with no
    multi-device axis (nothing to measure — there is no ICI).
    """
    import jax

    rings, _fitted = _torus_axis_rings(topology, n_devices,
                                       max_rings_per_axis,
                                       warn_on_skip=False)
    return [
        fabric_bandwidth_probe(
            mesh=jax.sharding.Mesh(np.array(list(ring)), (_AXIS,)),
            payload_mib=payload_mib, rounds=rounds,
            min_gbytes_per_s=min_gbytes_per_s)
        for _axis, ring in rings
    ]


class ICIFabricValidator:
    """NodeValidator adapter: plugs the fabric probe into the validation
    state (ValidationManager ``extra_validator`` seam).

    The operator process typically runs on (or adjacent to) the slice being
    validated; ``probe_runner`` is injectable so tests — and deployments
    where probing happens via a validation Job — can substitute transport.
    Results are cached for ``cache_seconds`` per slice to keep reconcile
    loops cheap. When the validated node carries a GKE topology label, the
    per-axis torus battery (:func:`fabric_probe_topology`) runs instead of
    the flat probe.
    """

    def __init__(self,
                 probe_runner: Optional[Callable[..., Any]] = None,
                 cache_seconds: float = 300.0,
                 clock: Optional["Clock"] = None,
                 tolerance: float = 1e-3,
                 min_bandwidth_gbytes_per_s: Optional[float] = None) -> None:
        from tpu_operator_libs.util import Clock

        self._probe = probe_runner
        self._tolerance = tolerance
        self._min_bandwidth = min_bandwidth_gbytes_per_s
        self._cache_seconds = cache_seconds
        self._clock = clock or Clock()
        # Keyed per slice/topology: one validator instance serves the whole
        # fleet (examples/libtpu_operator.py), and a cached result for
        # slice A must never be served for slice B.
        self._cached: dict[object, tuple[float, bool]] = {}

    @staticmethod
    def _cache_key(node) -> object:
        from tpu_operator_libs.consts import GKE_TPU_TOPOLOGY_LABEL
        from tpu_operator_libs.topology.slice_topology import (
            slice_id_for_node,
        )

        if node is None:
            return None
        labels = getattr(node.metadata, "labels", {})
        return (slice_id_for_node(node),
                labels.get(GKE_TPU_TOPOLOGY_LABEL, ""))

    def _default_probe(self, node) -> bool:
        from tpu_operator_libs.consts import GKE_TPU_TOPOLOGY_LABEL

        topology = ""
        if node is not None:
            topology = getattr(node.metadata, "labels", {}).get(
                GKE_TPU_TOPOLOGY_LABEL, "")
        if topology:
            results = fabric_probe_topology(topology,
                                            tolerance=self._tolerance)
            healthy = all(r.healthy for r in results)
        else:
            healthy = fabric_probe(tolerance=self._tolerance).healthy
        if healthy and self._min_bandwidth is not None:
            # correctness passed; also require undegraded throughput —
            # per torus axis when a topology is known, so each measured
            # ring rides single physical links
            import jax

            if len(jax.devices()) < 2:
                # off-slice single-device host: the floor is unenforceable
                # from here — must be visible, not a silent pass
                logger.warning(
                    "bandwidth floor configured but only %d local device "
                    "visible; skipping the throughput gate",
                    len(jax.devices()))
            else:
                if topology:
                    bw = fabric_bandwidth_topology(
                        topology, min_gbytes_per_s=self._min_bandwidth)
                    if not bw:
                        # single-chip topology: no ICI to measure — the
                        # configured floor is unenforceable here, which
                        # must be visible, not a silent pass
                        logger.warning(
                            "bandwidth floor configured but topology %r "
                            "has no multi-device axis; skipping the "
                            "throughput gate", topology)
                    healthy = all(r.healthy for r in bw)
                else:
                    healthy = fabric_bandwidth_probe(
                        min_gbytes_per_s=self._min_bandwidth).healthy
        return healthy

    def __call__(self, node: "Node") -> bool:
        now = self._clock.now()
        key = self._cache_key(node)
        cached = self._cached.get(key)
        if cached is not None:
            ts, healthy = cached
            if now - ts < self._cache_seconds:
                return healthy
        if self._probe is not None:
            result = self._probe()
            healthy = bool(getattr(result, "healthy", result))
        else:
            healthy = self._default_probe(node)
        self._cached[key] = (now, healthy)
        return healthy
