"""In-flight-request drain gate for evicting live serving (decode) pods.

The checkpoint gate (health/checkpoint_gate.py) protects TRAINING pods:
eviction waits for a durable Orbax step. Serving pods have no checkpoint
— their unit of loss is the in-flight generation: evicting a decode pod
mid-generation drops every request it was streaming. This module is the
serving-side counterpart, plugged into the exact same eviction-gate seam
(upgrade/gate.py ``GateKeeper``, the generalization of the reference's
``PodDeletionFilter`` hook, pod_manager.go:76, and of
``WaitForCompletionSpec``, upgrade_spec.go:52-64):

1. The first time the upgrade flow wants to evict a node's serving
   pods, the gate puts its endpoints into **draining**: new requests are
   no longer admitted (``try_begin`` returns None; the router parks or
   re-routes them — in-flight generations are untouched).
2. While any generation is still in flight the gate stays CLOSED; the
   node parks in its current state (drain / pod-deletion required) and
   is retried next reconcile — the same park-don't-escalate semantics
   the checkpoint gate gets from GateKeeper.
3. Once every in-flight generation finishes, the gate OPENS and
   eviction proceeds having dropped zero generations.
4. If the upgrade flow abandons the node (e.g. policy change),
   ``release`` returns its endpoints to admitting.

A :class:`ServingEndpoint` is the library-side handle for one decode
server (one per serving pod; ``examples/llama_decode.generate_on_device``
is the compute it fronts). Real deployments adapt this to their serving
runtime (the admission check wraps the server's request intake); the
contract the gate needs is only admitting/draining + an in-flight count.
"""

from __future__ import annotations

import logging
import re
import threading
from typing import Callable

from tpu_operator_libs.k8s.objects import Node, Pod

logger = logging.getLogger(__name__)

#: DNS-label shape a traffic-class name must take (mirrors
#: api/upgrade_policy._CLASS_NAME_RE — the gate is importable without
#: the policy layer, so the pattern is duplicated by design).
_CLASS_NAME_RE = re.compile(r"^[a-z0-9]([a-z0-9-]{0,61}[a-z0-9])?$")


class ServingEndpoint:
    """Admission control + in-flight accounting for one decode server.

    Thread-safe: the upgrade controller drains from its reconcile
    thread while request handlers begin/finish generations concurrently.

    ``traffic_class`` and ``model`` are the disruption-cost signals the
    :class:`~tpu_operator_libs.upgrade.handover.DisruptionCostRanker`
    ranks drain candidates by: endpoints of a batch class (or of a
    well-replicated model) are cheap to disrupt, the sole admitting
    replica of an interactive model is held behind the prewarm arc.
    Both are validated at construction — a malformed class name or a
    non-positive capacity must fail HERE, not misbehave passes later
    inside the budget math.
    """

    def __init__(self, name: str,
                 capacity: "int | None" = None,
                 traffic_class: str = "batch",
                 model: str = "") -> None:
        if not isinstance(name, str) or not name:
            raise ValueError("ServingEndpoint name must be a non-empty "
                             "string")
        if capacity is not None:
            if isinstance(capacity, bool) \
                    or not isinstance(capacity, int) or capacity < 1:
                raise ValueError(
                    f"ServingEndpoint {name}: capacity must be a "
                    f"positive integer or None, got {capacity!r}")
        if not isinstance(traffic_class, str) \
                or not _CLASS_NAME_RE.match(traffic_class):
            raise ValueError(
                f"ServingEndpoint {name}: traffic_class "
                f"{traffic_class!r} is malformed (must be a lowercase "
                f"DNS label)")
        self.name = name
        #: Concurrent generations this endpoint sustains — the per-node
        #: capacity signal the traffic-aware budget controller
        #: (upgrade/capacity.py) aggregates into fleet headroom. None =
        #: the controller's policy default (capacityBudget.
        #: perNodeCapacity) applies.
        self.capacity = capacity
        #: Traffic class this endpoint serves (matches a
        #: TrafficClassSpec name; "batch" = the cheap default).
        self.traffic_class = traffic_class
        #: Model identity for replication counting ("" = unscoped: the
        #: endpoint never counts as anyone's sole replica).
        self.model = model
        self._lock = threading.Lock()
        self._draining = False
        self._in_flight = 0
        self.completed = 0
        #: Generations aborted mid-flight (the metric the gate drives
        #: to zero; killed pods abort their in-flight handles).
        self.dropped = 0
        #: Generations the router migrated OFF this endpoint to a peer
        #: replica (session handover past the class drain deadline) —
        #: they completed elsewhere, not here, and were never dropped.
        self.handed_over = 0

    # -- request side ---------------------------------------------------
    def try_begin(self) -> bool:
        """Admit one generation; False while draining (the caller parks
        or re-routes the request — it is NOT dropped: it never started)."""
        with self._lock:
            if self._draining:
                return False
            self._in_flight += 1
            return True

    def finish(self) -> None:
        """A generation completed and its tokens were delivered."""
        with self._lock:
            if self._in_flight <= 0:
                raise RuntimeError(
                    f"endpoint {self.name}: finish() without begin()")
            self._in_flight -= 1
            self.completed += 1

    def kill(self) -> int:
        """The serving pod died (eviction, node failure): every
        in-flight generation is lost. Returns how many were dropped."""
        with self._lock:
            dropped = self._in_flight
            self.dropped += dropped
            self._in_flight = 0
            self._draining = True
            return dropped

    def handover(self) -> bool:
        """The router re-bound one in-flight generation to a peer
        replica: it leaves this endpoint's accounting WITHOUT counting
        as completed or dropped (the receiving endpoint's ``try_begin``
        picks it up). False when nothing was in flight to move."""
        with self._lock:
            if self._in_flight <= 0:
                return False
            self._in_flight -= 1
            self.handed_over += 1
            return True

    # -- upgrade side ---------------------------------------------------
    def begin_drain(self) -> None:
        """Stop admitting new generations (idempotent); in-flight ones
        run to completion."""
        with self._lock:
            if not self._draining:
                logger.info("serving endpoint %s: draining "
                            "(%d generation(s) in flight)",
                            self.name, self._in_flight)
            self._draining = True

    def resume(self) -> None:
        with self._lock:
            self._draining = False

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    @property
    def in_flight(self) -> int:
        with self._lock:
            return self._in_flight

    @property
    def quiesced(self) -> bool:
        with self._lock:
            return self._in_flight == 0


#: Maps (node, pods-about-to-be-evicted) to the serving endpoints those
#: pods back. Deployment-specific: a fleet registry keyed by pod name,
#: a label-driven lookup, etc.
EndpointResolver = Callable[[Node, "list[Pod]"], "list[ServingEndpoint]"]


class ServingDrainGate:
    """EvictionGate (upgrade/gate.py) for serving fleets.

    Evaluating the gate is what initiates the drain: the first reconcile
    that wants the node's pods gone flips its endpoints to draining, and
    the gate reports closed until they quiesce. Plug into both eviction
    paths exactly like the checkpoint gate::

        gate = ServingDrainGate(resolver)
        mgr.drain_manager.set_eviction_gate(gate)
        mgr.pod_manager.set_eviction_gate(gate)

    Compose with a checkpoint gate when a fleet runs both kinds of
    workload: ``lambda n, p: ckpt_gate(n, p) and serving_gate(n, p)``
    (both gates are park-don't-escalate, so conjunction is safe).
    """

    def __init__(self, resolver: EndpointResolver) -> None:
        self._resolver = resolver

    def __call__(self, node: Node, pods: "list[Pod]") -> bool:
        endpoints = self._resolver(node, pods)
        for ep in endpoints:
            ep.begin_drain()
        blocked = [ep for ep in endpoints if not ep.quiesced]
        if blocked:
            logger.info(
                "serving gate closed for node %s: %s still streaming",
                node.metadata.name,
                ", ".join(f"{ep.name}({ep.in_flight})" for ep in blocked))
            return False
        return True

    def release(self, node: Node, pods: "list[Pod]") -> None:
        """The upgrade flow no longer wants this node's pods evicted;
        let its endpoints admit requests again."""
        for ep in self._resolver(node, pods):
            ep.resume()
