"""Failure-precursor health signals: condemn hardware BEFORE it dies.

The Ironwood retrospective (PAPERS.md) credits proactive routing —
moving work off degrading hardware before the hard failure — as a
primary fleet-resilience mechanism, alongside the optical-circuit-switch
remaps the :class:`~tpu_operator_libs.topology.reconfigurer.
SliceReconfigurer` reproduces. Today's remediation machine is purely
reactive: it waits for a :class:`~tpu_operator_libs.remediation.
detectors.WedgeDetector` verdict, paying full MTTR and the unplanned
session drops of a dead decode host on every failure. This module is
the predictive half:

- :class:`NodeHealthSignal` — the library-side handle for one node's
  hardware health counters (ECC corrections, ICI link flaps, thermal
  throttle events). Real deployments adapt this to their telemetry
  agent; the contract the model needs is only a monotonic per-family
  counter snapshot. Construction-time validation follows
  :class:`~tpu_operator_libs.health.serving_gate.ServingEndpoint`: a
  malformed counter family or a negative count must fail HERE, not
  misbehave passes later inside the rate math.

- :class:`FailurePrecursorModel` — the online model, built from the
  same estimator pieces as the PR 9 duration predictor
  (``upgrade/estimators.py``): a per-(node, signal) EWMA of counter
  *rates* as the warm path, fleet-pooled bucketed rate histograms as
  the evidence surface, and a durable per-node seed annotation so a
  fresh operator incarnation resumes each node's model from cluster
  state alone. ``observe`` returns the annotation updates that must
  ride the caller's merge patch (one wire write, crash-atomic — the
  predictor's ``observe_transition`` contract).

- :class:`PrecursorVerdict` — the ``condemned-at-risk`` output: a node
  whose EWMA rate has stayed over threshold for ``min_observations``
  consecutive samples. The remediation machine commits it as the
  ``at-risk`` state and routes the node into the PR 6 reconfigure arc
  while it still serves: spare reserved, slice remapped, node drained
  as a *planned* low-cost candidate — the failure, when it comes,
  lands on an already-evacuated host.
"""

from __future__ import annotations

import logging
import re
import threading
from typing import Mapping, Optional

from tpu_operator_libs.consts import RemediationKeys
from tpu_operator_libs.upgrade.estimators import (
    PooledHistogram,
    ewma_update,
)
from tpu_operator_libs.util import Clock

logger = logging.getLogger(__name__)

#: The counter families the model learns, in verdict-priority order.
#: Deliberately a closed set (like the predictor's PHASES): the durable
#: seed annotation's encode/decode filters to these, so a renamed or
#: retired family can never poison a resumed model.
SIGNALS: tuple[str, ...] = ("ecc", "link-flap", "thermal")

#: DNS-label shape a counter-family name must take (mirrors
#: health/serving_gate._CLASS_NAME_RE — one validation idiom per layer,
#: duplicated by design so this module imports nothing from serving).
_SIGNAL_NAME_RE = re.compile(r"^[a-z0-9]([a-z0-9-]{0,61}[a-z0-9])?$")

#: Pooled-histogram buckets (events per hour): precursor rates ride the
#: scale from background noise (fractions of an event per hour) to the
#: runaway ramps a dying part emits (hundreds per hour).
RATE_PER_HOUR_BUCKETS: tuple[float, ...] = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
    1000.0)


class NodeHealthSignal:
    """Monotonic hardware-health counters for one node.

    Thread-safe: a telemetry agent bumps counters while the operator's
    reconcile thread snapshots them. Counter families are validated at
    construction and on every ``bump`` — the model side must never see
    a malformed family name or a non-integer count.
    """

    def __init__(self, node: str,
                 counters: "Optional[Mapping[str, int]]" = None) -> None:
        if not isinstance(node, str) or not node:
            raise ValueError("NodeHealthSignal node must be a non-empty "
                             "string")
        self.node = node
        self._lock = threading.Lock()
        self._counters: dict[str, int] = {s: 0 for s in SIGNALS}
        if counters:
            for signal, value in counters.items():
                self._validate(signal, value)
                self._counters[signal] = value

    def _validate(self, signal: str, value: int) -> None:
        if not isinstance(signal, str) \
                or not _SIGNAL_NAME_RE.match(signal):
            raise ValueError(
                f"NodeHealthSignal {self.node}: counter family "
                f"{signal!r} is malformed (must be a lowercase DNS "
                f"label)")
        if isinstance(value, bool) or not isinstance(value, int) \
                or value < 0:
            raise ValueError(
                f"NodeHealthSignal {self.node}: counter {signal!r} must "
                f"be a non-negative integer, got {value!r}")

    def bump(self, signal: str, by: int = 1) -> int:
        """Add ``by`` events to one counter family; returns the new
        cumulative count. Families outside :data:`SIGNALS` are accepted
        (forward compatibility with richer telemetry) — the model simply
        ignores them."""
        self._validate(signal, by)
        with self._lock:
            self._counters[signal] = self._counters.get(signal, 0) + by
            return self._counters[signal]

    def read(self) -> "dict[str, int]":
        """Point-in-time snapshot of every counter family."""
        with self._lock:
            return dict(self._counters)


class PrecursorVerdict:
    """One ``condemned-at-risk`` verdict: which signal family crossed
    the line, and by how much. Immutable evidence — the remediation
    machine stamps ``reason`` durably next to the at-risk commit."""

    __slots__ = ("node", "signal", "rate_per_hour", "threshold_per_hour")

    def __init__(self, node: str, signal: str, rate_per_hour: float,
                 threshold_per_hour: float) -> None:
        self.node = node
        self.signal = signal
        self.rate_per_hour = rate_per_hour
        self.threshold_per_hour = threshold_per_hour

    @property
    def reason(self) -> str:
        """Machine-readable slug (the at-risk-reason annotation value)."""
        return (f"precursor-{self.signal}:"
                f"{self.rate_per_hour:g}/h>={self.threshold_per_hour:g}/h")

    @property
    def detail(self) -> str:
        return (f"{self.signal} precursor rate {self.rate_per_hour:g}/h "
                f"crossed the condemnation threshold "
                f"{self.threshold_per_hour:g}/h")


class FailurePrecursorModel:
    """Online per-node failure-precursor model (PR 9 predictor idiom).

    Feed :meth:`observe` one counter snapshot per node per reconcile
    pass; it converts the monotonic counters into per-hour rates
    against the previous snapshot, folds them into the per-node EWMA
    and the fleet pool, and returns the annotation updates that keep
    the node's durable model seed current. :meth:`verdict` answers
    whether the node has earned the ``condemned-at-risk`` call;
    :meth:`cleared` answers whether an already-committed at-risk arc
    may stand down — and deliberately answers False on a cold model, so
    a freshly restarted operator can never abort a verdict a previous
    incarnation committed durably.
    """

    def __init__(self, keys: Optional[RemediationKeys] = None,
                 clock: Optional[Clock] = None,
                 smoothing: float = 0.5,
                 rate_threshold_per_hour: float = 6.0,
                 min_observations: int = 3) -> None:
        if not 0.0 < smoothing <= 1.0:
            raise ValueError("smoothing must be in (0, 1]")
        if rate_threshold_per_hour <= 0.0:
            raise ValueError("rate_threshold_per_hour must be positive")
        if isinstance(min_observations, bool) \
                or not isinstance(min_observations, int) \
                or min_observations < 1:
            raise ValueError("min_observations must be a positive integer")
        self.keys = keys or RemediationKeys()
        self._clock = clock or Clock()
        self.smoothing = smoothing
        self.rate_threshold_per_hour = rate_threshold_per_hour
        self.min_observations = min_observations
        # One coarse lock over every model mutation, exactly like the
        # duration predictor: observations arrive from the reconcile
        # pass while metrics drains and status reads run concurrently.
        self._lock = threading.Lock()
        # per-(node, signal) EWMA of events/hour
        self._ewma: dict[str, dict[str, float]] = {}
        # per-node previous snapshot: (at, {signal: count}) — the rate
        # baseline. In-memory only: losing it on a crash re-baselines
        # the node (one sample lost, never invented).
        self._last: dict[str, tuple[float, dict[str, int]]] = {}
        # consecutive over-threshold / under-threshold observations
        self._streak: dict[str, int] = {}
        self._clear_streak: dict[str, int] = {}
        # fleet-pooled per-signal rate histograms (evidence surface)
        self._pooled: dict[str, PooledHistogram] = {
            signal: PooledHistogram(RATE_PER_HOUR_BUCKETS)
            for signal in SIGNALS}
        #: (signal, rate_per_hour) samples since the last metrics drain.
        self._sample_buffer: list[tuple[str, float]] = []
        #: lifetime accounting
        self.observations_total = 0

    # ------------------------------------------------------------------
    # learning side
    # ------------------------------------------------------------------
    def observe(self, name: str, counters: "Mapping[str, int]",
                now: Optional[float] = None,
                annotations: "Optional[Mapping[str, str]]" = None,
                ) -> "Optional[dict[str, Optional[str]]]":
        """Fold one counter snapshot into the node's model.

        Returns annotation updates (the durable per-node seed) to merge
        into the caller's patch when the encoded rates changed, or None.
        The first snapshot after a (re)start only establishes the rate
        baseline — and seeds the in-memory EWMA from the node's durable
        annotation, so a fresh incarnation resumes from cluster state
        alone instead of relearning the fleet from zero.
        """
        if now is None:
            now = self._clock.now()
        seed_key = self.keys.precursor_rates_annotation
        with self._lock:
            per_node = self._ewma.get(name)
            if per_node is None:
                per_node = {}
                if annotations:
                    # read-through: the durable seed becomes the
                    # in-memory model (the predictor's crash-recovery
                    # idiom)
                    per_node.update(decode_rates(
                        annotations.get(seed_key)))
                self._ewma[name] = per_node
            last = self._last.get(name)
            snapshot = {signal: int(counters.get(signal, 0))
                        for signal in SIGNALS}
            self._last[name] = (now, snapshot)
            if last is None or now <= last[0]:
                return None  # baseline (re)established; no rate yet
            t0, prev = last
            hours = (now - t0) / 3600.0
            for signal in SIGNALS:
                delta = snapshot[signal] - prev.get(signal, 0)
                if delta < 0:
                    # counter reset (agent restart): the post-reset
                    # count is the whole window's worth of events
                    delta = snapshot[signal]
                rate = delta / hours
                per_node[signal] = ewma_update(per_node.get(signal),
                                               rate, self.smoothing)
                self._pooled[signal].record(rate)
                self._sample_buffer.append((signal, rate))
            self.observations_total += 1
            if any(per_node.get(signal, 0.0)
                   >= self.rate_threshold_per_hour
                   for signal in SIGNALS):
                self._streak[name] = self._streak.get(name, 0) + 1
                self._clear_streak[name] = 0
            else:
                self._clear_streak[name] = \
                    self._clear_streak.get(name, 0) + 1
                self._streak[name] = 0
            encoded = encode_rates(per_node)
        durable = annotations.get(seed_key) if annotations else None
        if encoded and encoded != durable:
            return {seed_key: encoded}
        return None

    # ------------------------------------------------------------------
    # verdict side
    # ------------------------------------------------------------------
    def verdict(self, name: str) -> Optional[PrecursorVerdict]:
        """The ``condemned-at-risk`` call: the worst over-threshold
        signal once the node's EWMA has stayed over the line for
        ``min_observations`` consecutive observations (a single noisy
        sample can never condemn a node)."""
        with self._lock:
            if self._streak.get(name, 0) < self.min_observations:
                return None
            per_node = self._ewma.get(name, {})
            over = [(per_node[signal], signal) for signal in SIGNALS
                    if per_node.get(signal, 0.0)
                    >= self.rate_threshold_per_hour]
            if not over:
                return None
            rate, signal = max(over)
        return PrecursorVerdict(name, signal, round(rate, 3),
                                self.rate_threshold_per_hour)

    def cleared(self, name: str) -> bool:
        """True when THIS incarnation has itself observed the node
        under threshold ``min_observations`` times in a row — the
        stand-down gate for an in-flight at-risk arc. A cold model
        (fresh incarnation, zero observations) is never cleared: the
        durable at-risk stamp outranks an empty memory."""
        with self._lock:
            return (self._clear_streak.get(name, 0)
                    >= self.min_observations)

    # ------------------------------------------------------------------
    # evidence feed (observe_precursor / status)
    # ------------------------------------------------------------------
    def drain_rate_samples(self) -> "list[tuple[str, float]]":
        """(signal, events/hour) samples observed since the last drain."""
        with self._lock:
            out, self._sample_buffer = self._sample_buffer, []
        return out

    @property
    def known_nodes(self) -> int:
        with self._lock:
            return len(self._ewma)

    @property
    def at_risk_streaks(self) -> int:
        """Nodes currently carrying a non-zero over-threshold streak."""
        with self._lock:
            return sum(1 for v in self._streak.values() if v)

    def pooled_stats(self) -> "dict[str, dict]":
        """Per-signal pooled (count, mean, p50, p95) events/hour — the
        model's own evidence, read through the shared quantile
        estimator (same shape as the predictor's pooled_stats)."""
        out = {}
        with self._lock:
            for signal, pooled in self._pooled.items():
                out[signal] = {
                    "count": pooled.count,
                    "mean": (round(pooled.total / pooled.count, 2)
                             if pooled.count else None),
                    "p50": (round(pooled.quantile(0.5), 2)
                            if pooled.count else None),
                    "p95": (round(pooled.quantile(0.95), 2)
                            if pooled.count else None),
                }
        return out


def decode_rates(value: Optional[str]) -> "dict[str, float]":
    """``ecc=12.5,link-flap=0.4`` -> {signal: events/hour} (unknown
    families and malformed entries are dropped — the predictor's
    decode_durations contract)."""
    out: dict[str, float] = {}
    if not value:
        return out
    for entry in value.split(","):
        signal, sep, raw = entry.partition("=")
        if not sep or signal not in SIGNALS:
            continue
        try:
            out[signal] = float(raw)
        except ValueError:
            continue
    return out


def encode_rates(rates: "dict[str, float]") -> str:
    return ",".join(f"{signal}={rates[signal]:g}"
                    for signal in SIGNALS if signal in rates)
