"""TPU-native health gates.

Two gates that replace the reference's OFED/RDMA-specific concerns
(docs/automatic-ofed-upgrade.md) with their TPU equivalents:

- ``ici_probe``: a JAX/XLA collective probe that proves the ICI fabric of a
  slice is healthy after a libtpu upgrade, plugged into the validation
  state via the ValidationManager ``extra_validator`` seam
  (SURVEY.md §5 "distributed communication backend").
- ``checkpoint_gate``: an Orbax checkpoint-durability check that blocks
  eviction of a live JAX training job until its latest checkpoint is
  committed to durable storage (BASELINE config #4).
- ``serving_gate``: the serving-side counterpart — park new requests,
  finish in-flight generations, then admit eviction, so a rolling
  upgrade over a decode fleet drops zero generations.
- ``precursor``: the predictive side — hardware-health counter signals
  and the online failure-precursor model that condemns a node AT RISK
  (and routes its slice around it) before the hardware dies.
"""

from tpu_operator_libs.health.ici_probe import (  # noqa: F401
    FabricProbeResult,
    ICIFabricValidator,
    fabric_probe,
    fabric_probe_topology,
    make_mesh,
    single_chip_probe,
)
from tpu_operator_libs.health.checkpoint_gate import (  # noqa: F401
    CheckpointDurabilityGate,
    latest_committed_step,
)
from tpu_operator_libs.health.serving_gate import (  # noqa: F401
    ServingDrainGate,
    ServingEndpoint,
)
from tpu_operator_libs.health.precursor import (  # noqa: F401
    FailurePrecursorModel,
    NodeHealthSignal,
    PrecursorVerdict,
)
