"""Auto-remediation: detect and recover wedged TPU nodes.

The planned-upgrade machine (:mod:`tpu_operator_libs.upgrade`) chooses
its disruptions; this package handles the ones the hardware chooses —
NotReady kubelets, crash-looping libtpu pods, stuck-Terminating
workloads, device-plugin health conditions. Detection
(:mod:`.detectors`) turns those signals into durable wedge facts on the
node; the unplanned-fault state machine (:mod:`.state_machine`) drives
each confirmed-wedged node through an escalation ladder — quarantine →
drain → runtime restart → host reboot → revalidate — with every
transition committed as a node label, so a crashed operator resumes
mid-remediation exactly like the upgrade flow does
(upgrade_state.go:68-72).
"""

from tpu_operator_libs.remediation.detectors import (  # noqa: F401
    NodeConditionDetector,
    NodeNotReadyDetector,
    RuntimePodCrashLoopDetector,
    StuckTerminatingDetector,
    WedgeDetectorChain,
    WedgeSignal,
    default_detector_chain,
)
from tpu_operator_libs.remediation.state_machine import (  # noqa: F401
    AnnotationRebooter,
    NodeRemediationManager,
    RemediationSnapshot,
)
