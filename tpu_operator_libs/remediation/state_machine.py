"""NodeRemediationManager — the unplanned-fault state machine.

The dual of :class:`~tpu_operator_libs.upgrade.state_manager.
ClusterUpgradeStateManager`: that machine schedules disruptions on
healthy nodes; this one recovers nodes the hardware already disrupted.
One reconcile is:

1. ``build_state``: snapshot every managed node + its runtime pod,
   bucketed by the node's remediation-state label.
2. ``apply_state``: one pass over the buckets in fixed order, moving
   each node at most one transition along the graph
   (consts.REMEDIATION_EDGES):

   healthy ──(signal persisted past grace)──────────→ wedged
   wedged ─┬─(signal cleared, nothing dispatched)──→ healthy
           ├─(attempt budget exhausted)────────────→ remediation-failed
           └─(slot available)──────────────────────→ cordon-required
   cordon-required ─(cordoned, upgrade flow parked)→ drain-required
   drain-required ─┬─(attempt ≤ restart rungs)─────→ runtime-restart
                   ├─(rungs exhausted, rebooter)───→ reboot-required
                   └─(no action applicable)────────→ remediation-failed
   runtime-restart ─(pod recreated & ready)────────→ revalidate
                    (timeout → wedged, attempt consumed)
   reboot-required ─(node Ready again)─────────────→ revalidate
                    (timeout → wedged, attempt consumed)
   revalidate ─┬─(clear for settle window + gate)──→ uncordon | healthy
               └─(signal returned past timeout)────→ wedged
   uncordon-required ─(uncordoned)─────────────────→ healthy
   remediation-failed ─┬─(out-of-band fix | re-arm)→ revalidate
                       └─(condemned slice member,
                          reconfiguration enabled)─→ reconfigure-required
   reconfigure-required ─┬─(slice released: spare
                            remap | degraded admit)→ remediation-failed
                         └─(manual re-arm)─────────→ revalidate
   healthy ─(precursor verdict, budget admitted)───→ at-risk
   at-risk ─┬─(risk subsided before the join)──────→ healthy
            ├─(wedge signal: hardware beat us)─────→ wedged
            └─(slice released; planned drain done)─→ remediation-failed

Durability model is identical to the upgrade machine: the node label is
the commit point, every decision re-derives from the snapshot, and the
escalation ladder's rung pointer (the attempt annotation), debounce
stamps, and action handshakes are all node annotations — a crashed
operator resumes mid-remediation for free (upgrade_state.go:68-72).

Coordination with the planned-upgrade machine is explicit and two-way:
detection never confirms a wedge on a node the upgrade machine is
actively moving (its failure handling owns mid-rollout breakage), and a
node under remediation carries the upgrade ``skip`` label from cordon
until recovery, so a rollout starting mid-remediation routes around it.
"""

from __future__ import annotations

import contextlib
import logging
from dataclasses import dataclass, field, replace
from typing import (
    TYPE_CHECKING,
    Callable,
    Iterator,
    Mapping,
    Optional,
    Protocol,
)

from tpu_operator_libs.api.remediation_policy import RemediationPolicySpec
from tpu_operator_libs.api.upgrade_policy import (
    scaled_value_from_int_or_percent,
)
from tpu_operator_libs.consts import (
    GKE_NODEPOOL_LABEL,
    IN_PROGRESS_STATES,
    REMEDIATION_ALL_STATES,
    REMEDIATION_IN_PROGRESS_STATES,
    TPU_RESOURCE_NAME,
    TRUE_STRING,
    RemediationKeys,
    RemediationState,
    UpgradeKeys,
    UpgradeState,
)
from tpu_operator_libs.k8s.client import (
    ApiServerError,
    ConflictError,
    K8sClient,
    NotFoundError,
)
from tpu_operator_libs.k8s.drain import DrainError, DrainHelper
from tpu_operator_libs.k8s.objects import Node, Pod
from tpu_operator_libs.k8s.selectors import selector_from_labels
from tpu_operator_libs.remediation.detectors import (
    WedgeDetector,
    default_detector_chain,
)
from tpu_operator_libs.upgrade.cordon_manager import CordonManager
from tpu_operator_libs.upgrade.gate import EvictionGate, GateKeeper
from tpu_operator_libs.upgrade.state_provider import (
    NodeUpgradeStateProvider,
)
from tpu_operator_libs.upgrade.validation_manager import NodeValidator
from tpu_operator_libs.util import Clock, Event, EventRecorder, log_event

if TYPE_CHECKING:
    from tpu_operator_libs.health.precursor import FailurePrecursorModel
    from tpu_operator_libs.topology.reconfigurer import SliceReconfigurer
    from tpu_operator_libs.upgrade.nudger import ReconcileNudger

#: Telemetry seam for the predictive arc: () -> {node name: {signal
#: family: cumulative count}} — the operator-side read of whatever
#: NodeHealthSignal sources the deployment runs.
PrecursorSource = Callable[[], Mapping[str, Mapping[str, int]]]

logger = logging.getLogger(__name__)


class NodeRebooter(Protocol):
    """Escalation seam: ask the infrastructure to power-cycle a node.

    Implementations range from stamping an annotation a privileged host
    agent watches (:class:`AnnotationRebooter`, the default contract) to
    calling a cloud instance API. ``request_reboot`` must be idempotent
    per node — the machine guards re-requests with a handshake
    annotation, but a crashed pass may replay one request.
    """

    def request_reboot(self, node: Node) -> None:
        """Initiate a reboot of ``node``; returns immediately."""
        ...


class AnnotationRebooter:
    """Default rebooter: records the request as a node annotation.

    The deployment contract: a privileged DaemonSet agent on each host
    watches its own node for ``keys.reboot_requested_annotation`` and
    executes the reboot out-of-band. The machine detects completion by
    the node turning Ready again, not by anything the agent writes, so
    the agent side stays trivial.
    """

    def __init__(self, provider: NodeUpgradeStateProvider,
                 keys: RemediationKeys, clock: Optional[Clock] = None,
                 ) -> None:
        self._provider = provider
        self._keys = keys
        self._clock = clock or Clock()

    def request_reboot(self, node: Node) -> None:
        self._provider.change_node_upgrade_annotation(
            node, self._keys.reboot_requested_annotation,
            str(int(self._clock.now())))


@dataclass
class NodeRemediationState:
    """A managed node and the runtime pod on it (None when the pod is
    gone — possible for a node wedged long enough for pod GC)."""

    node: Node
    runtime_pod: Optional[Pod]


@dataclass
class RemediationSnapshot:
    """Snapshot of the managed fleet bucketed by remediation state.

    Carries the runtime namespace + labels it was built from so
    pass-scoped consumers (the SliceReconfigurer resolving the runtime
    DaemonSet) need no side channel."""

    node_states: dict[str, list[NodeRemediationState]] = field(
        default_factory=dict)
    namespace: str = ""
    runtime_labels: dict[str, str] = field(default_factory=dict)

    def bucket(self, state: RemediationState | str,
               ) -> list[NodeRemediationState]:
        return self.node_states.get(str(state), [])

    def total_nodes(self) -> int:
        return sum(len(v) for v in self.node_states.values())

    def in_progress(self) -> int:
        return sum(len(self.bucket(s))
                   for s in REMEDIATION_IN_PROGRESS_STATES)

    def unavailable_nodes(self) -> int:
        """Cordoned or NotReady nodes across all buckets (same
        definition as the upgrade machine's availability budget,
        upgrade_state.go:192-211)."""
        return sum(
            1 for bucket in self.node_states.values() for ns in bucket
            if ns.node.is_unschedulable() or not ns.node.is_ready())


class NodeRemediationManager:
    """The unplanned-fault state machine hub."""

    def __init__(self, client: K8sClient,
                 keys: Optional[RemediationKeys] = None,
                 upgrade_keys: Optional[UpgradeKeys] = None,
                 detector: Optional[WedgeDetector] = None,
                 rebooter: Optional[NodeRebooter] = None,
                 validator: Optional[NodeValidator] = None,
                 recorder: Optional[EventRecorder] = None,
                 clock: Optional[Clock] = None,
                 provider: Optional[NodeUpgradeStateProvider] = None,
                 sync_timeout: float = 10.0,
                 poll_interval: float = 1.0,
                 nudger: Optional["ReconcileNudger"] = None,
                 reconfigurer: Optional["SliceReconfigurer"] = None,
                 precursor: Optional["FailurePrecursorModel"] = None,
                 precursor_source: Optional[PrecursorSource] = None,
                 eviction_gate: Optional[EvictionGate] = None,
                 ) -> None:
        self.keys = keys or RemediationKeys()
        # Completion-wakeup seam, shared with the upgrade machine (both
        # feed the same controller key): every durable deadline this
        # machine stamps — wedge-grace debounce, action timeouts, the
        # revalidation settle window — registers a precise wakeup so
        # expiry is acted on at expiry, not at the next resync.
        self.nudger = nudger
        self.client = client
        # With upgrade keys, the two machines actively coordinate:
        # detection defers to in-progress upgrades, and remediated
        # nodes carry the upgrade skip label until recovered.
        self.upgrade_keys = upgrade_keys
        self.recorder = recorder
        self.clock = clock or Clock()
        # The provider is the same durable-commit writer the upgrade
        # machine uses — RemediationKeys exposes the state_label /
        # event_reason surface it needs, so every remediation
        # transition gets the same visibility-wait and
        # optimistic-concurrency guarantees for free.
        self.provider = provider or NodeUpgradeStateProvider(
            client, self.keys,  # type: ignore[arg-type]
            recorder, self.clock,
            sync_timeout=sync_timeout, poll_interval=poll_interval)
        self.cordon_manager = CordonManager(client)
        self._explicit_detector = detector
        self.rebooter = rebooter if rebooter is not None else \
            AnnotationRebooter(self.provider, self.keys, self.clock)
        self.validator = validator
        # Degraded-slice reconfiguration seam (topology/reconfigurer.py):
        # drives condemned nodes through the reconfigure-required arc.
        # None = the pre-reconfiguration dead end (FAILED parks the
        # slice), regardless of policy.
        self.reconfigurer = reconfigurer
        # Predictive condemn-before-fail seams (health/precursor.py):
        # the online model plus the telemetry read that feeds it. Both
        # must be present (and policy.precursor.enable on) for the
        # at-risk arc to run — otherwise the machine stays reactive.
        self.precursor = precursor
        self.precursor_source = precursor_source
        # Serving-aware gate for the at-risk PLANNED drain (same
        # EvictionGate contract as the upgrade machine's drain path,
        # with the same park-don't-escalate GateKeeper semantics): the
        # at-risk node is still serving when its slice is released, so
        # eviction waits for in-flight work to finish. The REACTIVE
        # drain rungs never consult it — their pods are already dead.
        self._at_risk_gatekeeper = GateKeeper(
            self.keys, recorder,  # type: ignore[arg-type]
            "at-risk drain")
        self._at_risk_gatekeeper.set_gate(eviction_gate)
        # Set per apply_state pass from policy.reconfiguration: when
        # True, nodes parked in the upgrade machine's terminal FAILED
        # state are eligible for wedge detection/triage (the upgrade
        # machine holds its own FAILED recovery while the remediation
        # skip label is on the node, so only one machine drives it).
        self._takeover_failed_upgrades = False
        self._poll_interval = poll_interval
        # fleet counters (exported via metrics.observe_remediation)
        self.wedged_detected_total = 0
        self.remediations_succeeded_total = 0
        self.remediations_failed_total = 0
        self.runtime_restarts_total = 0
        self.reboots_requested_total = 0
        # predictive-arc counters (exported via metrics.observe_precursor)
        self.at_risk_condemned_total = 0
        self.at_risk_aborted_total = 0
        self.at_risk_parked_total = 0
        self.at_risk_budget_deferrals_total = 0
        self._recovery_seconds: list[float] = []
        self._transient_deferrals = 0
        self.last_pass_deferrals = 0
        # Sharded control plane (k8s/sharding.py): ownership view
        # shared with the upgrade machine. None = single-owner.
        self._shard_view = None

    def with_sharding(self, view: "object") -> "NodeRemediationManager":
        """Install (or clear) the sharded-control-plane ownership view:
        ``build_state`` keeps only nodes whose shard this replica owns,
        and the provider + cordon manager fence their durable writes
        (same contract as the upgrade machine's ``with_sharding``).
        Budgets (maxConcurrent, maxUnavailable) then apply to the
        PARTITION — remediation quarantines already-broken nodes, so a
        per-partition budget errs conservative rather than unsafe."""
        self._shard_view = view
        fence = view.fence if view is not None else None
        with_fence = getattr(self.provider, "with_fence", None)
        if with_fence is not None:
            with_fence(fence)
        self.cordon_manager.with_fence(fence)
        return self

    # ------------------------------------------------------------------
    # snapshot
    # ------------------------------------------------------------------
    def build_state(self, namespace: str,
                    runtime_labels: dict[str, str]) -> RemediationSnapshot:
        """Snapshot managed nodes + runtime pods into state buckets.

        A node is managed when it runs a runtime pod, carries the TPU
        resource label, or already has a remediation state — the last
        arm keeps a node whose pods were GC'd mid-remediation from
        silently leaving the machine.
        """
        snapshot = RemediationSnapshot(
            namespace=namespace, runtime_labels=dict(runtime_labels))
        selector = selector_from_labels(runtime_labels)
        pods_by_node: dict[str, Pod] = {}
        for pod in self.client.list_pods(namespace=namespace,
                                         label_selector=selector):
            if pod.spec.node_name:
                pods_by_node.setdefault(pod.spec.node_name, pod)
        for node in self.client.list_nodes():
            label = node.metadata.labels.get(self.keys.state_label, "")
            pod = pods_by_node.get(node.metadata.name)
            if pod is None and not label \
                    and TPU_RESOURCE_NAME not in node.metadata.labels:
                continue
            if self._shard_view is not None and not self._shard_view.owns(
                    node.metadata.name,
                    node.metadata.labels.get(GKE_NODEPOOL_LABEL, "")):
                # ownership-filtered snapshot: another replica's shard
                continue
            snapshot.node_states.setdefault(label, []).append(
                NodeRemediationState(node=node, runtime_pod=pod))
        return snapshot

    # ------------------------------------------------------------------
    # apply_state
    # ------------------------------------------------------------------
    def apply_state(self, snapshot: RemediationSnapshot,
                    policy: Optional[RemediationPolicySpec]) -> None:
        """One transition pass. Transient cluster errors defer only the
        affected node (the upgrade machine's per-node isolation,
        state_manager._defer_node_on_transient); hard errors abort the
        pass for the caller to retry."""
        if snapshot is None:
            raise ValueError("snapshot should not be empty")
        self.last_pass_deferrals = 0
        if policy is None or not policy.enable:
            logger.info("auto remediation is disabled, skipping")
            return
        logger.info("remediation states: %s", {
            str(s) or "healthy": len(snapshot.bucket(s))
            for s in REMEDIATION_ALL_STATES})
        reconfig = policy.reconfiguration
        reconfig_active = (reconfig is not None and reconfig.enable
                          and self.reconfigurer is not None)
        self._takeover_failed_upgrades = (
            reconfig_active and reconfig.take_over_failed_upgrades)
        if reconfig_active:
            self.reconfigurer.begin_pass(snapshot)
        detector = self._detector_for_policy(policy)
        self.process_healthy_nodes(snapshot, detector)
        self.process_precursor_signals(snapshot, policy)
        self.process_at_risk_nodes(snapshot, policy, detector)
        self.process_wedged_nodes(snapshot, policy, detector)
        self.process_cordon_required_nodes(snapshot)
        self.process_drain_required_nodes(snapshot, policy)
        self.process_restart_required_nodes(snapshot, policy)
        self.process_reboot_required_nodes(snapshot, policy)
        self.process_revalidate_required_nodes(snapshot, policy, detector)
        self.process_uncordon_required_nodes(snapshot)
        self.process_failed_nodes(snapshot, detector, policy)
        self.process_reconfigure_required_nodes(snapshot, policy)
        if reconfig_active:
            # settle-stamp expiry + degraded-slice healing ride the same
            # pass; transient errors defer to the next reconcile
            try:
                self.reconfigurer.reconcile_extras(snapshot, reconfig)
            except (ApiServerError, ConflictError, NotFoundError) as exc:
                logger.warning("transient cluster error during slice-"
                               "reconfiguration follow-through; deferring "
                               "to the next reconcile: %s", exc)
                self._transient_deferrals += 1
                self.last_pass_deferrals += 1
        logger.info("remediation manager finished processing")

    def _detector_for_policy(self, policy: RemediationPolicySpec,
                             ) -> WedgeDetector:
        if self._explicit_detector is not None:
            return self._explicit_detector
        return default_detector_chain(policy.detection)

    @contextlib.contextmanager
    def _defer_node_on_transient(self, node: Node,
                                 action: str) -> Iterator[None]:
        try:
            yield
        except (ApiServerError, ConflictError, NotFoundError) as exc:
            logger.warning(
                "transient cluster error during %s for node %s; "
                "deferring the node to the next reconcile: %s",
                action, node.metadata.name, exc)
            self._transient_deferrals += 1
            self.last_pass_deferrals += 1

    # ------------------------------------------------------------------
    # per-state processors
    # ------------------------------------------------------------------
    def process_healthy_nodes(self, snapshot: RemediationSnapshot,
                              detector: WedgeDetector) -> None:
        """Detection with durable debounce: first sighting stamps the
        wedge-first-seen annotation; the wedge is confirmed (node →
        wedged) only once the signal has persisted past the detector's
        grace window. A cleared signal erases the stamp."""
        now = self.clock.now()
        for ns in snapshot.bucket(RemediationState.HEALTHY):
            node = ns.node
            with self._defer_node_on_transient(node, "wedge detection"):
                if self._skip_remediation(node):
                    continue
                if self._upgrade_in_progress(node):
                    # mid-rollout breakage belongs to the upgrade
                    # machine's own failure handling
                    continue
                signal = detector(node, ns.runtime_pod, now)
                since_raw = node.metadata.annotations.get(
                    self.keys.wedge_since_annotation)
                if signal is None:
                    # clear the debounce stamp AND any wedge-reason
                    # residue: a crash between the reason stamp and the
                    # WEDGED commit leaves a healthy-labeled node with a
                    # reason annotation that nothing else ever deletes
                    # (found by the chaos harness, seed 16)
                    stale = {
                        key: None for key in (
                            self.keys.wedge_since_annotation,
                            self.keys.wedge_reason_annotation)
                        if key in node.metadata.annotations}
                    if stale:
                        self.provider.change_node_upgrade_annotations(
                            node, stale)
                    continue
                if since_raw is None:
                    self.provider.change_node_upgrade_annotation(
                        node, self.keys.wedge_since_annotation,
                        str(int(now)))
                    since = now
                else:
                    since = float(since_raw)
                if now - since < signal.grace_seconds:
                    if self.nudger is not None:
                        # confirm the wedge at grace expiry, not at
                        # whenever the next pass happens to run
                        self.nudger.nudge_at(
                            since + signal.grace_seconds, "wedge-grace")
                    continue
                self.provider.change_node_upgrade_annotation(
                    node, self.keys.wedge_reason_annotation, signal.reason)
                if self.provider.change_node_upgrade_state(
                        node, RemediationState.WEDGED):
                    self.wedged_detected_total += 1
                    logger.warning("node %s confirmed wedged: %s",
                                   node.metadata.name, signal.detail)
                    log_event(self.recorder, node, Event.WARNING,
                              self.keys.event_reason,
                              f"Node wedged ({signal.reason}): "
                              f"{signal.detail}")

    def process_precursor_signals(self, snapshot: RemediationSnapshot,
                                  policy: RemediationPolicySpec) -> None:
        """Predictive detection (condemn-before-fail): feed every
        healthy node's hardware-health counters to the
        FailurePrecursorModel, keep each node's durable model seed
        current, and commit ``at-risk`` verdicts under the fleet-wide
        condemnation budget. The verdict stamp and its evidence ride
        the SAME merge patch as the state commit — a crash between
        "decided" and "stamped" is impossible, so a fresh incarnation
        resumes the arc from annotations alone."""
        spec = policy.precursor
        reconfig = policy.reconfiguration
        if (spec is None or not spec.enable
                or self.precursor is None
                or self.precursor_source is None
                or reconfig is None or not reconfig.enable
                or self.reconfigurer is None):
            return
        try:
            counters_by_node = self.precursor_source()
        except Exception as exc:  # noqa: BLE001 — telemetry seam boundary
            logger.warning("precursor source raised; skipping the "
                           "predictive pass: %s", exc)
            return
        now = self.clock.now()
        budget = scaled_value_from_int_or_percent(
            spec.max_at_risk, snapshot.total_nodes(), round_up=True)
        # Every node carrying the at-risk stamp counts — in-flight AND
        # parked — so a signal storm drains at most the budget's worth
        # of capacity until repaired nodes are re-armed.
        at_risk = sum(
            1 for bucket in snapshot.node_states.values() for ns in bucket
            if self.keys.at_risk_annotation
            in ns.node.metadata.annotations)
        # AT_RISK nodes stay under observation too: their counters
        # must keep feeding the model or cleared() could never fire
        # and the stand-down path would be unreachable — but only
        # HEALTHY nodes are eligible for a NEW verdict.
        observed = list(snapshot.bucket(RemediationState.HEALTHY)) \
            + list(snapshot.bucket(RemediationState.AT_RISK))
        for ns in observed:
            node = ns.node
            with self._defer_node_on_transient(node,
                                               "precursor observation"):
                counters = counters_by_node.get(node.metadata.name)
                if counters is None:
                    continue
                updates = self.precursor.observe(
                    node.metadata.name, counters, now=now,
                    annotations=node.metadata.annotations)
                if updates:
                    # durable per-node model seed: a fresh incarnation
                    # resumes the model from cluster state alone
                    self.provider.change_node_upgrade_annotations(
                        node, updates)
                if self.keys.at_risk_annotation \
                        in node.metadata.annotations:
                    continue
                if self._skip_remediation(node) \
                        or self._upgrade_in_progress(node):
                    continue
                verdict = self.precursor.verdict(node.metadata.name)
                if verdict is None:
                    continue
                if not node.metadata.labels.get(GKE_NODEPOOL_LABEL):
                    # no slice to route around it; the reactive ladder
                    # will handle the death when (if) it comes
                    continue
                if at_risk >= budget:
                    self.at_risk_budget_deferrals_total += 1
                    logger.info(
                        "deferring at-risk condemnation of node %s: "
                        "%d/%d at-risk budget already committed",
                        node.metadata.name, at_risk, budget)
                    continue
                if self.provider.change_node_upgrade_state(
                        node, RemediationState.AT_RISK, annotations={
                            self.keys.at_risk_annotation: str(int(now)),
                            self.keys.at_risk_reason_annotation:
                                verdict.reason,
                        }):
                    at_risk += 1
                    self.at_risk_condemned_total += 1
                    logger.warning("node %s condemned AT RISK: %s",
                                   node.metadata.name, verdict.detail)
                    log_event(self.recorder, node, Event.WARNING,
                              "NodeAtRisk",
                              f"Precursor model condemned the node at "
                              f"risk ({verdict.detail}); remapping its "
                              f"slice to a spare while it still serves")

    def process_at_risk_nodes(self, snapshot: RemediationSnapshot,
                              policy: RemediationPolicySpec,
                              detector: WedgeDetector) -> None:
        """Drive condemned-at-risk nodes through the reconfigure arc
        WHILE THEY STILL SERVE: reserve a spare, wait for it to
        provision, join it in the node's place — and only then cordon,
        drain (planned, through the serving-aware eviction gate) and
        park the node ``remediation-failed`` with the condemned stamp.
        A node whose risk subsides before the join stands down to
        healthy with zero residue; a node whose hardware beats the
        planned drain falls to the reactive wedge ladder, which resumes
        the remap from the durable reservation."""
        from tpu_operator_libs.topology.reconfigurer import RELEASED

        now = self.clock.now()
        reconfig = policy.reconfiguration
        spec = policy.precursor
        reconfig_active = (reconfig is not None and reconfig.enable
                          and self.reconfigurer is not None)
        precursor_active = spec is not None and spec.enable
        for ns in snapshot.bucket(RemediationState.AT_RISK):
            node = ns.node
            with self._defer_node_on_transient(node,
                                               "at-risk condemnation"):
                signal = detector(node, ns.runtime_pod, now)
                if signal is not None:
                    # The hardware beat the planned drain. No grace
                    # window — the precursor already distrusts this
                    # node. The reservation (if stamped) is durable, so
                    # the reactive condemnation arc resumes the remap.
                    self.provider.change_node_upgrade_annotations(node, {
                        self.keys.wedge_since_annotation: str(int(now)),
                        self.keys.wedge_reason_annotation: signal.reason,
                    })
                    if self.provider.change_node_upgrade_state(
                            node, RemediationState.WEDGED):
                        self.wedged_detected_total += 1
                        logger.warning(
                            "at-risk node %s hard-failed before its "
                            "planned drain (%s); reactive ladder takes "
                            "over", node.metadata.name, signal.detail)
                        log_event(self.recorder, node, Event.WARNING,
                                  self.keys.event_reason,
                                  f"At-risk node wedged before its "
                                  f"planned drain ({signal.reason})")
                    continue
                if not reconfig_active or not precursor_active:
                    # policy flipped off mid-arc: the node was healthy
                    # all along — stand down with zero residue
                    self._abort_at_risk(node, "predictive condemnation "
                                              "disabled")
                    continue
                if self.precursor is not None \
                        and self.precursor.cleared(node.metadata.name) \
                        and not self.reconfigurer.remap_committed(node):
                    self._abort_at_risk(node, "precursor risk subsided")
                    continue
                # Drive the remap while the node serves. Degraded
                # admission is never allowed from here: the node is
                # ALIVE — cutting the slice to a short shape would
                # trade real capacity for a prediction. No spare means
                # the node simply keeps serving at risk.
                if self.reconfigurer.advance(
                        ns, replace(reconfig, allow_degraded=False)) \
                        != RELEASED:
                    continue
                # Slice released (spare joined in its place): now the
                # node leaves service as a PLANNED disruption — cordon,
                # park the upgrade flow, gated drain, condemned stamp.
                # Every step is idempotent; a crash anywhere resumes
                # here because advance() short-circuits to RELEASED
                # once the node has no pool.
                self.cordon_manager.cordon(node)
                self._park_upgrade_flow(node, parked=True)
                if not self._planned_drain_done(node, policy):
                    continue  # gate parked or drain failed; retry next pass
                if self.provider.change_node_upgrade_state(
                        node, RemediationState.FAILED, annotations={
                            self.keys.condemned_annotation:
                                str(int(now)),
                        }):
                    self.at_risk_parked_total += 1
                    self.remediations_failed_total += 1
                    reason = node.metadata.annotations.get(
                        self.keys.at_risk_reason_annotation, "unknown")
                    logger.warning(
                        "node %s drained and parked condemned-at-risk "
                        "(%s); slice already remapped",
                        node.metadata.name, reason)
                    log_event(self.recorder, node, Event.WARNING,
                              "NodeCondemned",
                              f"At-risk node drained (planned) and "
                              f"parked for repair ({reason}); slice "
                              f"already routed to a spare")

    def _abort_at_risk(self, node: Node, why: str) -> None:
        """Stand the at-risk arc down: drop the spare booking and
        return the node to healthy. The stamp removals ride the state
        commit in ONE merge patch — a crash can never leave a
        healthy-labeled node holding at-risk residue."""
        if self.reconfigurer is not None:
            self.reconfigurer.abort(node)
        if self.provider.change_node_upgrade_state(
                node, RemediationState.HEALTHY, annotations={
                    self.keys.at_risk_annotation: None,
                    self.keys.at_risk_reason_annotation: None,
                }):
            self.at_risk_aborted_total += 1
            logger.info("node %s at-risk arc stood down: %s",
                        node.metadata.name, why)
            log_event(self.recorder, node, Event.NORMAL,
                      self.keys.event_reason,
                      f"At-risk condemnation stood down ({why})")

    def _planned_drain_done(self, node: Node,
                            policy: RemediationPolicySpec) -> bool:
        """Planned (serving-aware) drain of an at-risk node. Unlike the
        reactive drain rung this one consults the eviction gate with
        park-don't-escalate semantics: the node's endpoints stop
        admitting, in-flight work finishes, and only then are the pods
        evicted — the zero-drop property the soak invariant checks."""
        spec = policy.drain
        if spec is not None and spec.enable:
            helper = DrainHelper(
                client=self.client, force=spec.force,
                delete_empty_dir_data=spec.delete_empty_dir,
                timeout_seconds=spec.timeout_seconds,
                pod_selector=spec.pod_selector,
                clock=self.clock, poll_interval=self._poll_interval)
        else:
            # eviction is the point of the at-risk park, so the planned
            # drain runs even when the reactive drain stage is disabled
            helper = DrainHelper(client=self.client, force=True,
                                 clock=self.clock,
                                 poll_interval=self._poll_interval)
        name = node.metadata.name
        if self._at_risk_gatekeeper.gate is not None:
            try:
                pods, _ = helper.get_pods_for_deletion(name)
            except (ApiServerError, ConflictError, NotFoundError) as exc:
                logger.warning("could not enumerate pods for the "
                               "at-risk gate on node %s; deferring: %s",
                               name, exc)
                return False
            if not self._at_risk_gatekeeper.allows(node, pods):
                return False
        try:
            helper.run_node_drain(name)
        except DrainError as exc:
            logger.warning("planned drain of at-risk node %s failed "
                           "(will retry): %s", name, exc)
            return False
        return True

    def process_wedged_nodes(self, snapshot: RemediationSnapshot,
                             policy: RemediationPolicySpec,
                             detector: WedgeDetector) -> None:
        """Triage the quarantine queue: self-healed nodes go back to
        healthy, exhausted nodes park as failed, and the rest are
        admitted under the concurrency + availability budgets."""
        now = self.clock.now()
        total = snapshot.total_nodes()
        in_progress = snapshot.in_progress()
        slots = (len(snapshot.bucket(RemediationState.WEDGED))
                 if policy.max_concurrent == 0
                 else max(0, policy.max_concurrent - in_progress))
        max_unavailable = total
        if policy.max_unavailable is not None:
            max_unavailable = scaled_value_from_int_or_percent(
                policy.max_unavailable, total, round_up=True)
        unavailable = snapshot.unavailable_nodes()
        for ns in snapshot.bucket(RemediationState.WEDGED):
            node = ns.node
            with self._defer_node_on_transient(node, "wedge triage"):
                attempts = self._attempts_used(node)
                if attempts == 0 \
                        and detector(node, ns.runtime_pod, now) is None:
                    # self-healed before any recovery action ran
                    if self.reconfigurer is not None \
                            and self.keys.at_risk_annotation \
                            in node.metadata.annotations:
                        # an at-risk arc funneled here, then the node
                        # self-healed: drop the spare booking before
                        # the bookkeeping (and its stamps) go
                        self.reconfigurer.abort(node)
                    self._clear_bookkeeping(node)
                    self.provider.change_node_upgrade_state(
                        node, RemediationState.HEALTHY)
                    logger.info("node %s wedge cleared on its own",
                                node.metadata.name)
                    continue
                if attempts >= policy.max_attempts:
                    self._mark_failed(
                        node, f"attempt budget exhausted "
                              f"({attempts}/{policy.max_attempts})")
                    continue
                if self._skip_remediation(node):
                    continue
                if self._upgrade_in_progress(node):
                    # The upgrade machine took the node between wedge
                    # confirmation and this triage (both can happen in
                    # one reconcile cycle): admitting now would have two
                    # machines driving one node — the upgrade's uncordon
                    # would fire mid-quarantine (found by the chaos
                    # harness, seed 132). Mid-rollout breakage belongs
                    # to the upgrade machine's own failure handling;
                    # this node waits in the quarantine queue.
                    continue
                if slots <= 0:
                    continue
                live = node.is_ready() and not node.is_unschedulable()
                if live and unavailable >= max_unavailable:
                    # quarantining a still-serving node would breach the
                    # availability budget; dead nodes are exempt (they
                    # already count as unavailable)
                    logger.info(
                        "deferring remediation of live node %s: "
                        "%d/%d nodes already unavailable",
                        node.metadata.name, unavailable, max_unavailable)
                    continue
                if attempts == 0 and node.is_unschedulable():
                    # remember the pre-remediation cordon so the node is
                    # not uncordoned at the end; only on FIRST admission
                    # — a re-admission after a failed attempt sees the
                    # cordon this machine itself applied
                    self.provider.change_node_upgrade_annotation(
                        node, self.keys.initial_state_annotation,
                        TRUE_STRING)
                if self.provider.change_node_upgrade_state(
                        node, RemediationState.CORDON_REQUIRED):
                    slots -= 1
                    if live:
                        unavailable += 1
                    logger.info("node %s admitted for remediation",
                                node.metadata.name)
                    log_event(self.recorder, node, Event.NORMAL,
                              self.keys.event_reason,
                              "Remediation started (attempt "
                              f"{attempts + 1}/{policy.max_attempts})")

    def process_cordon_required_nodes(
            self, snapshot: RemediationSnapshot) -> None:
        for ns in snapshot.bucket(RemediationState.CORDON_REQUIRED):
            node = ns.node
            with self._defer_node_on_transient(node, "quarantine cordon"):
                self.cordon_manager.cordon(node)
                self._park_upgrade_flow(node, parked=True)
                self.provider.change_node_upgrade_state(
                    node, RemediationState.DRAIN_REQUIRED)

    def process_drain_required_nodes(self, snapshot: RemediationSnapshot,
                                     policy: RemediationPolicySpec) -> None:
        """Evict workloads (when configured), then dispatch the next
        recovery rung. The drain runs inline — remediation throughput is
        bounded by the concurrency budget, not by drain parallelism, and
        an inline drain keeps the pass deterministic."""
        for ns in snapshot.bucket(RemediationState.DRAIN_REQUIRED):
            node = ns.node
            with self._defer_node_on_transient(node, "quarantine drain"):
                spec = policy.drain
                if spec is not None and spec.enable:
                    helper = DrainHelper(
                        client=self.client, force=spec.force,
                        delete_empty_dir_data=spec.delete_empty_dir,
                        timeout_seconds=spec.timeout_seconds,
                        pod_selector=spec.pod_selector,
                        clock=self.clock,
                        poll_interval=self._poll_interval)
                    try:
                        helper.run_node_drain(node.metadata.name)
                    except DrainError as exc:
                        # stay in drain-required; retried next pass
                        logger.warning("drain of node %s failed: %s",
                                       node.metadata.name, exc)
                        continue
                self._dispatch_recovery_action(ns, policy)

    def _dispatch_recovery_action(self, ns: NodeRemediationState,
                                  policy: RemediationPolicySpec) -> None:
        """Stamp the next attempt and route to its rung. Idempotent
        across crashes: a pass that stamped the attempt but died before
        the state transition re-enters here and reuses the stamp (the
        action-start annotation is the marker)."""
        node = ns.node
        started = node.metadata.annotations.get(
            self.keys.action_start_annotation)
        if started is None:
            attempt = self._attempts_used(node) + 1
            # ONE merge patch: the attempt counter and the action-start
            # stamp are indistinguishable crash markers when written
            # separately — a crash between the two writes would make the
            # resumed operator read the half-stamped attempt as a
            # previous (completed) one and bill the ladder twice.
            self.provider.change_node_upgrade_annotations(node, {
                self.keys.attempt_annotation: str(attempt),
                self.keys.action_start_annotation:
                    str(int(self.clock.now())),
            })
        else:
            attempt = self._attempts_used(node)
        use_restart = (attempt <= policy.restart_attempts
                       or self.rebooter is None)
        if use_restart and ns.runtime_pod is not None:
            self.provider.change_node_upgrade_state(
                node, RemediationState.RESTART_REQUIRED)
        elif self.rebooter is not None:
            self.provider.change_node_upgrade_state(
                node, RemediationState.REBOOT_REQUIRED)
        else:
            self._mark_failed(
                node, "no recovery action applicable "
                      "(no runtime pod to restart, no rebooter)")

    def process_restart_required_nodes(
            self, snapshot: RemediationSnapshot,
            policy: RemediationPolicySpec) -> None:
        """The cheap rung: delete the runtime pod so the DaemonSet
        controller recreates it. 'Recreated' is detected by UID change
        (recorded durably), so the check survives operator restarts."""
        now = self.clock.now()
        for ns in snapshot.bucket(RemediationState.RESTART_REQUIRED):
            node = ns.node
            with self._defer_node_on_transient(node, "runtime restart"):
                recorded = node.metadata.annotations.get(
                    self.keys.restart_pod_uid_annotation)
                if recorded is None:
                    old_uid = "gone"
                    if ns.runtime_pod is not None:
                        old_uid = ns.runtime_pod.metadata.uid
                        try:
                            self.client.delete_pod(
                                ns.runtime_pod.namespace,
                                ns.runtime_pod.name)
                        except NotFoundError:
                            pass  # already gone — that is the goal
                    self.provider.change_node_upgrade_annotation(
                        node, self.keys.restart_pod_uid_annotation,
                        old_uid)
                    self.runtime_restarts_total += 1
                    log_event(self.recorder, node, Event.NORMAL,
                              self.keys.event_reason,
                              "Runtime pod deleted for restart")
                    continue
                pod = ns.runtime_pod
                if pod is not None and pod.metadata.uid != recorded \
                        and pod.metadata.deletion_timestamp is None \
                        and pod.is_ready():
                    self.provider.change_node_upgrade_annotation(
                        node, self.keys.restart_pod_uid_annotation, None)
                    self.provider.change_node_upgrade_state(
                        node, RemediationState.REVALIDATE_REQUIRED)
                    continue
                self._maybe_action_timeout(
                    node, policy, now, "runtime restart",
                    extra_annotations=(
                        self.keys.restart_pod_uid_annotation,))

    def process_reboot_required_nodes(
            self, snapshot: RemediationSnapshot,
            policy: RemediationPolicySpec) -> None:
        """The escalation rung: one reboot request per attempt (guarded
        by the handshake annotation); completion is the node reporting
        Ready again."""
        now = self.clock.now()
        for ns in snapshot.bucket(RemediationState.REBOOT_REQUIRED):
            node = ns.node
            with self._defer_node_on_transient(node, "node reboot"):
                if self.rebooter is None:
                    # configuration changed mid-flight: write the
                    # attempt off rather than wait out the timeout
                    self._fail_attempt(node, "rebooter removed")
                    continue
                requested = node.metadata.annotations.get(
                    self.keys.reboot_requested_annotation)
                if requested is None:
                    self.rebooter.request_reboot(node)
                    if node.metadata.annotations.get(
                            self.keys.reboot_requested_annotation) is None:
                        # non-annotation rebooters (cloud APIs) do not
                        # stamp the handshake themselves
                        self.provider.change_node_upgrade_annotation(
                            node, self.keys.reboot_requested_annotation,
                            str(int(now)))
                    self.reboots_requested_total += 1
                    log_event(self.recorder, node, Event.WARNING,
                              self.keys.event_reason,
                              "Node reboot requested")
                    continue
                if node.is_ready():
                    self.provider.change_node_upgrade_annotation(
                        node, self.keys.reboot_requested_annotation, None)
                    self.provider.change_node_upgrade_state(
                        node, RemediationState.REVALIDATE_REQUIRED)
                    continue
                self._maybe_action_timeout(
                    node, policy, now, "reboot",
                    extra_annotations=(
                        self.keys.reboot_requested_annotation,))

    def process_revalidate_required_nodes(
            self, snapshot: RemediationSnapshot,
            policy: RemediationPolicySpec,
            detector: WedgeDetector) -> None:
        """The recovery gate: the wedge signal must stay clear for the
        settle window AND the optional validator (e.g. the ICI fabric
        probe) must pass. Signal flaps reset the window; flapping past
        the revalidation timeout writes the attempt off."""
        now = self.clock.now()
        for ns in snapshot.bucket(RemediationState.REVALIDATE_REQUIRED):
            node = ns.node
            with self._defer_node_on_transient(node, "revalidation"):
                signal = detector(node, ns.runtime_pod, now)
                settle_raw = node.metadata.annotations.get(
                    self.keys.settle_start_annotation)
                if signal is not None:
                    if settle_raw is not None:
                        self.provider.change_node_upgrade_annotation(
                            node, self.keys.settle_start_annotation, None)
                    self._maybe_action_timeout(
                        node, policy, now, "revalidation",
                        timeout=(policy.action_timeout_seconds
                                 + policy.revalidate_timeout_seconds))
                    continue
                if settle_raw is None:
                    self.provider.change_node_upgrade_annotation(
                        node, self.keys.settle_start_annotation,
                        str(int(now)))
                    if self.nudger is not None:
                        self.nudger.nudge_at(now + policy.settle_seconds,
                                             "remediation-settle")
                    continue
                if now - float(settle_raw) < policy.settle_seconds:
                    if self.nudger is not None:
                        self.nudger.nudge_at(
                            float(settle_raw) + policy.settle_seconds,
                            "remediation-settle")
                    continue
                if not self._validator_passes(node):
                    self._maybe_action_timeout(
                        node, policy, now, "revalidation",
                        timeout=(policy.action_timeout_seconds
                                 + policy.revalidate_timeout_seconds))
                    continue
                if self.keys.initial_state_annotation \
                        in node.metadata.annotations:
                    # node was cordoned before remediation began: leave
                    # the cordon, finish directly
                    self._finish_recovery(node)
                else:
                    self.provider.change_node_upgrade_state(
                        node, RemediationState.UNCORDON_REQUIRED)

    def process_uncordon_required_nodes(
            self, snapshot: RemediationSnapshot) -> None:
        for ns in snapshot.bucket(RemediationState.UNCORDON_REQUIRED):
            node = ns.node
            with self._defer_node_on_transient(node, "uncordon"):
                # stale-snapshot guard, same as the upgrade machine's
                # uncordon: never uncordon a node another pass moved on
                current = self.provider.get_node(node.metadata.name) \
                    .metadata.labels.get(self.keys.state_label, "")
                if current != str(RemediationState.UNCORDON_REQUIRED):
                    logger.warning(
                        "node %s is %r, not uncordon-required: snapshot "
                        "is stale; skipping uncordon",
                        node.metadata.name, current or "healthy")
                    continue
                self.cordon_manager.uncordon(node)
                self._finish_recovery(node)

    def process_failed_nodes(self, snapshot: RemediationSnapshot,
                             detector: WedgeDetector,
                             policy: Optional[RemediationPolicySpec] = None,
                             ) -> None:
        """Parked nodes re-enter revalidation when the wedge cleared
        out-of-band, or when an operator re-arms them (which also resets
        the attempt ladder). A node whose signal persists is CONDEMNED:
        the give-up is stamped durably and announced as a
        ``NodeCondemned`` Event (FAILED used to be a silent dead end
        neither the reconfigurer nor an operator watching ``kubectl get
        events`` could react to), and — with reconfiguration enabled —
        a condemned member of a named slice moves to
        ``reconfigure-required`` so the slice is routed around it."""
        now = self.clock.now()
        reconfig = policy.reconfiguration if policy is not None else None
        reconfig_active = (reconfig is not None and reconfig.enable
                          and self.reconfigurer is not None)
        for ns in snapshot.bucket(RemediationState.FAILED):
            node = ns.node
            with self._defer_node_on_transient(node, "failed-node triage"):
                rearmed = node.metadata.annotations.get(
                    self.keys.rearm_annotation) == TRUE_STRING
                if not rearmed and self.keys.at_risk_annotation \
                        in node.metadata.annotations:
                    # Parked condemned-at-risk: the hardware is
                    # PREDICTED to fail, so a currently-clear wedge
                    # signal is not evidence of health — only a manual
                    # re-arm (post-repair) returns the node to service.
                    continue
                if rearmed:
                    self.provider.change_node_upgrade_annotation(
                        node, self.keys.rearm_annotation, None)
                    self.provider.change_node_upgrade_annotation(
                        node, self.keys.attempt_annotation, None)
                elif detector(node, ns.runtime_pod, now) is not None:
                    if self.keys.condemned_annotation \
                            not in node.metadata.annotations:
                        self.provider.change_node_upgrade_annotation(
                            node, self.keys.condemned_annotation,
                            str(int(now)))
                        reason = node.metadata.annotations.get(
                            self.keys.wedge_reason_annotation, "unknown")
                        logger.error(
                            "node %s condemned: remediation exhausted "
                            "with wedge signal (%s) still present",
                            node.metadata.name, reason)
                        log_event(self.recorder, node, Event.WARNING,
                                  "NodeCondemned",
                                  f"Remediation gave the node up "
                                  f"({reason}); slice reconfiguration "
                                  f"or manual repair required")
                    if reconfig_active and node.metadata.labels.get(
                            GKE_NODEPOOL_LABEL):
                        if self.provider.change_node_upgrade_state(
                                node,
                                RemediationState.RECONFIGURE_REQUIRED):
                            logger.warning(
                                "condemned node %s entering slice "
                                "reconfiguration", node.metadata.name)
                    continue
                self.provider.change_node_upgrade_annotation(
                    node, self.keys.settle_start_annotation, None)
                self.provider.change_node_upgrade_state(
                    node, RemediationState.REVALIDATE_REQUIRED)
                logger.info("failed node %s re-entering revalidation%s",
                            node.metadata.name,
                            " (re-armed)" if rearmed else "")

    def process_reconfigure_required_nodes(
            self, snapshot: RemediationSnapshot,
            policy: RemediationPolicySpec) -> None:
        """Drive condemned slice members through the reconfigurer: once
        the slice is released (remapped onto a spare, or admitted as a
        documented degraded shape) the node parks back in FAILED — out
        of its slice, so planners and budgets stop paying for it. A
        re-arm aborts the remap and re-enters revalidation."""
        from tpu_operator_libs.topology.reconfigurer import RELEASED

        reconfig = policy.reconfiguration
        reconfig_active = (reconfig is not None and reconfig.enable
                          and self.reconfigurer is not None)
        for ns in snapshot.bucket(RemediationState.RECONFIGURE_REQUIRED):
            node = ns.node
            with self._defer_node_on_transient(node,
                                               "slice reconfiguration"):
                rearmed = node.metadata.annotations.get(
                    self.keys.rearm_annotation) == TRUE_STRING
                if rearmed:
                    if self.reconfigurer is not None:
                        self.reconfigurer.abort(node)
                    self.provider.change_node_upgrade_annotations(node, {
                        self.keys.rearm_annotation: None,
                        self.keys.attempt_annotation: None,
                        self.keys.settle_start_annotation: None,
                    })
                    self.provider.change_node_upgrade_state(
                        node, RemediationState.REVALIDATE_REQUIRED)
                    logger.info("node %s re-armed mid-reconfiguration; "
                                "remap aborted", node.metadata.name)
                    continue
                if not reconfig_active:
                    # policy flipped off mid-flight: the node returns to
                    # the plain parked state (its slice membership is
                    # whatever the remap got to)
                    self.provider.change_node_upgrade_state(
                        node, RemediationState.FAILED)
                    continue
                if self.reconfigurer.advance(ns, reconfig) == RELEASED:
                    self.provider.change_node_upgrade_state(
                        node, RemediationState.FAILED)

    # ------------------------------------------------------------------
    # helpers
    # ------------------------------------------------------------------
    def _attempts_used(self, node: Node) -> int:
        raw = node.metadata.annotations.get(self.keys.attempt_annotation)
        try:
            return int(raw) if raw is not None else 0
        except ValueError:
            logger.warning("node %s has malformed attempt annotation %r; "
                           "treating as 0", node.metadata.name, raw)
            return 0

    def _skip_remediation(self, node: Node) -> bool:
        return node.metadata.labels.get(
            self.keys.skip_label) == TRUE_STRING

    def _upgrade_in_progress(self, node: Node) -> bool:
        if self.upgrade_keys is None:
            return False
        state = node.metadata.labels.get(self.upgrade_keys.state_label, "")
        if state == str(UpgradeState.FAILED) \
                and self._takeover_failed_upgrades:
            # upgrade-failed is a PARKED state, not active motion: the
            # upgrade machine is waiting for pod health, which only this
            # machine's ladder can restore when the hardware is the
            # problem. It holds its FAILED recovery while the skip label
            # (set at quarantine cordon) is on the node, so the takeover
            # never has two machines driving one node.
            return False
        return state in {str(s) for s in IN_PROGRESS_STATES}

    def _park_upgrade_flow(self, node: Node, parked: bool) -> None:
        """Set/clear the upgrade machine's skip label so a rollout
        starting mid-remediation routes around the quarantined node."""
        if self.upgrade_keys is None:
            return
        value = TRUE_STRING if parked else None
        self.client.patch_node_labels(
            node.metadata.name, {self.upgrade_keys.skip_label: value})
        if parked:
            node.metadata.labels[self.upgrade_keys.skip_label] = TRUE_STRING
        else:
            node.metadata.labels.pop(self.upgrade_keys.skip_label, None)

    def _validator_passes(self, node: Node) -> bool:
        if self.validator is None:
            return True
        try:
            return bool(self.validator(node))
        except Exception as exc:  # noqa: BLE001 — gate boundary
            logger.warning("remediation validator raised on node %s: %s",
                           node.metadata.name, exc)
            return False

    def _maybe_action_timeout(self, node: Node,
                              policy: RemediationPolicySpec, now: float,
                              action: str,
                              timeout: Optional[float] = None,
                              extra_annotations: tuple[str, ...] = (),
                              ) -> None:
        """Write the attempt off (node → wedged) when its action has run
        past its budget; otherwise leave the node in place to retry."""
        started_raw = node.metadata.annotations.get(
            self.keys.action_start_annotation)
        if started_raw is None:
            # dispatch stamps this before routing here; a missing stamp
            # means an operator with older keys — start the clock now
            self.provider.change_node_upgrade_annotation(
                node, self.keys.action_start_annotation, str(int(now)))
            return
        limit = timeout if timeout is not None \
            else policy.action_timeout_seconds
        if now - float(started_raw) <= limit:
            if self.nudger is not None:
                # write the attempt off exactly at its deadline instead
                # of discovering the expiry a resync later
                self.nudger.nudge_at(float(started_raw) + limit,
                                     "remediation-timeout")
            return
        self._fail_attempt(node, f"{action} timed out after {limit:g}s",
                           extra_annotations=extra_annotations)

    def _fail_attempt(self, node: Node, why: str,
                      extra_annotations: tuple[str, ...] = ()) -> None:
        """One consumed attempt: clear the action bookkeeping and send
        the node back to the quarantine queue (which escalates or parks
        it)."""
        for key in (self.keys.action_start_annotation,
                    self.keys.settle_start_annotation,
                    *extra_annotations):
            if key in node.metadata.annotations:
                self.provider.change_node_upgrade_annotation(
                    node, key, None)
        if self.provider.change_node_upgrade_state(
                node, RemediationState.WEDGED):
            logger.warning("remediation attempt on node %s failed: %s",
                           node.metadata.name, why)
            log_event(self.recorder, node, Event.WARNING,
                      self.keys.event_reason,
                      f"Recovery attempt failed: {why}")

    def _mark_failed(self, node: Node, why: str) -> None:
        if self.provider.change_node_upgrade_state(
                node, RemediationState.FAILED):
            self.remediations_failed_total += 1
            logger.error("node %s remediation failed: %s",
                         node.metadata.name, why)
            log_event(self.recorder, node, Event.WARNING,
                      self.keys.event_reason,
                      f"Remediation failed; node parked for manual "
                      f"repair: {why}")

    def _clear_bookkeeping(self, node: Node) -> None:
        for key in (self.keys.wedge_since_annotation,
                    self.keys.wedge_reason_annotation,
                    self.keys.attempt_annotation,
                    self.keys.action_start_annotation,
                    self.keys.restart_pod_uid_annotation,
                    self.keys.settle_start_annotation,
                    self.keys.reboot_requested_annotation,
                    self.keys.initial_state_annotation,
                    self.keys.condemned_annotation,
                    self.keys.at_risk_annotation,
                    self.keys.at_risk_reason_annotation,
                    self.keys.rearm_annotation):
            if key in node.metadata.annotations:
                self.provider.change_node_upgrade_annotation(
                    node, key, None)

    def _finish_recovery(self, node: Node) -> None:
        """Return the node to service: clear the upgrade parking and all
        bookkeeping, record MTTR, commit healthy."""
        since_raw = node.metadata.annotations.get(
            self.keys.wedge_since_annotation)
        self._park_upgrade_flow(node, parked=False)
        self._clear_bookkeeping(node)
        if not self.provider.change_node_upgrade_state(
                node, RemediationState.HEALTHY):
            return
        self.remediations_succeeded_total += 1
        if since_raw is not None:
            self._recovery_seconds.append(
                max(0.0, self.clock.now() - float(since_raw)))
        logger.info("node %s recovered", node.metadata.name)
        log_event(self.recorder, node, Event.NORMAL,
                  self.keys.event_reason,
                  "Node recovered and returned to service")

    # ------------------------------------------------------------------
    # status / metrics feed
    # ------------------------------------------------------------------
    def drain_recovery_durations(self) -> list[float]:
        """Pop the wedge→recovered durations (seconds) accumulated since
        the last call — the MTTR histogram feed."""
        out = self._recovery_seconds
        self._recovery_seconds = []
        return out

    def remediation_status(self, snapshot: RemediationSnapshot) -> dict:
        """CRD-embeddable status block for one snapshot (JSON-ready,
        camelCase, deterministic ordering — the shape consumers splice
        into their CRD ``.status`` next to the upgrade block)."""
        per_state = {key or "healthy": len(bucket)
                     for key, bucket in sorted(snapshot.node_states.items())
                     if bucket}
        status = {
            "totalNodes": snapshot.total_nodes(),
            "wedgedNodes": len(snapshot.bucket(RemediationState.WEDGED)),
            "remediationsInProgress": snapshot.in_progress(),
            "remediationsFailed": len(
                snapshot.bucket(RemediationState.FAILED)),
            "unavailableNodes": snapshot.unavailable_nodes(),
            "nodesByState": per_state,
            "wedgedDetectedTotal": self.wedged_detected_total,
            "recoveredTotal": self.remediations_succeeded_total,
        }
        if self.last_pass_deferrals:
            status["transientDeferrals"] = self.last_pass_deferrals
        condemned = sum(
            1 for bucket in snapshot.node_states.values() for ns in bucket
            if self.keys.condemned_annotation
            in ns.node.metadata.annotations)
        if condemned:
            status["condemnedNodes"] = condemned
        at_risk = sum(
            1 for bucket in snapshot.node_states.values() for ns in bucket
            if self.keys.at_risk_annotation
            in ns.node.metadata.annotations)
        if at_risk:
            status["atRiskNodes"] = at_risk
        if self.reconfigurer is not None:
            status["reconfiguration"] = self.reconfigurer.status()
        return status

    # ------------------------------------------------------------------
    # chained reconcile
    # ------------------------------------------------------------------
    def reconcile(self, namespace: str, runtime_labels: dict[str, str],
                  policy: Optional[RemediationPolicySpec],
                  max_chain: int = 10) -> Optional[RemediationSnapshot]:
        """build_state + apply_state, chained until node states
        stabilize — the same dead-time elimination the upgrade machine's
        chained reconcile performs, with the fingerprint covering every
        durable bit a pass can write (labels, schedulability, and all
        remediation annotations)."""
        last_snapshot = None
        fingerprint = None
        # Two durable families matter to this machine's quiescence: its
        # own bookkeeping and the reconfigurer's remap annotations
        # (reservation / remapped-at / released-from) — a remap step
        # that only moved those must not look like a settled chain.
        prefixes = (f"{self.keys.domain}/{self.keys.driver}-remediation",
                    f"{self.keys.domain}/{self.keys.driver}-topology")
        for _ in range(max_chain):
            snapshot = self.build_state(namespace, runtime_labels)
            new_fingerprint = tuple(sorted(
                (ns.node.metadata.name, label,
                 ns.node.is_unschedulable(),
                 ns.node.metadata.labels.get(GKE_NODEPOOL_LABEL, ""),
                 tuple(sorted(
                     (key, value) for key, value
                     in ns.node.metadata.annotations.items()
                     if key.startswith(prefixes))))
                for label, bucket in snapshot.node_states.items()
                for ns in bucket))
            if new_fingerprint == fingerprint:
                return snapshot
            fingerprint = new_fingerprint
            last_snapshot = snapshot
            self.apply_state(snapshot, policy)
        return last_snapshot
