"""Wedge detectors: the sensory layer of the auto-remediation machine.

A detector inspects one node (plus its runtime pod, when present) and
answers "does this node look wedged right now, and why?". Detectors are
deliberately *stateless and instantaneous* — debouncing lives in the
state machine, which stamps the first-seen time durably in a node
annotation and only confirms the wedge once the signal has persisted
past the detector's grace window. That split keeps detectors trivially
composable and keeps the debounce crash-safe (an operator restart does
not reset the clock).

The reference library has no counterpart: a wedged node under
``k8s-operator-libs`` just stalls the rollout until a human notices.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import TYPE_CHECKING, Callable, Iterable, Optional, Sequence

from tpu_operator_libs.k8s.objects import Node, Pod, PodPhase

if TYPE_CHECKING:  # pragma: no cover - types only
    from tpu_operator_libs.api.remediation_policy import WedgeDetectionSpec

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class WedgeSignal:
    """One detector's verdict that a node is wedged.

    ``reason`` is a stable machine-readable slug (it lands in the node's
    wedge-reason annotation, events, and metrics labels); ``detail`` is
    the human-facing elaboration; ``grace_seconds`` is how long the
    signal must persist before the state machine confirms the wedge.
    """

    reason: str
    detail: str = ""
    grace_seconds: float = 0.0


#: A wedge detector: ``(node, runtime_pod, now) -> Optional[WedgeSignal]``.
#: ``runtime_pod`` is None when the node has no runtime pod in the
#: snapshot (possible for a node so wedged its pods were GC'd).
WedgeDetector = Callable[[Node, Optional[Pod], float],
                         Optional[WedgeSignal]]


class NodeNotReadyDetector:
    """Node Ready condition not "True" — the kubelet-level wedge.

    The grace window absorbs kubelet restarts and transient network
    partitions; a genuinely dead host stays NotReady far longer.
    """

    def __init__(self, grace_seconds: float = 300.0) -> None:
        self._grace = grace_seconds

    def __call__(self, node: Node, runtime_pod: Optional[Pod],
                 now: float) -> Optional[WedgeSignal]:
        if node.is_ready():
            return None
        return WedgeSignal(
            reason="node-not-ready",
            detail=f"node {node.metadata.name} reports NotReady",
            grace_seconds=self._grace)


class RuntimePodCrashLoopDetector:
    """Runtime (libtpu) pod crash-looping or unreachable.

    Two arms: a not-ready container past the restart threshold (the same
    failure the upgrade machine recognizes mid-rollout,
    upgrade_state.go:966-978 — this detector covers it *outside* a
    rollout), and phase Unknown (kubelet stopped reporting, the phase
    the apiserver shows exactly when a TPU host wedges hard).
    """

    def __init__(self, restart_threshold: int = 10) -> None:
        self._threshold = restart_threshold

    def __call__(self, node: Node, runtime_pod: Optional[Pod],
                 now: float) -> Optional[WedgeSignal]:
        if runtime_pod is None:
            return None
        if runtime_pod.status.phase == PodPhase.UNKNOWN:
            return WedgeSignal(
                reason="runtime-pod-unknown",
                detail=f"runtime pod {runtime_pod.name} phase Unknown "
                       "(kubelet unreachable)")
        if runtime_pod.is_failing(self._threshold):
            return WedgeSignal(
                reason="runtime-crashloop",
                detail=f"runtime pod {runtime_pod.name} crash-looping "
                       f"(>{self._threshold} restarts while not ready)")
        return None


class StuckTerminatingDetector:
    """Runtime pod stuck Terminating — a wedged TPU driver commonly
    blocks container teardown, which then blocks the DaemonSet from ever
    recreating the pod."""

    def __init__(self, stuck_seconds: float = 600.0) -> None:
        self._stuck = stuck_seconds

    def __call__(self, node: Node, runtime_pod: Optional[Pod],
                 now: float) -> Optional[WedgeSignal]:
        if runtime_pod is None:
            return None
        deleted_at = runtime_pod.metadata.deletion_timestamp
        if deleted_at is None or now - deleted_at < self._stuck:
            return None
        return WedgeSignal(
            reason="runtime-pod-stuck-terminating",
            detail=f"runtime pod {runtime_pod.name} Terminating for "
                   f"{now - deleted_at:.0f}s")


class NodeConditionDetector:
    """Node-problem-detector-style conditions (e.g. a TPU health agent
    publishing ``TpuHealthy=False``). Any listed condition type present
    with status != "True" wedges the node immediately (the agent already
    debounced)."""

    def __init__(self,
                 condition_types: Sequence[str] = ("TpuHealthy",)) -> None:
        self._types = tuple(condition_types)

    def __call__(self, node: Node, runtime_pod: Optional[Pod],
                 now: float) -> Optional[WedgeSignal]:
        for cond in node.status.conditions:
            if cond.type in self._types and cond.status != "True":
                return WedgeSignal(
                    reason=f"condition-{cond.type}",
                    detail=f"node condition {cond.type}={cond.status}")
        return None


class WedgeDetectorChain:
    """First-signal-wins composition of detectors.

    Order matters for *reason attribution* only (any firing detector
    wedges the node): put the most specific detectors first so the
    recorded reason names the root cause, not a symptom. A detector
    that raises is logged and skipped — one broken probe must not blind
    the whole chain (same boundary rule as ValidationManager's
    extra_validator seam).
    """

    def __init__(self, detectors: Iterable[WedgeDetector]) -> None:
        self._detectors = tuple(detectors)

    def __call__(self, node: Node, runtime_pod: Optional[Pod],
                 now: float) -> Optional[WedgeSignal]:
        for detector in self._detectors:
            try:
                signal = detector(node, runtime_pod, now)
            except Exception:  # noqa: BLE001 — detector boundary
                logger.exception(
                    "wedge detector %r failed on node %s; skipping",
                    detector, node.metadata.name)
                continue
            if signal is not None:
                return signal
        return None


def default_detector_chain(
        detection: Optional["WedgeDetectionSpec"] = None,
) -> WedgeDetectorChain:
    """The built-in chain, thresholds taken from the policy's detection
    spec (defaults when None). Condition and crash-loop detectors come
    first: they name root causes, while NotReady is the symptom every
    hard wedge eventually shows."""
    from tpu_operator_libs.api.remediation_policy import WedgeDetectionSpec

    spec = detection or WedgeDetectionSpec()
    return WedgeDetectorChain((
        NodeConditionDetector(spec.unhealthy_condition_types),
        RuntimePodCrashLoopDetector(spec.pod_restart_threshold),
        StuckTerminatingDetector(spec.terminating_stuck_seconds),
        NodeNotReadyDetector(spec.not_ready_grace_seconds),
    ))
