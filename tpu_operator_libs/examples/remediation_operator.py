#!/usr/bin/env python3
"""Auto-remediation operator demo: detect and recover wedged TPU nodes.

Runs the unplanned-fault state machine
(:mod:`tpu_operator_libs.remediation`) against a simulated GKE TPU fleet
and walks both rungs of the escalation ladder end-to-end:

- one node's libtpu pod crash-loops → quarantine → drain → runtime-pod
  restart → revalidate → back in service;
- one node goes hard NotReady (kubelet dead) → the restart rung cannot
  help → escalation to a host reboot via the NodeRebooter seam →
  revalidate → back in service.

Usage:

    # simulated 2-fault fleet, virtual time
    python examples/remediation_operator.py --demo

    # validate a remediation policy file and print its canonical form
    python examples/remediation_operator.py --policy policy.json --check
"""

from __future__ import annotations

import argparse
import json
import logging
import sys

from tpu_operator_libs.api.remediation_policy import RemediationPolicySpec
from tpu_operator_libs.api.upgrade_policy import DrainSpec
from tpu_operator_libs.consts import RemediationKeys
from tpu_operator_libs.metrics import MetricsRegistry, observe_remediation
from tpu_operator_libs.remediation import NodeRemediationManager
from tpu_operator_libs.simulate import (
    NS,
    RUNTIME_LABELS,
    FleetSpec,
    build_fleet,
)
from tpu_operator_libs.util import EventRecorder

logger = logging.getLogger("remediation-operator")


def load_remediation_policy(path: str | None) -> RemediationPolicySpec:
    """Load a RemediationPolicySpec from a JSON (or, when PyYAML is
    installed, YAML) file; defaults when path is None."""
    if path is None:
        return RemediationPolicySpec(
            enable=True, drain=DrainSpec(enable=True, force=True))
    with open(path) as fh:
        text = fh.read()
    try:
        data = json.loads(text)
    except json.JSONDecodeError:
        try:
            import yaml
        except ImportError as exc:  # pragma: no cover - env dependent
            raise SystemExit(
                f"policy file {path} is not JSON and PyYAML is not "
                f"installed: {exc}") from exc
        data = yaml.safe_load(text)
    if data is None:
        raise SystemExit(f"policy file {path} is empty")
    spec = RemediationPolicySpec.from_dict(data)
    spec.validate()
    return spec


class DemoRebooter:
    """Demo NodeRebooter: 'reboots' a simulated node by scheduling its
    Ready condition to flip back on after ``reboot_seconds`` of virtual
    time — the observable effect of a real power-cycle."""

    def __init__(self, cluster, reboot_seconds: float = 90.0) -> None:
        self._cluster = cluster
        self._reboot_seconds = reboot_seconds

    def request_reboot(self, node) -> None:
        name = node.metadata.name
        logger.info("rebooting node %s (virtual)", name)
        self._cluster.schedule_at(
            self._cluster.clock.now() + self._reboot_seconds,
            lambda: self._cluster.set_node_ready(name, True))


def run_demo(args: argparse.Namespace, registry: MetricsRegistry) -> int:
    fleet = FleetSpec(n_slices=args.demo_slices, hosts_per_slice=2,
                      pod_recreate_delay=5.0, pod_ready_delay=15.0)
    cluster, clock, upgrade_keys = build_fleet(fleet)
    recorder = EventRecorder()
    keys = RemediationKeys()
    mgr = NodeRemediationManager(
        cluster, keys, upgrade_keys=upgrade_keys,
        rebooter=DemoRebooter(cluster), recorder=recorder,
        clock=clock, poll_interval=0.0, sync_timeout=5.0)
    policy = RemediationPolicySpec(
        enable=True, max_concurrent=2,
        restart_attempts=1, max_attempts=3,
        action_timeout_seconds=120, settle_seconds=30,
        revalidate_timeout_seconds=120,
        drain=DrainSpec(enable=True, force=True))
    policy.detection.not_ready_grace_seconds = 60

    # fault 1: crash-looping libtpu pod on s0-h0 (restart rung recovers)
    crash_node = "s0-h0"
    crash_pod = next(p for p in cluster.list_pods(namespace=NS)
                     if p.spec.node_name == crash_node)
    cluster.set_pod_status(NS, crash_pod.name, ready=False,
                           restart_count=20)
    # fault 2: hard NotReady on s1-h0 (only the reboot rung recovers)
    dead_node = "s1-h0"
    cluster.set_node_ready(dead_node, False)

    faulted = (crash_node, dead_node)
    deadline = 4 * 3600.0
    snapshot = None
    while clock.now() < deadline:
        snapshot = mgr.reconcile(NS, RUNTIME_LABELS, policy)
        observe_remediation(registry, mgr, snapshot)
        healthy = all(
            cluster.get_node(n).metadata.labels.get(
                keys.state_label, "") == ""
            for n in faulted)
        if healthy and mgr.remediations_succeeded_total >= len(faulted):
            break
        clock.advance(10.0)
        cluster.step()
    else:
        logger.error("demo did not converge within the safety window")
        return 1

    recovered = mgr.remediations_succeeded_total
    logger.info(
        "demo complete: %d/%d wedged nodes recovered in %.0fs virtual "
        "(restarts=%d reboots=%d)", recovered, len(faulted), clock.now(),
        mgr.runtime_restarts_total, mgr.reboots_requested_total)
    status = mgr.remediation_status(
        mgr.build_state(NS, RUNTIME_LABELS))
    print(json.dumps(status, indent=2, sort_keys=True))
    if args.print_metrics:
        print(registry.render_prometheus())
    return 0


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--demo", action="store_true",
                        help="run the simulated two-fault fleet demo")
    parser.add_argument("--demo-slices", type=int, default=2)
    parser.add_argument("--policy", default=None,
                        help="remediation policy file (JSON/YAML)")
    parser.add_argument("--check", action="store_true",
                        help="validate --policy and print its canonical "
                             "JSON form, then exit")
    parser.add_argument("--print-metrics", action="store_true",
                        default=True)
    parser.add_argument("--verbose", "-v", action="store_true")
    args = parser.parse_args()
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s")
    if args.check:
        spec = load_remediation_policy(args.policy)
        print(json.dumps(spec.to_dict(), indent=2, sort_keys=True))
        return 0
    if args.demo:
        return run_demo(args, MetricsRegistry())
    parser.error("live-cluster mode is provided by the consumer "
                 "operator (see examples/libtpu_operator.py for the "
                 "wiring); use --demo or --check here")
    return 2  # pragma: no cover - parser.error raises


if __name__ == "__main__":
    sys.exit(main())
