#!/usr/bin/env python3
"""Resumable JAX training job — the workload BASELINE config #4 protects.

This is the pod on the other side of the checkpoint-durability gate
(tpu_operator_libs.health.checkpoint_gate): a JAX training loop that
checkpoints with **real Orbax** every ``--save-interval`` steps and, on
restart, resumes from the newest committed step. During a rolling libtpu
upgrade the operator parks a node in pod-deletion-required until this
job's latest checkpoint is durable, evicts the pod, and a replacement pod
resumes from that checkpoint on another node — worst-case loss is the
steps since the last commit, never the whole run.

The model is a dp×tp-sharded MLP over a `jax.sharding.Mesh` (data-parallel
batch, tensor-parallel hidden dimension) so the resumed state round-trips
through Orbax with its shardings — the same pattern a real multi-host
LLM job on a TPU slice uses, scaled down. Run it:

    python examples/jax_training_job.py --checkpoint-dir /tmp/ckpt \
        --max-steps 200 --save-interval 20

Kill it at any point and rerun: it continues from the last committed
step. The operator-side wiring is:

    python examples/libtpu_operator.py --job-selector tpu-job=demo \
        --checkpoint-dir /tmp/ckpt ...
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys

logger = logging.getLogger("jax-training-job")


def make_mesh(n_devices: int | None = None):
    """A dp×tp mesh over the available devices (largest dp ≤ √n)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    dp = 1
    for cand in range(1, int(n ** 0.5) + 1):
        if n % cand == 0:
            dp = cand
    return Mesh(np.array(devices).reshape(dp, n // dp), ("dp", "tp"))


def init_state(mesh, d_in: int = 32, d_hidden_per_shard: int = 16,
               learning_rate: float = 1e-2):
    """Model + optimizer state, tp-sharded where it matters.

    Returns (state, apply_update) where state is a pytree of
    {"params", "opt", "step"} living on the mesh.
    """
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    tp = mesh.shape["tp"]
    d_hidden = d_hidden_per_shard * tp
    key = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)
    params = {
        # w1 columns / w2 rows shard over tp: activations psum over tp
        "w1": jax.device_put(
            jax.random.normal(k1, (d_in, d_hidden)) * 0.1,
            NamedSharding(mesh, P(None, "tp"))),
        "w2": jax.device_put(
            jax.random.normal(k2, (d_hidden, 1)) * 0.1,
            NamedSharding(mesh, P("tp", None))),
    }
    optimizer = optax.adam(learning_rate)
    opt_state = optimizer.init(params)
    state = {"params": params, "opt": opt_state,
             "step": jnp.zeros((), jnp.int32)}
    state = replicate_unplaced(state, mesh)

    def loss_fn(params, batch_x, batch_y):
        hidden = jnp.tanh(batch_x @ params["w1"])
        pred = hidden @ params["w2"]
        return jnp.mean((pred - batch_y) ** 2)

    @jax.jit
    def apply_update(state, batch_x, batch_y):
        loss, grads = jax.value_and_grad(loss_fn)(
            state["params"], batch_x, batch_y)
        updates, opt = optimizer.update(grads, state["opt"],
                                        state["params"])
        params = optax.apply_updates(state["params"], updates)
        return {"params": params, "opt": opt,
                "step": state["step"] + 1}, loss

    return state, apply_update


def make_batch(mesh, step: int, batch_per_shard: int = 8, d_in: int = 32):
    """Deterministic synthetic regression batch, dp-sharded."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    dp = mesh.shape["dp"]
    key = jax.random.PRNGKey(1000 + step)
    kx, _ = jax.random.split(key)
    x = jax.random.normal(kx, (batch_per_shard * dp, d_in))
    y = jnp.sum(x[:, :4], axis=1, keepdims=True)  # learnable target
    sharding = NamedSharding(mesh, P("dp", None))
    return jax.device_put(x, sharding), jax.device_put(y, sharding)


def make_checkpoint_manager(checkpoint_dir: str, max_to_keep: int = 3):
    import orbax.checkpoint as ocp

    return ocp.CheckpointManager(
        os.path.abspath(checkpoint_dir),
        options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                             create=True))


def restore_state(manager, state):
    """Resume from the newest committed step, or return ``state`` as-is.

    Returns (state, start_step). Restoration targets the existing state's
    shardings, so a resumed job lands its arrays back on the mesh.
    """
    import jax
    import orbax.checkpoint as ocp

    latest = manager.latest_step()
    if latest is None:
        return state, 0
    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, state)
    restored = manager.restore(
        latest, args=ocp.args.StandardRestore(abstract))
    logger.info("resumed from checkpoint step %d", latest)
    return restored, latest


def replicate_unplaced(state, mesh):
    """Leaves that didn't inherit a mesh sharding (optimizer step
    counters, scalars) get replicated over the mesh so the whole state
    has one consistent device set — otherwise a restored checkpoint
    pins them to device 0 and jit rejects the mixed placement."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    replicated = NamedSharding(mesh, P())
    n_mesh = mesh.devices.size

    def place(x):
        sharding = getattr(x, "sharding", None)
        if sharding is not None and len(sharding.device_set) == n_mesh:
            return x
        return jax.device_put(x, replicated)

    return jax.tree.map(place, state)


def init_state_llama(mesh, trainer_overrides=None):
    """Llama-style decoder workload (BASELINE #4's model family): same
    {"params", "opt", "step"} state contract as the MLP, so the
    checkpoint/resume loop and the operator's durability gate are
    model-agnostic. ``trainer_overrides`` replaces LlamaConfig fields
    (the CLI's --total-steps/--warmup-steps/--grad-clip-norm path);
    NOTE a checkpoint must resume with the same overrides — the
    schedule position and the clip chain's state shape live in the
    optimizer state."""
    import dataclasses

    import jax.numpy as jnp

    from tpu_operator_libs.examples.llama import (
        config_for_mesh,
        init_llama_params,
        make_train_step,
    )

    config = config_for_mesh(mesh.shape["tp"])
    if trainer_overrides:
        config = dataclasses.replace(config, **trainer_overrides)
    params = init_llama_params(mesh, config)
    optimizer, step_fn = make_train_step(mesh, config)
    state = {"params": params, "opt": optimizer.init(params),
             "step": jnp.zeros((), jnp.int32)}
    return replicate_unplaced(state, mesh), step_fn, config


def train(checkpoint_dir: str, max_steps: int = 100,
          save_interval: int = 10, n_devices: int | None = None,
          stop_flag=None, model: str = "mlp",
          trainer_overrides=None) -> dict:
    """The training loop. Returns {"final_step", "start_step", "loss"}.

    ``model`` picks the workload: "mlp" (tiny regression net) or
    "llama" (dp×tp-sharded Llama-style decoder). Importable for tests;
    __main__ adds signal handling around it. ``trainer_overrides``
    (llama only) replaces LlamaConfig fields, e.g. the LR schedule /
    grad-clip knobs.
    """
    if model not in ("mlp", "llama"):
        raise ValueError(f"unknown model {model!r}")
    if trainer_overrides and model != "llama":
        raise ValueError(
            "trainer_overrides (LR schedule / grad clip) apply to the "
            "llama workload only")
    mesh = make_mesh(n_devices)
    if model == "llama":
        from tpu_operator_libs.examples.llama import make_token_batch

        state, step_fn, config = init_state_llama(mesh,
                                                  trainer_overrides)

        def apply_update(state, x, y):
            return step_fn(state, x)

        def llama_batch(step):
            return make_token_batch(mesh, step, config), None

        next_batch = llama_batch
    elif model == "mlp":
        state, apply_update = init_state(mesh)

        def mlp_batch(step):
            return make_batch(mesh, step)

        next_batch = mlp_batch
    else:
        raise ValueError(f"unknown model {model!r}")
    manager = make_checkpoint_manager(checkpoint_dir)
    try:
        state, start_step = restore_state(manager, state)
        loss = None
        step = start_step
        for step in range(start_step, max_steps):
            if stop_flag is not None and stop_flag():
                logger.info("stop requested at step %d", step)
                break
            x, y = next_batch(step)
            state, loss = apply_update(state, x, y)
            done = step + 1
            if done % save_interval == 0 or done == max_steps:
                # blocking save: once save() returns the step is
                # committed, which is exactly what the operator's gate
                # checks for
                manager.save(done, args=save_args(state))
                manager.wait_until_finished()
                logger.info("step %d: loss %.5f (checkpoint committed)",
                            done, float(loss))
            step = done
    finally:
        manager.close()
    return {"final_step": step, "start_step": start_step,
            "loss": None if loss is None else float(loss)}


def save_args(state):
    import orbax.checkpoint as ocp

    return ocp.args.StandardSave(state)


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--checkpoint-dir", required=True)
    parser.add_argument("--max-steps", type=int, default=100)
    parser.add_argument("--save-interval", type=int, default=10)
    parser.add_argument("--n-devices", type=int, default=None)
    parser.add_argument("--model", choices=("mlp", "llama"),
                        default="mlp",
                        help="workload: tiny regression MLP or the "
                             "dp x tp-sharded Llama-style decoder")
    parser.add_argument("--total-steps", type=int, default=0,
                        help="llama: LR schedule horizon (warmup + "
                             "cosine decay); 0 = constant LR")
    parser.add_argument("--warmup-steps", type=int, default=0,
                        help="llama: linear LR warmup steps")
    parser.add_argument("--grad-clip-norm", type=float, default=0.0,
                        help="llama: global-norm gradient clip; "
                             "0 = off")
    args = parser.parse_args()
    trainer_overrides = {
        k: v for k, v in (("total_steps", args.total_steps),
                          ("warmup_steps", args.warmup_steps),
                          ("grad_clip_norm", args.grad_clip_norm))
        if v} or None
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    stop = {"flag": False}

    def on_term(signum, _frame):
        # an evicted pod gets SIGTERM: stop cleanly WITHOUT saving —
        # durability must come from the periodic commits the operator's
        # gate verified, not from a grace-period race
        stop["flag"] = True
        if signum == signal.SIGINT:
            # keep the Ctrl-C escape hatch: a second SIGINT raises
            # KeyboardInterrupt even while blocked inside an Orbax save
            signal.signal(signal.SIGINT, signal.default_int_handler)

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)
    result = train(args.checkpoint_dir, args.max_steps, args.save_interval,
                   args.n_devices, stop_flag=lambda: stop["flag"],
                   model=args.model, trainer_overrides=trainer_overrides)
    logger.info("exiting at step %d (started from %d)",
                result["final_step"], result["start_step"])
    return 0


if __name__ == "__main__":
    sys.exit(main())
