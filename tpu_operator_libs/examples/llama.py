"""Llama-style decoder-only transformer, dp×tp-sharded over a Mesh.

BASELINE config #4 names the protected workload: a live JAX Llama-style
training Job whose eviction is gated on checkpoint durability. This
module is that workload's model, TPU-first and scaled by config: RMSNorm
→ causal self-attention with rotary embeddings and grouped-query KV
heads → SwiGLU MLP, the Llama-3 block structure
(cf. /root/reference — no counterpart: the reference manages drivers,
it ships no model code; this is the beyond-reference workload side).

Sharding follows the Megatron tensor-parallel pattern the scaling book
describes: column-parallel in-projections (wq/wk/wv/w_gate/w_up shard
their output dim over ``tp``), row-parallel out-projections (wo/w_down
shard their input dim), activations replicated at block boundaries —
XLA inserts the psum over ``tp`` at each row-parallel matmul and the
gradient psum over ``dp`` from the shardings alone; no hand-written
collectives. Training math runs in f32 by default so checkpoint-resume
tests can assert bit-identity on CPU; pass ``param_dtype=bfloat16`` for
MXU-shaped runs on TPU.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional


#: Attention implementations forward() accepts; validate_for and
#: forward both check against this single list so they cannot drift.
ATTENTION_IMPLS = ("xla", "flash", "ring")


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    """Model shape. tp must divide n_heads, n_kv_heads and d_ff."""

    vocab: int = 64
    d_model: int = 64
    n_layers: int = 2
    n_heads: int = 8
    n_kv_heads: int = 4      # grouped-query attention (Llama-3 style)
    d_ff: int = 128          # SwiGLU hidden width (total, pre-shard)
    seq_len: int = 32
    rope_theta: float = 10000.0
    learning_rate: float = 3e-3
    # Optional LR schedule: with total_steps > 0 the step uses linear
    # warmup over warmup_steps then cosine decay to 0 at total_steps
    # (the standard LLM pretraining shape); 0 keeps the constant LR so
    # existing configs (and the bench protocol) are bit-unchanged.
    warmup_steps: int = 0
    total_steps: int = 0
    # Optional global-norm gradient clipping (0 = off). When on, the
    # optimizer state gains the chain's tuple nesting — a checkpoint
    # written with clipping on/off must resume with the same setting.
    grad_clip_norm: float = 0.0
    # "xla" (einsum softmax; the compiler tiles it well to ~4k context)
    # or "flash" (the Pallas TPU flash-attention kernel; never
    # materializes the S x S scores — measured ~15x faster at seq 8192
    # on a v5e with amortized-fence timing, where XLA's materialized
    # f32 score matrix thrashes HBM). tp=1 only: the Pallas custom
    # call has no tensor-parallel partitioning rule.
    attention_impl: str = "xla"
    # Rematerialize each decoder layer on the backward pass
    # (jax.checkpoint around the per-layer body): activations are
    # recomputed instead of stored, trading ~1/3 more layer FLOPs for
    # O(n_layers) less live activation memory — the standard lever for
    # growing batch (better MFU amortization) or sequence length on a
    # fixed-HBM chip. Forward-only callers are unaffected (remat
    # changes what the BACKWARD keeps, not the math).
    remat: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def validate_for(self, tp: int) -> None:
        if self.d_model % self.n_heads:
            raise ValueError("n_heads must divide d_model")
        if self.head_dim % 2:
            raise ValueError(
                f"head_dim={self.head_dim} must be even (RoPE rotates "
                "half-dimension pairs)")
        if self.n_heads % self.n_kv_heads:
            raise ValueError("n_kv_heads must divide n_heads (GQA)")
        if self.n_kv_heads % tp or self.d_ff % tp or self.vocab % tp:
            raise ValueError(
                f"tp={tp} must divide n_kv_heads={self.n_kv_heads}, "
                f"d_ff={self.d_ff} and vocab={self.vocab} "
                "(lm_head is column-parallel)")
        if self.attention_impl not in ATTENTION_IMPLS:
            raise ValueError(
                f"unknown attention_impl {self.attention_impl!r} "
                f"(expected one of {ATTENTION_IMPLS})")
        if self.attention_impl == "flash" and tp > 1:
            # the Pallas custom call registers no GSPMD partitioning
            # rule, so head-sharded q/k/v cannot flow through it; until
            # it is wrapped in shard_map, flash is the tp=1 (dp/sp-only)
            # configuration
            raise ValueError(
                "attention_impl='flash' requires tp=1 (the Pallas "
                "kernel is not tensor-parallel partitionable)")
        if self.attention_impl == "ring" and tp > 1:
            raise ValueError(
                "attention_impl='ring' shards the sequence (sp), not "
                "heads; use tp=1 with a dp x sp mesh")


def _rms_norm(x, weight, eps: float = 1e-5):
    import jax.numpy as jnp

    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1,
                   keepdims=True)
    return (x * jnp.reciprocal(jnp.sqrt(var + eps))).astype(x.dtype) \
        * weight


def _rope(x, theta: float, positions=None):
    """Rotary position embedding over the last axis of (B, S, H, D).

    ``positions`` (S,) overrides the default 0..S-1 — decode steps pass
    the absolute position so a cached token rotates identically whether
    it arrived via prefill or one step at a time."""
    import jax.numpy as jnp

    _, seq, _, head_dim = x.shape
    half = head_dim // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    if positions is None:
        positions = jnp.arange(seq, dtype=jnp.float32)
    angles = positions.astype(jnp.float32)[:, None] * freqs[None, :]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos],
        axis=-1).astype(x.dtype)


def init_llama_params(mesh, config: Optional[LlamaConfig] = None,
                      param_dtype=None, seed: int = 0):
    """Initialize tp-sharded parameters on the mesh.

    Column-parallel projections carry ``P(None, "tp")``, row-parallel
    ``P("tp", None)``; norms/embeddings are replicated.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    config = config or LlamaConfig()
    config.validate_for(dict(mesh.shape).get("tp", 1))
    dtype = param_dtype or jnp.float32
    d, hd = config.d_model, config.head_dim
    keys = iter(jax.random.split(jax.random.PRNGKey(seed),
                                 4 + 9 * config.n_layers))

    axis_names = set(mesh.axis_names)
    if "tp" not in axis_names and "sp" not in axis_names:
        # a loud error beats silently replicating every weight on a
        # mesh whose tp axis was merely misspelled
        raise ValueError(
            f"mesh axes {tuple(mesh.axis_names)} carry neither 'tp' "
            "(Megatron tensor parallelism) nor 'sp' (sequence "
            "parallelism)")

    def tensor(key, shape, spec, scale=None):
        scale = scale if scale is not None else shape[0] ** -0.5
        value = (jax.random.normal(key, shape, jnp.float32)
                 * scale).astype(dtype)
        if "tp" not in axis_names and "tp" in spec:
            # sequence-parallel (dp x sp) meshes replicate the weights
            spec = P()
        return jax.device_put(value, NamedSharding(mesh, spec))

    params = {
        "embed": tensor(next(keys), (config.vocab, d), P(), scale=0.02),
        "final_norm": jax.device_put(
            jnp.ones((d,), dtype), NamedSharding(mesh, P())),
        "lm_head": tensor(next(keys), (d, config.vocab), P(None, "tp")),
        "layers": [],
    }
    for _ in range(config.n_layers):
        params["layers"].append({
            "attn_norm": jax.device_put(
                jnp.ones((d,), dtype), NamedSharding(mesh, P())),
            "wq": tensor(next(keys), (d, config.n_heads * hd),
                         P(None, "tp")),
            "wk": tensor(next(keys), (d, config.n_kv_heads * hd),
                         P(None, "tp")),
            "wv": tensor(next(keys), (d, config.n_kv_heads * hd),
                         P(None, "tp")),
            "wo": tensor(next(keys), (config.n_heads * hd, d),
                         P("tp", None)),
            "mlp_norm": jax.device_put(
                jnp.ones((d,), dtype), NamedSharding(mesh, P())),
            "w_gate": tensor(next(keys), (d, config.d_ff), P(None, "tp")),
            "w_up": tensor(next(keys), (d, config.d_ff), P(None, "tp")),
            "w_down": tensor(next(keys), (config.d_ff, d), P("tp", None)),
        })
    return params


def forward(params, tokens, config: LlamaConfig, mesh=None):
    """Logits (B, S, vocab) for int32 ``tokens`` (B, S), causal."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    def constrain(x, spec):
        if mesh is None:
            return x
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, spec))

    batch, seq = tokens.shape
    hd, nh, nkv = config.head_dim, config.n_heads, config.n_kv_heads
    if config.attention_impl not in ATTENTION_IMPLS:
        raise ValueError(
            f"unknown attention_impl {config.attention_impl!r} "
            f"(expected one of {ATTENTION_IMPLS})")
    use_flash = config.attention_impl == "flash"
    use_ring = config.attention_impl == "ring"
    if use_flash:
        if jax.devices()[0].platform != "tpu":
            raise ValueError(
                "attention_impl='flash' is the Pallas TPU kernel; "
                "use 'xla' on other backends")
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention,
        )
    if use_ring:
        # sequence parallelism: the sequence dimension shards over an
        # "sp" mesh axis; attention runs as the ppermute ring (RoPE is
        # applied below on the GLOBAL position view, so sharding the
        # sequence cannot skew positions)
        if mesh is None or "sp" not in mesh.axis_names:
            raise ValueError(
                "attention_impl='ring' needs a mesh with an 'sp' axis")
        try:
            from jax import shard_map
        except ImportError:  # pre-0.7 jax: experimental location
            from functools import partial as _partial

            from jax.experimental.shard_map import shard_map as _shard_map

            shard_map = _partial(_shard_map, check_rep=False)
        from tpu_operator_libs.examples.ring_attention import (
            ring_attention,
        )

        sp = mesh.shape["sp"]
        if seq % sp:
            raise ValueError(
                f"sequence {seq} must divide over sp={sp}")
        spec4 = P("dp", "sp", None, None)

        def ring_fn(q, k, v, _sp=sp):
            from functools import partial

            inner = partial(ring_attention, axis_name="sp",
                            axis_size=_sp, causal=True)
            return shard_map(inner, mesh=mesh,
                             in_specs=(spec4, spec4, spec4),
                             out_specs=spec4)(q, k, v)

    h_spec = (P("dp", "sp", None) if use_ring
              else P("dp", None, None))
    h = params["embed"][tokens]
    h = constrain(h, h_spec)
    # only the einsum path materializes a mask; flash and ring mask
    # inside their kernels
    causal = (None if (use_flash or use_ring)
              else jnp.tril(jnp.ones((seq, seq), jnp.bool_)))

    def layer_fn(h, layer):
        a = _rms_norm(h, layer["attn_norm"])
        q = (a @ layer["wq"]).reshape(batch, seq, nh, hd)
        k = (a @ layer["wk"]).reshape(batch, seq, nkv, hd)
        v = (a @ layer["wv"]).reshape(batch, seq, nkv, hd)
        q, k = _rope(q, config.rope_theta), _rope(k, config.rope_theta)
        # grouped-query attention: each KV head serves n_heads/n_kv_heads
        # query heads (repeat stays inside the tp shard: both counts
        # divide by tp)
        # grouped-query attention: xla/flash repeat KV up-front; the
        # ring path hands the kernel the narrow nkv-head K/V so each
        # ppermute hop moves group-x fewer bytes (the kernel repeats
        # locally per fold)
        group = nh // nkv
        if not use_ring:
            k = jnp.repeat(k, group, axis=2)
            v = jnp.repeat(v, group, axis=2)
        if use_flash:
            ctx = flash_attention(
                jnp.transpose(q, (0, 2, 1, 3)),
                jnp.transpose(k, (0, 2, 1, 3)),
                jnp.transpose(v, (0, 2, 1, 3)),
                causal=True, sm_scale=hd ** -0.5)
            ctx = jnp.transpose(ctx, (0, 2, 1, 3))
        elif use_ring:
            ctx = ring_fn(q, k, v)
        else:
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (hd ** -0.5)
            scores = jnp.where(causal[None, None, :, :],
                               scores.astype(jnp.float32), -1e30)
            attn = jax.nn.softmax(scores, axis=-1).astype(h.dtype)
            ctx = jnp.einsum("bhqk,bkhd->bqhd", attn, v)
        h = h + ctx.reshape(batch, seq, nh * hd) @ layer["wo"]
        h = constrain(h, h_spec)

        m = _rms_norm(h, layer["mlp_norm"])
        gated = jax.nn.silu(m @ layer["w_gate"]) * (m @ layer["w_up"])
        h = h + gated @ layer["w_down"]
        return constrain(h, h_spec)

    if config.remat:
        # recompute the layer's activations on the backward pass; the
        # saveable boundary is the layer input/output residual stream
        layer_fn = jax.checkpoint(layer_fn)
    for layer in params["layers"]:
        h = layer_fn(h, layer)

    h = _rms_norm(h, params["final_norm"])
    # ring mode keeps the logits sequence-sharded: replicating
    # (B, S, vocab) — the model's largest activation — would undo the
    # memory win sequence parallelism exists for
    return constrain(h @ params["lm_head"],
                     P("dp", "sp", None) if use_ring
                     else P("dp", None, None))


def next_token_loss(params, tokens, config: LlamaConfig, mesh=None):
    """Mean next-token cross-entropy over (B, S) int32 tokens."""
    import jax
    import jax.numpy as jnp

    logits = forward(params, tokens, config, mesh)[:, :-1, :]
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(logp, targets[..., None],
                                 axis=-1)[..., 0]
    return -jnp.mean(picked)


def config_for_mesh(tp: int) -> LlamaConfig:
    """The default config when it shards evenly over ``tp``, otherwise
    a tp-derived shape that always does — so the workload starts on any
    mesh (a v5e-16's tp=8 must not crash a config built for tp<=4)."""
    base = LlamaConfig()
    try:
        base.validate_for(tp)
        return base
    except ValueError:
        return LlamaConfig(vocab=16 * tp, d_model=8 * tp,
                           n_heads=tp, n_kv_heads=tp, d_ff=16 * tp,
                           seq_len=base.seq_len)


def make_train_step(mesh, config: LlamaConfig,
                    donate: bool = False) -> "tuple[object, Callable]":
    """(optimizer, jitted (state, tokens) -> (state, loss)); state is
    {"params", "opt", "step"} as the checkpoint/resume loop expects —
    the optimizer is returned so callers can ``optimizer.init`` it.

    ``donate=True`` donates the state into the step
    (``donate_argnums``): XLA updates params/optimizer in place instead
    of allocating a fresh ~2x-params footprint per step, which is what
    lets a training loop queue several steps behind one fence without
    thrashing the allocator (measured on a v5e: 309 -> 249 ms/step for
    Llama-277M, 47 -> 59 % MFU). The donated (pre-step) state is dead
    after the call — callers that keep old states (checkpoint tests)
    must leave this off."""
    import jax
    import optax

    if config.total_steps < 0 or config.warmup_steps < 0:
        raise ValueError(
            f"total_steps={config.total_steps} / warmup_steps="
            f"{config.warmup_steps} must be >= 0 (a negative horizon "
            "would silently fall back to constant LR)")
    if config.warmup_steps and config.total_steps <= 0:
        raise ValueError(
            f"warmup_steps={config.warmup_steps} requires "
            "total_steps > 0 (the schedule horizon); total_steps=0 "
            "means constant LR and would silently skip the warmup")
    if 0 < config.total_steps <= config.warmup_steps:
        raise ValueError(
            f"warmup_steps={config.warmup_steps} must be < "
            f"total_steps={config.total_steps} (cosine decay needs a "
            "positive post-warmup horizon)")
    if config.grad_clip_norm < 0.0:
        raise ValueError(
            f"grad_clip_norm must be >= 0, got {config.grad_clip_norm}")
    if config.total_steps > 0:
        lr = optax.warmup_cosine_decay_schedule(
            init_value=0.0, peak_value=config.learning_rate,
            warmup_steps=config.warmup_steps,
            decay_steps=config.total_steps)
    else:
        lr = config.learning_rate
    optimizer = optax.adamw(lr)
    if config.grad_clip_norm > 0.0:
        optimizer = optax.chain(
            optax.clip_by_global_norm(config.grad_clip_norm),
            optimizer)

    def train_step(state, tokens):
        def loss_of(p):
            return next_token_loss(p, tokens, config, mesh)

        loss, grads = jax.value_and_grad(loss_of)(state["params"])
        updates, opt = optimizer.update(grads, state["opt"],
                                        state["params"])
        params = optax.apply_updates(state["params"], updates)
        return {"params": params, "opt": opt,
                "step": state["step"] + 1}, loss

    jitted = jax.jit(train_step,
                     donate_argnums=(0,) if donate else ())
    return optimizer, jitted


def make_token_batch(mesh, step: int, config: LlamaConfig,
                     batch_per_shard: int = 2):
    """Deterministic synthetic sequences with learnable structure
    (affine next-token rule mod vocab), dp-sharded."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    dp = mesh.shape["dp"]
    batch = batch_per_shard * dp
    key = jax.random.PRNGKey(7000 + step)
    start = jax.random.randint(key, (batch, 1), 0, config.vocab)
    steps = jnp.arange(config.seq_len, dtype=jnp.int32)[None, :]
    # x_t = (start * 7^t + 3 * (7^t - 1) / 6) mod vocab — affine orbit,
    # computed iteratively to stay in int32
    def advance(carry, _):
        nxt = (carry * 7 + 3) % config.vocab
        return nxt, carry

    _, seq = jax.lax.scan(advance, start[:, 0],
                          steps[0], length=config.seq_len)
    tokens = jnp.transpose(seq, (1, 0)).astype(jnp.int32)
    return jax.device_put(tokens, NamedSharding(mesh, P("dp", None)))
