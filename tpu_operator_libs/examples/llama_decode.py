"""Autoregressive decoding with a KV cache for the Llama example.

The serving half of the workload family: training (``llama.py``) and
inference share the same parameters and block math; decode adds a
per-layer key/value cache so each generated token costs one pass over
the new position instead of re-running the full sequence (decode is
memory-bound — every step streams the parameters once, so step time
≈ param bytes / HBM bandwidth).

The test contract: feeding a sequence one token at a time through
:func:`forward_with_cache` reproduces the batch
:func:`~tpu_operator_libs.examples.llama.forward` logits at every
position to float tolerance (~1e-4 — the cache is a rearrangement,
not an approximation, but softmax reduction order differs over the
masked cache width).
"""

from __future__ import annotations


def init_kv_cache(mesh, config, batch: int, max_seq: int,
                  param_dtype=None, quantize_kv: bool = False):
    """Per-layer K/V buffers (B, max_seq, n_kv_heads, head_dim),
    zero-filled; sharded over tp on the KV-head axis when the mesh
    carries a tp axis.

    With ``quantize_kv=True`` the buffers are int8 with a per-token
    per-kv-head float32 scale (``k_s``/``v_s``, (B, max_seq, n_kv)):
    at serving context lengths the cache — not the weights — is the
    dominant HBM stream of each decode step (e.g. 277M bf16 weights
    are ~0.55 GB read once per step, while a batch-8 ctx-1024 bf16
    cache is ~1 GB read per step), so halving the cache bytes is the
    rung of the memory-bound roofline that weight-only int8
    (:func:`quantize_params_int8`) cannot reach. Unlike weight
    quantization the write side is in the hot loop, so the scheme is
    chosen so both sides fuse: symmetric per-(token, kv-head) scales
    make the K dequant a rank-1 rescale of the score matrix AFTER the
    int8 einsum and the V dequant a rescale of the attention weights
    BEFORE the value einsum — HBM sees int8 bytes, the MXU sees the
    activation dtype, and nothing ever materializes a dequantized
    cache."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    dtype = param_dtype or jnp.float32
    tp = "tp" in mesh.axis_names
    spec = (P("dp", None, "tp", None) if tp
            else P("dp", None, None, None))
    shape = (batch, max_seq, config.n_kv_heads, config.head_dim)

    def buf(shp, dt, sp):
        # a FRESH zeros per leaf: device_put returns its input
        # unchanged when the sharding already matches (e.g. any
        # single-device mesh), so a shared zeros template would make
        # every k/v leaf alias ONE buffer — and donating the cache
        # into generate_on_device then dies with XLA's
        # "buffer was previously donated in the same call" error
        return jax.device_put(jnp.zeros(shp, dt),
                              NamedSharding(mesh, sp))

    if quantize_kv:
        s_spec = P("dp", None, "tp") if tp else P("dp", None, None)
        return [{"k": buf(shape, jnp.int8, spec),
                 "k_s": buf(shape[:3], jnp.float32, s_spec),
                 "v": buf(shape, jnp.int8, spec),
                 "v_s": buf(shape[:3], jnp.float32, s_spec)}
                for _ in range(config.n_layers)]
    return [{"k": buf(shape, dtype, spec),
             "v": buf(shape, dtype, spec)}
            for _ in range(config.n_layers)]


def _sym_int8(x, axis):
    """Symmetric int8 quantization along ``axis``: ``s = max|x| / 127``
    (floored at 1e-8 so all-zero slices don't divide by zero), ``q =
    clip(round(x / s))``. The single recipe both the weight and the
    KV-cache quantizers share — one place to change the clamp floor or
    the symmetry policy. Returns (int8 codes, float32 scales with
    ``axis`` removed)."""
    import jax.numpy as jnp

    xf = x.astype(jnp.float32)
    s = jnp.maximum(jnp.max(jnp.abs(xf), axis=axis), 1e-8) / 127.0
    q = jnp.clip(jnp.round(xf / jnp.expand_dims(s, axis)), -127, 127) \
        .astype(jnp.int8)
    return q, s


def _quantize_kv_block(x):
    """(B, T, n_kv, head_dim) activations -> (int8 codes, (B, T, n_kv)
    float32 scales), symmetric per-(token, kv-head) over head_dim. The
    scale axis choice is what keeps dequantization out of the cache
    stream (see :func:`init_kv_cache`)."""
    return _sym_int8(x, axis=-1)


def quantize_params_int8(params):
    """Weight-only int8 quantization of every matmul weight.

    Decode is memory-bound — each step streams the parameters once —
    so halving the weight bytes (bf16 → int8 + per-output-channel
    scale) is a ~2x decode-throughput lever with no change to the
    cache, activations, or MXU math (weights dequantize on the fly in
    the matmul's operand load; XLA fuses the convert+scale into the
    epilogue). Symmetric per-output-channel scheme: ``q = round(w /
    s)``, ``s = max|w[:, j]| / 127`` — the layout int8 serving stacks
    standardize on. Norm weights and the embedding table (a gather,
    not a matmul) stay in the original dtype.

    Returns a params pytree where each 2-D weight is replaced by
    ``{"q": int8 (in, out), "s": f32 (out,)}``; every decode entry
    point (:func:`forward_with_cache`, :func:`generate`,
    :func:`generate_on_device`) accepts either representation.
    """
    def quant(w):
        q, s = _sym_int8(w, axis=0)
        return {"q": q, "s": s}

    out = {"embed": params["embed"],
           "final_norm": params["final_norm"],
           "lm_head": quant(params["lm_head"]),
           "layers": []}
    for layer in params["layers"]:
        out["layers"].append({
            "attn_norm": layer["attn_norm"],
            "mlp_norm": layer["mlp_norm"],
            **{k: quant(layer[k])
               for k in ("wq", "wk", "wv", "wo",
                         "w_gate", "w_up", "w_down")},
        })
    return out


def _mm(x, w):
    """x @ w for a plain weight or an int8-quantized {"q", "s"} one.

    The quantized path computes ``(x @ cast(q)) * s`` — exact for a
    per-output-channel scale, and the int8→activation-dtype convert
    happens in the matmul's operand load, so HBM sees int8 bytes."""
    if isinstance(w, dict):
        return (x @ w["q"].astype(x.dtype)) * w["s"].astype(x.dtype)
    return x @ w


def forward_with_cache(params, tokens, cache, start_pos, config,
                       mesh=None):
    """Logits for ``tokens`` (B, T) occupying absolute positions
    ``start_pos .. start_pos+T-1``, attending to everything already in
    ``cache`` plus themselves. Returns (logits (B, T, vocab),
    updated cache). T is static; ``start_pos`` may be traced (the same
    jitted function serves every decode step)."""
    import jax
    import jax.numpy as jnp
    from tpu_operator_libs.examples.llama import _rms_norm, _rope

    from jax.sharding import NamedSharding, PartitionSpec as P

    if config.attention_impl != "xla":
        raise ValueError(
            "forward_with_cache implements the einsum path; decode "
            "with attention_impl='xla'")

    def constrain(x, spec):
        if mesh is None:
            return x
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, spec))

    batch, t_new = tokens.shape
    hd, nh, nkv = config.head_dim, config.n_heads, config.n_kv_heads
    group = nh // nkv
    max_seq = cache[0]["k"].shape[1]
    positions = start_pos + jnp.arange(t_new)

    h = params["embed"][tokens]
    h = constrain(h, P("dp", None, None))
    new_cache = []
    # key validity: cached positions < start_pos+T, and causality
    # within the new block
    kv_pos = jnp.arange(max_seq)
    mask = (kv_pos[None, :] <= positions[:, None])  # (T, max_seq)

    for layer, entry in zip(params["layers"], cache):
        quant_kv = "k_s" in entry
        a = _rms_norm(h, layer["attn_norm"])
        q = _mm(a, layer["wq"]).reshape(batch, t_new, nh, hd)
        k = _mm(a, layer["wk"]).reshape(batch, t_new, nkv, hd)
        v = _mm(a, layer["wv"]).reshape(batch, t_new, nkv, hd)
        q = _rope(q, config.rope_theta, positions)
        k = _rope(k, config.rope_theta, positions)
        if quant_kv:
            # quantize AFTER RoPE — the cache holds exactly what dense
            # attention would read, just coded int8 + per-token scale
            k_q, k_s = _quantize_kv_block(k)
            v_q, v_s = _quantize_kv_block(v)
            k_cache = jax.lax.dynamic_update_slice(
                entry["k"], k_q, (0, start_pos, 0, 0))
            v_cache = jax.lax.dynamic_update_slice(
                entry["v"], v_q, (0, start_pos, 0, 0))
            ks_cache = jax.lax.dynamic_update_slice(
                entry["k_s"], k_s, (0, start_pos, 0))
            vs_cache = jax.lax.dynamic_update_slice(
                entry["v_s"], v_s, (0, start_pos, 0))
            new_cache.append({"k": k_cache, "k_s": ks_cache,
                              "v": v_cache, "v_s": vs_cache})
        else:
            k_cache = jax.lax.dynamic_update_slice(
                entry["k"], k.astype(entry["k"].dtype),
                (0, start_pos, 0, 0))
            v_cache = jax.lax.dynamic_update_slice(
                entry["v"], v.astype(entry["v"].dtype),
                (0, start_pos, 0, 0))
            new_cache.append({"k": k_cache, "v": v_cache})

        # grouped einsum over (kv-head, group) — never materializes a
        # group-times-repeated copy of the cache, which would dominate
        # the step's HBM traffic at long context
        q_g = q.reshape(batch, t_new, nkv, group, hd)
        # int8 codes must be widened to the compute dtype before the
        # einsum (the dequant path); a float cache is left as-is — when
        # it is wider than the activations (float32 cache, bf16 params)
        # casting would narrow it, and the mixed-dtype einsum already
        # promotes correctly.
        k_op = k_cache.astype(h.dtype) if quant_kv else k_cache
        scores = jnp.einsum("bqkgd,bskd->bkgqs", q_g,
                            k_op) * (hd ** -0.5)
        scores = scores.astype(jnp.float32)
        if quant_kv:
            # K dequant: the per-(s, k) scale factors straight out of
            # the head_dim contraction — one rank-1 rescale of the
            # score matrix, the int8 codes were the einsum operand
            scores = scores \
                * ks_cache.transpose(0, 2, 1)[:, :, None, None, :]
        scores = jnp.where(mask[None, None, None, :, :],
                           scores, -1e30)
        attn = jax.nn.softmax(scores, axis=-1)
        if quant_kv:
            # V dequant: fold the per-(s, k) scale into the attention
            # weights BEFORE the value einsum (the s axis is the
            # contraction, so scaling either operand is exact)
            attn = attn \
                * vs_cache.transpose(0, 2, 1)[:, :, None, None, :]
        attn = attn.astype(h.dtype)
        v_op = v_cache.astype(h.dtype) if quant_kv else v_cache
        ctx = jnp.einsum("bkgqs,bskd->bqkgd", attn, v_op)
        h = h + _mm(ctx.reshape(batch, t_new, nh * hd), layer["wo"])
        h = constrain(h, P("dp", None, None))

        m = _rms_norm(h, layer["mlp_norm"])
        gated = jax.nn.silu(_mm(m, layer["w_gate"])) \
            * _mm(m, layer["w_up"])
        h = h + _mm(gated, layer["w_down"])
        h = constrain(h, P("dp", None, None))

    h = _rms_norm(h, params["final_norm"])
    return constrain(_mm(h, params["lm_head"]), P("dp", None, None)), \
        new_cache


_STEP_JIT = None


def _jitted_step(config, mesh):
    """The jitted cache-step, shared across every (config, mesh).

    One module-level ``jax.jit`` with config/mesh as *static* arguments:
    jit's own cache keys on their equality, so a caller constructing a
    fresh-but-identical Mesh per request hits the compiled executable
    instead of recompiling (and nothing here pins Mesh or executable
    references beyond jax's standard cache, which ``jax.clear_caches()``
    empties — the leak a per-module ``lru_cache`` keyed on mesh identity
    would have made permanent). jit itself specializes per token-block
    shape, so the same function serves prefill and decode."""
    import jax

    global _STEP_JIT
    if _STEP_JIT is None:
        def step(params, tokens, cache, pos, config, mesh):
            return forward_with_cache(params, tokens, cache, pos,
                                      config, mesh)

        _STEP_JIT = jax.jit(step, static_argnums=(4, 5))

    return lambda p, t, c, pos: _STEP_JIT(p, t, c, pos, config, mesh)


def _pick_next(logits_last, temperature: float, top_k, key,
               top_p=None, want_logprob: bool = False):
    """(B, vocab) logits -> (B, 1) int32 next tokens.

    temperature 0 = greedy argmax (no key needed). Otherwise sample
    from softmax(logits/temperature), optionally truncated to the
    ``top_k`` highest-logit tokens and/or the ``top_p`` nucleus (the
    smallest set of tokens whose tempered probability sums to
    ``top_p``; ties at the nucleus boundary are kept) first. top_k and
    top_p compose the standard way: top_k truncates, then the nucleus
    is computed over the renormalized survivors."""
    import jax
    import jax.numpy as jnp

    if temperature <= 0.0:
        choice = jnp.argmax(logits_last, axis=-1)
    else:
        logits_f = logits_last.astype(jnp.float32)
        if top_k is not None:
            kth = jnp.sort(logits_f, axis=-1)[:, -top_k][:, None]
            logits_f = jnp.where(logits_f < kth, -jnp.inf, logits_f)
        if top_p is not None:
            # nucleus over the tempered distribution, sort-free on the
            # sampling side: find the smallest kept probability p*
            # (sorted cumulative mass exclusive of self < top_p), then
            # mask everything below it — no gather/scatter, shapes
            # static, fuses into the scan body
            probs = jax.nn.softmax(logits_f / temperature, axis=-1)
            sp = jnp.flip(jnp.sort(probs, axis=-1), axis=-1)
            csum = jnp.cumsum(sp, axis=-1)
            kept = (csum - sp) < top_p  # first token always kept
            pstar = jnp.min(jnp.where(kept, sp, jnp.inf), axis=-1,
                            keepdims=True)
            logits_f = jnp.where(probs < pstar, -jnp.inf, logits_f)
        choice = jax.random.categorical(key, logits_f / temperature,
                                        axis=-1)
    if not want_logprob:
        return choice[:, None].astype(jnp.int32), None
    # logprob of the chosen token under the MODEL's (untempered,
    # untruncated) distribution — what serving APIs report; the
    # truncated/tempered distribution above only steers the draw.
    # Computed only on request: a full-vocab log_softmax per step is
    # real work in the fused hot loop
    lp = jax.nn.log_softmax(logits_last.astype(jnp.float32), axis=-1)
    chosen_lp = jnp.take_along_axis(lp, choice[:, None], axis=-1)
    return choice[:, None].astype(jnp.int32), chosen_lp[:, 0]


def _prefill(step, params, prompt, cache, prefill_chunk):
    """Prompt through the cache in one pass, or in ``prefill_chunk``-
    sized blocks (static count — the loop unrolls at trace time).
    Returns (last block's logits, cache)."""
    prompt_len = prompt.shape[1]
    _check_prefill_chunk(prefill_chunk)
    if prefill_chunk is None or prompt_len <= prefill_chunk:
        return step(params, prompt, cache, 0)
    logits = None
    for off in range(0, prompt_len, prefill_chunk):
        block = prompt[:, off:off + prefill_chunk]
        logits, cache = step(params, block, cache, off)
    return logits, cache


def _check_prefill_chunk(prefill_chunk):
    """Both generate paths must agree on what a valid chunk is — an
    int >= 1 (a float would silently chunk differently on one path
    and crash range() on the other)."""
    if prefill_chunk is None:
        return
    if (not isinstance(prefill_chunk, int)
            or isinstance(prefill_chunk, bool) or prefill_chunk < 1):
        raise ValueError(
            f"prefill_chunk must be an int >= 1, got {prefill_chunk!r}")


def _check_sampling_args(temperature, key, top_p):
    """Shared sampling-argument validation for both generate paths."""
    if temperature > 0.0 and key is None:
        raise ValueError("sampling (temperature > 0) requires a PRNG key")
    if top_p is not None and not (0.0 < top_p <= 1.0):
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")


def generate(params, prompt, config, mesh, max_new_tokens: int,
             param_dtype=None, temperature: float = 0.0,
             top_k=None, key=None, quantize_kv: bool = False,
             top_p=None, eos_id=None, return_logprobs: bool = False,
             prefill_chunk=None):
    """Autoregressive decode: prefill the prompt, then one cached step
    per token. ``temperature=0`` (default) is greedy; otherwise
    softmax sampling at the given temperature, optionally top-k and/or
    top-p (nucleus) truncated, driven by ``key`` (required when
    sampling — explicit PRNG keys keep generation reproducible).
    ``quantize_kv`` stores the cache int8 (see :func:`init_kv_cache`).
    ``eos_id`` enables early-stop semantics: once a row emits it,
    every later position in that row is ``eos_id`` (the fixed-width
    padding convention serving stacks use — shapes stay static, the
    caller truncates at the first eos). Returns
    (B, prompt+max_new_tokens) int32; with ``return_logprobs=True``,
    a (tokens, logprobs) pair where logprobs is (B, max_new_tokens)
    float32 — each generated token's log-probability under the
    model's own (untempered, untruncated) distribution, the quantity
    serving APIs report; eos-padded positions carry 0.0.
    ``prefill_chunk`` processes the prompt in fixed-size blocks
    instead of one pass: the prefill score buffer is (T × cache
    width), so at long prompts chunking bounds peak memory at
    (chunk × width) — chunk-by-chunk prefill is mathematically the
    same attention (each query row reduces over the same positions in
    the same order), it just never materializes the full-T buffer."""
    import jax
    import jax.numpy as jnp

    _check_sampling_args(temperature, key, top_p)
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    batch, prompt_len = prompt.shape
    total = prompt_len + max_new_tokens
    cache = init_kv_cache(mesh, config, batch, total, param_dtype,
                          quantize_kv=quantize_kv)
    step = _jitted_step(config, mesh)

    def next_key():
        nonlocal key
        if key is None:
            return None
        key, sub = jax.random.split(key)
        return sub

    logits, cache = _prefill(step, params, prompt, cache,
                             prefill_chunk)
    tokens = [prompt]
    lps = []
    last, lp = _pick_next(logits[:, -1, :], temperature, top_k,
                          next_key(), top_p, return_logprobs)
    done = jnp.zeros((batch,), bool)
    for i in range(max_new_tokens):
        if eos_id is not None:
            last = jnp.where(done[:, None], eos_id, last)
            if return_logprobs:
                lp = jnp.where(done, 0.0, lp)
            done = done | (last[:, 0] == eos_id)
        tokens.append(last)
        lps.append(lp)
        if i + 1 == max_new_tokens:
            break
        if eos_id is not None and bool(done.all()):
            # Every row has emitted eos: the remaining positions are pure
            # padding, so unlike the device scan the host loop can stop
            # dispatching forward steps and fill them locally.
            pad = max_new_tokens - (i + 1)
            tokens.append(jnp.full((batch, pad), eos_id, last.dtype))
            if return_logprobs:
                lps.extend(jnp.zeros((batch,), jnp.float32)
                           for _ in range(pad))
            break
        logits, cache = step(params, last, cache, prompt_len + i)
        last, lp = _pick_next(logits[:, -1, :], temperature, top_k,
                              next_key(), top_p, return_logprobs)
    out = jnp.concatenate(tokens, axis=1)
    if return_logprobs:
        return out, jnp.stack(lps, axis=1)
    return out


_DEVICE_DECODE_JIT = None


def _jitted_device_decode():
    """The fused prefill+decode executable (one per (shapes, config,
    mesh, sampling) combination, cached by jax.jit's static-argument
    cache — same non-pinning rationale as :func:`_jitted_step`)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    global _DEVICE_DECODE_JIT
    if _DEVICE_DECODE_JIT is None:
        def decode(params, prompt, cache, key, max_new_tokens,
                   temperature, top_k, top_p, eos_id, want_lp,
                   prefill_chunk, config, mesh):
            prompt_len = prompt.shape[1]
            greedy = temperature <= 0.0
            if key is None:
                # keep the carry structure static; greedy never uses it
                key = jax.random.PRNGKey(0)

            def pick(logits_last, sub):
                # -> (token, logprob-or-None); the logprob branch is
                # traced only in the want_lp specialization
                return _pick_next(logits_last, temperature, top_k, sub,
                                  top_p, want_lp)

            def split(k):
                if greedy:
                    return k, None
                return tuple(jax.random.split(k))

            def step(p, t, c, pos):
                return forward_with_cache(p, t, c, pos, config, mesh)

            logits, cache = _prefill(step, params, prompt, cache,
                                     prefill_chunk)
            key, sub = split(key)
            first, first_lp = pick(logits[:, -1, :], sub)
            done0 = (first[:, 0] == eos_id if eos_id is not None
                     else jnp.zeros((first.shape[0],), bool))

            def body(carry, i):
                cache, last, key, done = carry
                logits, cache = forward_with_cache(
                    params, last, cache, prompt_len + i, config, mesh)
                key, sub = split(key)
                nxt, lp = pick(logits[:, -1, :], sub)
                if eos_id is not None:
                    # a finished row keeps emitting eos_id; the step
                    # above still ran (static shapes — the scan can't
                    # skip work), its output is simply masked out
                    nxt = jnp.where(done[:, None], eos_id, nxt)
                    if want_lp:
                        lp = jnp.where(done, 0.0, lp)
                    done = done | (nxt[:, 0] == eos_id)
                out = (nxt[:, 0], lp) if want_lp else nxt[:, 0]
                return (cache, nxt, key, done), out

            (_, _, _, _), rest_out = lax.scan(
                body, (cache, first, key, done0),
                jnp.arange(max_new_tokens - 1, dtype=jnp.int32))
            rest = rest_out[0] if want_lp else rest_out
            # rest: (max_new_tokens-1, B) -> (B, max_new_tokens-1)
            tokens = jnp.concatenate(
                [prompt, first, jnp.transpose(rest, (1, 0))], axis=1)
            if not want_lp:
                return tokens
            logprobs = jnp.concatenate(
                [first_lp[:, None],
                 jnp.transpose(rest_out[1], (1, 0))], axis=1)
            return tokens, logprobs

        _DEVICE_DECODE_JIT = jax.jit(
            decode, static_argnums=(4, 5, 6, 7, 8, 9, 10, 11, 12),
            donate_argnums=(2,))
    return _DEVICE_DECODE_JIT


def generate_on_device(params, prompt, config, mesh,
                       max_new_tokens: int, param_dtype=None,
                       temperature: float = 0.0, top_k=None, key=None,
                       quantize_kv: bool = False, top_p=None,
                       eos_id=None, return_logprobs: bool = False,
                       prefill_chunk=None):
    """:func:`generate`, but the token loop runs ON the device.

    The host-driven loop costs one dispatch (and on a tunneled backend,
    one ~66 ms round-trip) per token; here prefill, every decode step,
    and sampling are fused into ONE jitted call whose inner loop is a
    ``lax.scan``, and the tokens come back in a single readback — the
    difference between ~240 and several thousand tok/s on a v5e behind
    a tunnel. The KV cache is donated into the call (it is dead
    afterwards) and the scan carry aliases it in place thereafter.

    Same contract as :func:`generate` (tested equal on the greedy
    path, including with ``quantize_kv`` — both paths run the same
    quantized math, so host/device equality stays exact): returns
    (B, prompt+max_new_tokens) int32.
    """
    import warnings

    _check_sampling_args(temperature, key, top_p)
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    batch, prompt_len = prompt.shape
    cache = init_kv_cache(mesh, config, batch,
                          prompt_len + max_new_tokens, param_dtype,
                          quantize_kv=quantize_kv)
    with warnings.catch_warnings():
        # The donated cache cannot alias the (tiny, int32) token output
        # — donation here is for the entry copy + in-loop aliasing, so
        # XLA's "donated buffers were not usable [as outputs]" note is
        # expected, not a bug signal.
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        # normalize a no-op chunk to None BEFORE the jitted call:
        # the chunk is a static argument, so distinct values would
        # otherwise compile distinct (but identical) executables
        _check_prefill_chunk(prefill_chunk)
        if prefill_chunk is not None and prefill_chunk >= prompt_len:
            prefill_chunk = None
        return _jitted_device_decode()(
            params, prompt, cache, key if temperature > 0.0 else None,
            max_new_tokens, float(temperature), top_k,
            float(top_p) if top_p is not None else None,
            int(eos_id) if eos_id is not None else None,
            bool(return_logprobs), prefill_chunk, config, mesh)
