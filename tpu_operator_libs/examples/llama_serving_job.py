#!/usr/bin/env python3
"""Drainable JAX serving job — the workload BASELINE config #5 protects.

This is the pod on the other side of the serving drain gate
(tpu_operator_libs.health.serving_gate): a decode server whose request
intake is a :class:`~tpu_operator_libs.health.serving_gate
.ServingEndpoint` and whose compute is the fused single-dispatch loop
(``examples/llama_decode.generate_on_device`` — prefill + ``lax.scan``
token loop + sampling, donated KV cache). During a rolling libtpu
upgrade the operator's ``ServingDrainGate`` flips the endpoint to
draining: new requests are parked (never dropped — they simply never
start here and the router re-routes them), in-flight generations run to
completion, and only then does eviction proceed. The unit of loss the
gate drives to zero is a dropped generation; this binary's summary line
reports exactly that counter.

Run the self-contained demo (any backend; a TPU serves for real):

    python -m tpu_operator_libs.examples.llama_serving_job --demo

It serves a burst of concurrent requests, begins draining mid-burst
(as the first upgrade reconcile that wants this pod gone would), lets
the in-flight generations finish, and prints one JSON summary line —
``dropped`` is 0 and ``parked`` counts the requests the drain turned
away. On SIGTERM (the eviction that should only arrive after the gate
opened) it marks any still-in-flight generations dropped, so a
mis-sequenced eviction is visible in the same counter the gate
protects.

The operator-side wiring is ``ServingDrainGate`` on the eviction-gate
seam — see health/serving_gate.py and
docs/automatic-libtpu-upgrade.md.
"""

from __future__ import annotations

import argparse
import json
import logging
import signal
import sys
import threading

logger = logging.getLogger("llama-serving-job")


def make_mesh(n_devices=None):
    """A dp×tp mesh over the available devices — the same
    factorization the training job uses (one implementation; a future
    mesh-construction change must not silently diverge between the
    two workload binaries)."""
    from tpu_operator_libs.examples.jax_training_job import (
        make_mesh as _mm,
    )

    return _mm(n_devices)


class DecodeServer:
    """One serving pod: a ServingEndpoint fronting the fused decode.

    ``handle`` is the whole request path — admission, generation,
    accounting. It returns the generated tokens, or ``None`` when the
    endpoint is draining (the request was PARKED: it never started, so
    it is not a drop — the router's job is to re-route it)."""

    def __init__(self, mesh, config, params, endpoint,
                 max_new_tokens: int = 8, temperature: float = 0.0,
                 quantize_kv: bool = False):
        self.mesh = mesh
        self.config = config
        self.params = params
        self.endpoint = endpoint
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.quantize_kv = quantize_kv
        self.parked = 0
        self._lock = threading.Lock()
        # One multi-device computation in flight at a time: concurrent
        # sharded executions from several Python threads can interleave
        # their per-device collective steps on the CPU backend's shared
        # pool and deadlock (observed as worker threads parked forever
        # in __array__ under suite load). The slice is one device set —
        # serializing dispatch models real contention; admission and
        # drain stay concurrent (try_begin/finish are outside the lock).
        self._dispatch_lock = threading.Lock()

    def handle(self, prompt, key=None):
        import numpy as np

        from tpu_operator_libs.examples.llama_decode import (
            generate_on_device,
        )

        if not self.endpoint.try_begin():
            with self._lock:
                self.parked += 1
            return None
        try:
            with self._dispatch_lock:
                out = generate_on_device(
                    self.params, prompt, self.config, self.mesh,
                    self.max_new_tokens, temperature=self.temperature,
                    key=key, quantize_kv=self.quantize_kv)
                return np.asarray(out)
        finally:
            try:
                self.endpoint.finish()
            except RuntimeError:
                # the endpoint was kill()ed (eviction) while this
                # generation ran: its loss is already counted in
                # ``dropped``, and the finish of that dead epoch must
                # not crash the worker thread during shutdown
                pass

    def summary(self) -> dict:
        return {
            "completed": self.endpoint.completed,
            "dropped": self.endpoint.dropped,
            "parked": self.parked,
            "draining": self.endpoint.draining,
        }


def build_server(mesh, n_layers: int = 2, d_model: int = 64,
                 quantize: bool = False, quantize_kv: bool = False,
                 max_new_tokens: int = 8):
    """A small Llama-style decode server (demo-sized; real deployments
    load real weights the same way). ``quantize``/``quantize_kv``
    switch on the int8 weight / int8 KV-cache serving stack."""
    import jax.numpy as jnp

    from tpu_operator_libs.examples.llama import (
        LlamaConfig,
        init_llama_params,
    )
    from tpu_operator_libs.examples.llama_decode import (
        quantize_params_int8,
    )
    from tpu_operator_libs.health.serving_gate import ServingEndpoint

    config = LlamaConfig(vocab=d_model, d_model=d_model,
                         n_layers=n_layers,
                         n_heads=max(4, d_model // 16),
                         n_kv_heads=max(4, d_model // 16),
                         d_ff=4 * d_model, seq_len=64,
                         learning_rate=1e-4)
    params = init_llama_params(mesh, config, param_dtype=jnp.bfloat16)
    if quantize:
        params = quantize_params_int8(params)
    endpoint = ServingEndpoint("llama-serving-demo")
    return DecodeServer(mesh, config, params, endpoint,
                        max_new_tokens=max_new_tokens,
                        quantize_kv=quantize_kv)


def run_demo(server, n_requests: int = 12, drain_after: int = 6,
             workers: int = 3) -> dict:
    """Serve a burst of concurrent requests, begin draining mid-burst,
    and wait for quiescence — the sequence an upgrade reconcile drives
    through ServingDrainGate. The drain fires synchronously in the
    worker that picks request ``drain_after``, BEFORE it submits that
    request: the demo is deterministic about at least that request
    being parked (never a race against sub-millisecond decodes), while
    requests already admitted on other threads model the in-flight
    generations the gate waits out. Returns the summary dict."""
    import jax
    import jax.numpy as jnp

    # a drain index past the burst would never fire: clamp so --demo
    # with a tiny --requests still exercises the drain
    drain_after = min(drain_after, n_requests - 1)
    prompts = [
        jax.random.randint(jax.random.PRNGKey(i), (2, 4), 0,
                           server.config.vocab, dtype=jnp.int32)
        for i in range(n_requests)
    ]
    # warm the executable once so the drain window doesn't race a
    # multi-second first compile (a real server warms at startup too)
    server.handle(prompts[0])

    served = []
    idx_lock = threading.Lock()
    next_idx = [0]

    def worker():
        while True:
            with idx_lock:
                i = next_idx[0]
                if i >= n_requests:
                    return
                next_idx[0] = i + 1
            if i == drain_after:
                # the "upgrade reconcile": the first evaluation that
                # wants this pod gone begins the drain
                server.endpoint.begin_drain()
            out = server.handle(prompts[i])
            if out is not None:
                served.append(i)

    threads = [threading.Thread(target=worker) for _ in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    if not server.endpoint.quiesced:
        raise RuntimeError("demo did not quiesce")
    out = server.summary()
    out["served_request_ids"] = sorted(served)
    return out


def main() -> int:
    parser = argparse.ArgumentParser(
        description=__doc__.split("\n")[0])
    parser.add_argument("--demo", action="store_true",
                        help="serve a burst, drain mid-burst, print a "
                             "JSON summary line")
    parser.add_argument("--requests", type=int, default=12)
    parser.add_argument("--drain-after", type=int, default=6)
    parser.add_argument("--int8", action="store_true",
                        help="serve the int8 weight + int8 KV stack")
    parser.add_argument("--max-new-tokens", type=int, default=8)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    # honor JAX_PLATFORMS even where a sitecustomize force-registered
    # an accelerator plugin (env alone is not enough once jax is
    # imported — same belt-and-suspenders as the bench probes)
    import os

    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    if not args.demo:
        parser.error("only --demo mode is implemented standalone; "
                     "real deployments embed DecodeServer")

    mesh = make_mesh()
    server = build_server(mesh, quantize=args.int8,
                          quantize_kv=args.int8,
                          max_new_tokens=args.max_new_tokens)

    def on_sigterm(signum, frame):
        # eviction arriving BEFORE the gate opened: every in-flight
        # generation is lost, and the summary shows it
        dropped = server.endpoint.kill()
        logger.warning("SIGTERM: %d in-flight generation(s) dropped",
                       dropped)
        print(json.dumps(server.summary()))
        sys.exit(1)

    signal.signal(signal.SIGTERM, on_sigterm)
    summary = run_demo(server, n_requests=args.requests,
                       drain_after=args.drain_after)
    print(json.dumps(summary))
    return 0 if summary["dropped"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
