#!/usr/bin/env python3
"""Federation controller demo: region-as-canary global rollouts.

Runs the multi-cluster federation layer
(:mod:`tpu_operator_libs.federation`) over N simulated regions — each
a real FakeCluster running a real per-cluster operator — and walks two
episodes end-to-end:

- **episode 1 (rollout)**: the fleet target moves to a new revision;
  the canary (lowest-traffic) region upgrades first, bakes behind a
  durable stamp, then the remaining regions follow the sun through
  their traffic troughs under the global budget ledger.
- **episode 2 (containment)**: the target is a broken build whose
  pods can never become Ready; the canary region's own RolloutGuard
  halts and rolls the region back, the federation lifts the
  quarantine fleet-wide, and no other region ever admits the hash.

Usage:

    python -m tpu_operator_libs.examples.federation_operator --demo

    # validate a federation policy file, print its canonical form
    python -m tpu_operator_libs.examples.federation_operator \
        --policy fed-policy.json --check
"""

from __future__ import annotations

import argparse
import json
import logging
import sys

from tpu_operator_libs.api.federation_policy import FederationPolicySpec
from tpu_operator_libs.chaos.federation import (
    FED_FINAL_REVISION,
    FederationChaosConfig,
    FederationFleetSim,
    FederationMonitor,
)
from tpu_operator_libs.chaos.injector import BAD_REVISION_HASH
from tpu_operator_libs.metrics import MetricsRegistry, observe_federation

logger = logging.getLogger("federation-operator")


def _episode(config: FederationChaosConfig, target: str,
             done, registry: MetricsRegistry, label: str) -> int:
    sim = FederationFleetSim(config)
    monitor = FederationMonitor(sim)
    print(f"--- {label}: {len(config.regions)} regions x "
          f"{config.nodes_per_region} nodes, canary {sim.canary}, "
          f"global budget {config.global_budget} ---")
    last_phases: dict = {}
    for _ in range(config.max_steps):
        sim.fed.reconcile(target)
        monitor.sample()
        sim.reconcile_regions(monitor=monitor)
        status = sim.fed.last_status
        phases = {name: cell["phase"]
                  for name, cell in status["regions"].items()}
        if phases != last_phases:
            now = sim.clock.now()
            print(f"[t={now:6g}] " + "  ".join(
                f"{name}={phase}" for name, phase
                in sorted(phases.items())))
            last_phases = phases
        if done(sim, monitor):
            break
        sim.step_clusters()
    observe_federation(registry, sim.fed)
    for name in sorted(sim.regions):
        chain = sim.fed.explain_region(name)["blocking"]
        print(f"explain {name}: {chain[0] if chain else '<empty>'}")
    if monitor.violations:
        for violation in monitor.violations:
            print("VIOLATION:", violation.describe())
        return 1
    print(f"converged at t={sim.clock.now():g} with zero violations")
    return 0


def run_demo(args: argparse.Namespace,
             registry: MetricsRegistry) -> int:
    regions = tuple(f"region-{i}" for i in range(args.demo_regions))
    config = FederationChaosConfig(regions=regions, max_steps=600)
    rc = _episode(
        config, FED_FINAL_REVISION,
        lambda sim, monitor: all(
            sim.region_converged(name, FED_FINAL_REVISION)
            for name in sim.regions) and sim.shares_all_zero(),
        registry, "episode 1: region-as-canary rollout")
    if rc:
        return rc

    import copy

    bad_config = copy.deepcopy(config)
    bad_config.bad_revision = BAD_REVISION_HASH
    rc = _episode(
        bad_config, BAD_REVISION_HASH,
        lambda sim, monitor: monitor.fleet_quarantined_at is not None
        and all(sim.region_converged(name, "old")
                for name in sim.regions),
        registry, "episode 2: broken build contained to the canary "
        "region")
    if rc:
        return rc
    print("\n--- metrics (federation families) ---")
    for line in registry.render_prometheus().splitlines():
        if "federation" in line and not line.startswith("#"):
            print(line)
    return 0


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--demo", action="store_true",
                        help="run both simulated episodes")
    parser.add_argument("--demo-regions", type=int, default=3)
    parser.add_argument("--policy", help="federation policy JSON file")
    parser.add_argument("--check", action="store_true",
                        help="validate --policy and print it")
    args = parser.parse_args()
    logging.basicConfig(level=logging.WARNING)
    if args.policy:
        with open(args.policy) as fh:
            spec = FederationPolicySpec.from_dict(json.load(fh))
        spec.validate()
        print(json.dumps(spec.to_dict(), indent=2, sort_keys=True))
        if args.check:
            return 0
    if args.demo:
        return run_demo(args, MetricsRegistry(namespace="tpu_upgrade"))
    parser.print_help()
    print("\nthis demo is simulation-only (the production wiring is "
          "one FederationController over your regions' kubeconfigs); "
          "use --demo or --check here")
    return 2


if __name__ == "__main__":
    sys.exit(main())
