"""Runnable consumer examples, shipped with the package.

The reference keeps its consumer operators out of tree (SURVEY.md §1 L5);
we ship them as installable modules so ``pip install tpu-operator-libs``
gives working entry points (see ``[project.scripts]`` in pyproject.toml):

- :mod:`.libtpu_operator` — the libtpu upgrade operator (live or --demo).
- :mod:`.unified_operator` — mixed GPU+TPU fleet operator.
- :mod:`.safe_load_init` — the workload-side safe-load init-container.
- :mod:`.admission_webhook` — CRD defaulting/validation webhook.
- :mod:`.jax_training_job` — checkpoint-resumable JAX training job used
  by the eviction-gate scenario.

Thin shims remain at ``examples/`` in the repo for path-based invocation.
"""
