"""Expert parallelism: a mixture-of-experts layer with experts sharded
over an ``ep`` mesh axis.

Token-choice top-1 routing: a linear router scores every token against
every expert; each token is processed by its argmax expert, scaled by
the softmax router probability (Switch-Transformer style). Experts
live on distinct devices (one expert — or an equal stack — per ``ep``
shard); tokens are sharded over the same axis as data. Dispatch is the
all-gather pattern: every expert device gathers the full token set,
computes only the tokens routed to its local experts (others masked to
zero), and a ``psum`` combines the disjoint expert outputs back onto
every shard. Exact — no capacity factor, no token dropping — so tests
verify equality with the unsharded reference to float tolerance, and
the routing itself is deterministic.

The reference ships no model code; with the Megatron-split Llama block
(tp), ring attention (sp) and the GPipe pipeline (pp), this completes
the workload family's parallelism axes.
"""

from __future__ import annotations


def init_moe_params(key, n_experts: int, d_model: int, d_hidden: int):
    """Router + per-expert MLP weights (experts stacked on axis 0)."""
    import jax

    k_router, k1, k2 = jax.random.split(key, 3)
    return {
        "router": jax.random.normal(
            k_router, (d_model, n_experts)) * d_model ** -0.5,
        "w1": jax.random.normal(
            k1, (n_experts, d_model, d_hidden)) * d_model ** -0.5,
        "w2": jax.random.normal(
            k2, (n_experts, d_hidden, d_model)) * d_hidden ** -0.5,
    }


def _route(tokens, router):
    """(expert index per token, top-1 softmax gate per token)."""
    import jax
    import jax.numpy as jnp

    logits = tokens @ router
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    choice = jnp.argmax(logits, axis=-1)
    gate = jnp.take_along_axis(probs, choice[:, None], axis=-1)[:, 0]
    return choice, gate.astype(tokens.dtype)


def moe_forward(params_local, tokens_local, axis_name: str,
                axis_size: int, n_experts: int):
    """Call INSIDE shard_map. ``params_local``: router (replicated) +
    this shard's expert stack {"w1": (E/ep, d, h), "w2": (E/ep, h, d)};
    ``tokens_local``: this shard's tokens (B_local, d). Returns the
    locally-sharded MoE output (B_local, d)."""
    import jax.numpy as jnp
    from jax import lax

    shard = lax.axis_index(axis_name)
    experts_per_shard = n_experts // axis_size
    b_local = tokens_local.shape[0]

    # all-gather dispatch: every expert shard sees every token
    all_tokens = lax.all_gather(tokens_local, axis_name)
    all_tokens = all_tokens.reshape(-1, tokens_local.shape[-1])
    choice, gate = _route(all_tokens, params_local["router"])

    # compute local experts over the full token set, masked to the
    # tokens routed here; disjoint across shards, so psum recombines
    out = jnp.zeros_like(all_tokens)
    for local_idx in range(experts_per_shard):
        expert_id = shard * experts_per_shard + local_idx
        mine = (choice == expert_id)[:, None]
        x = jnp.where(mine, all_tokens, 0.0)
        y = jnp.tanh(x @ params_local["w1"][local_idx]) \
            @ params_local["w2"][local_idx]
        out = out + jnp.where(mine, y, 0.0)
    combined = lax.psum(out * gate[:, None], axis_name)
    # keep only this shard's token slice (the data sharding)
    return lax.dynamic_slice_in_dim(combined, shard * b_local, b_local,
                                    axis=0)


def make_moe(mesh, n_experts: int, axis_name: str = "ep"):
    """jitted (params, tokens) -> MoE output; tokens (B, d) sharded over
    ``ep``, experts sharded over ``ep``, router replicated."""
    import jax
    from jax import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    axis_size = mesh.shape[axis_name]
    if n_experts % axis_size:
        raise ValueError(
            f"ep={axis_size} must divide n_experts={n_experts}")
    param_spec = {"router": P(None, None),
                  "w1": P(axis_name, None, None),
                  "w2": P(axis_name, None, None)}
    token_spec = P(axis_name, None)

    def inner(params_local, tokens_local):
        return moe_forward(params_local, tokens_local, axis_name,
                           axis_size, n_experts)

    sharded = shard_map(inner, mesh=mesh,
                        in_specs=(param_spec, token_spec),
                        out_specs=token_spec)

    def place(params, tokens):
        placed = {
            name: jax.device_put(
                value, NamedSharding(mesh, param_spec[name]))
            for name, value in params.items()
        }
        data = jax.device_put(tokens, NamedSharding(mesh, token_spec))
        return sharded(placed, data)

    return jax.jit(place)


def dense_reference(params, tokens):
    """All experts on one device, for verification."""
    import jax.numpy as jnp

    choice, gate = _route(tokens, params["router"])
    out = jnp.zeros_like(tokens)
    for e in range(params["w1"].shape[0]):
        mine = (choice == e)[:, None]
        y = jnp.tanh(tokens @ params["w1"][e]) @ params["w2"][e]
        out = out + jnp.where(mine, y, 0.0)
    return out * gate[:, None]
