"""Expert parallelism: a mixture-of-experts layer with experts sharded
over an ``ep`` mesh axis.

Token-choice top-1 routing: a linear router scores every token against
every expert; each token is processed by its argmax expert, scaled by
the softmax router probability (Switch-Transformer style). Experts
live on distinct devices (one expert — or an equal stack — per ``ep``
shard); tokens are sharded over the same axis as data. Two dispatch
modes:

- ``"gather"`` (default): every expert device all-gathers the full
  token set, computes only the tokens routed to its local experts, and
  a ``psum`` combines the disjoint outputs. Exact — no capacity
  factor, no token dropping — so tests verify equality with the
  unsharded reference to float tolerance.
- ``"all_to_all"``: the production Switch shape — each token travels
  only to its expert's shard through capacity-bounded slots; tokens
  over capacity are dropped (zero MoE output, residual carries them)
  with exact drop accounting.

The reference ships no model code; with the Megatron-split Llama block
(tp), ring attention (sp) and the GPipe pipeline (pp), this completes
the workload family's parallelism axes.
"""

from __future__ import annotations


def init_moe_params(key, n_experts: int, d_model: int, d_hidden: int):
    """Router + per-expert MLP weights (experts stacked on axis 0)."""
    import jax

    k_router, k1, k2 = jax.random.split(key, 3)
    return {
        "router": jax.random.normal(
            k_router, (d_model, n_experts)) * d_model ** -0.5,
        "w1": jax.random.normal(
            k1, (n_experts, d_model, d_hidden)) * d_model ** -0.5,
        "w2": jax.random.normal(
            k2, (n_experts, d_hidden, d_model)) * d_hidden ** -0.5,
    }


def _expert_mlp(x, w1, w2):
    """One expert's MLP — single definition shared by both dispatch
    paths and the dense reference, so the equality tests can never mask
    a divergence introduced by editing one copy."""
    import jax.numpy as jnp

    return jnp.tanh(x @ w1) @ w2


def _route(tokens, router):
    """(expert index per token, top-1 softmax gate per token)."""
    import jax
    import jax.numpy as jnp

    logits = tokens @ router
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    choice = jnp.argmax(logits, axis=-1)
    gate = jnp.take_along_axis(probs, choice[:, None], axis=-1)[:, 0]
    return choice, gate.astype(tokens.dtype)


def moe_forward(params_local, tokens_local, axis_name: str,
                axis_size: int, n_experts: int):
    """Call INSIDE shard_map. ``params_local``: router (replicated) +
    this shard's expert stack {"w1": (E/ep, d, h), "w2": (E/ep, h, d)};
    ``tokens_local``: this shard's tokens (B_local, d). Returns the
    locally-sharded MoE output (B_local, d)."""
    import jax.numpy as jnp
    from jax import lax

    shard = lax.axis_index(axis_name)
    experts_per_shard = n_experts // axis_size
    b_local = tokens_local.shape[0]

    # all-gather dispatch: every expert shard sees every token
    all_tokens = lax.all_gather(tokens_local, axis_name)
    all_tokens = all_tokens.reshape(-1, tokens_local.shape[-1])
    choice, gate = _route(all_tokens, params_local["router"])

    # compute local experts over the full token set, masked to the
    # tokens routed here; disjoint across shards, so psum recombines
    out = jnp.zeros_like(all_tokens)
    for local_idx in range(experts_per_shard):
        expert_id = shard * experts_per_shard + local_idx
        mine = (choice == expert_id)[:, None]
        x = jnp.where(mine, all_tokens, 0.0)
        y = _expert_mlp(x, params_local["w1"][local_idx],
                        params_local["w2"][local_idx])
        out = out + jnp.where(mine, y, 0.0)
    combined = lax.psum(out * gate[:, None], axis_name)
    # keep only this shard's token slice (the data sharding)
    return lax.dynamic_slice_in_dim(combined, shard * b_local, b_local,
                                    axis=0)


def moe_forward_a2a(params_local, tokens_local, axis_name: str,
                    axis_size: int, n_experts: int, capacity: int):
    """Call INSIDE shard_map: capacity-bounded all_to_all dispatch —
    the production Switch-Transformer routing shape.

    Unlike the all-gather path (every shard sees every token, O(global
    tokens) per device), each token is *sent* to its expert's shard:
    per (source shard, expert) at most ``capacity`` token slots travel,
    so per-device ICI traffic and expert compute are O(local tokens ×
    capacity factor) regardless of fleet size. (The dense one-hot
    dispatch/combine einsums themselves cost O(Bl·E·C·d) — the standard
    Switch trade; sort-based dispatch would remove it at the price of
    gather/scatter.) Tokens beyond an expert's capacity are dropped
    (their MoE output is zero — the transformer's residual carries
    them, Switch semantics); the number dropped on this shard is
    returned for accounting.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    experts_per_shard = n_experts // axis_size
    d_model = tokens_local.shape[-1]
    choice, gate = _route(tokens_local, params_local["router"])

    # Slot assignment: position of each token within its expert's
    # capacity, computed over the LOCAL shard (per-source capacity, as
    # in Mesh-TensorFlow/Switch dispatch). Routing math stays in f32
    # regardless of token dtype: a bf16 cumsum cannot represent
    # integers past 256, which silently COLLIDES slot positions (tokens
    # summed into one slot, wrong outputs scattered back, no drop
    # recorded).
    onehot = jax.nn.one_hot(choice, n_experts,
                            dtype=jnp.float32)  # (Bl, E)
    position = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # (Bl, E)
    keep = onehot * (position < capacity)  # (Bl, E) {0,1}
    dropped = jnp.sum(onehot) - jnp.sum(keep)
    slot_onehot = keep[..., None] * jax.nn.one_hot(
        position.astype(jnp.int32), capacity,
        dtype=jnp.float32)  # (Bl, E, C)

    # dispatch: (E, C, d) slots destined per expert, reshaped so the
    # leading axis is the destination shard for all_to_all (f32 slot
    # math; cast back to the token dtype at the end)
    send = jnp.einsum("bd,bec->ecd",
                      tokens_local.astype(jnp.float32), slot_onehot)
    send = send.astype(tokens_local.dtype)
    send = send.reshape(axis_size, experts_per_shard, capacity, d_model)
    recv = lax.all_to_all(send, axis_name, split_axis=0, concat_axis=0,
                          tiled=False)
    # recv: (source_shard, Eps, C, d) — every source's slots for MY
    # experts; run each local expert over its flattened slot batch
    out_slots = []
    for local_idx in range(experts_per_shard):
        x = recv[:, local_idx].reshape(axis_size * capacity, d_model)
        y = _expert_mlp(x, params_local["w1"][local_idx],
                        params_local["w2"][local_idx])
        out_slots.append(y.reshape(axis_size, capacity, d_model))
    processed = jnp.stack(out_slots, axis=1)  # (src, Eps, C, d)
    back = lax.all_to_all(processed, axis_name, split_axis=0,
                          concat_axis=0, tiled=False)
    # back: (dest_shard=my experts' shards, Eps, C, d) == the slot
    # layout of `send`; combine into token order and apply the gate
    back = back.reshape(n_experts, capacity, d_model)
    combined = jnp.einsum("ecd,bec->bd", back.astype(jnp.float32),
                          slot_onehot).astype(tokens_local.dtype)
    return combined * gate[:, None], dropped


def make_moe(mesh, n_experts: int, axis_name: str = "ep",
             dispatch: str = "gather", capacity_factor: float = 1.25):
    """jitted (params, tokens) -> MoE output; tokens (B, d) sharded over
    ``ep``, experts sharded over ``ep``, router replicated.

    ``dispatch``: "gather" (all-gather + psum; exact, no drops, per-
    device cost O(global tokens)) or "all_to_all" (capacity-bounded
    Switch dispatch; per-device cost O(local tokens × capacity_factor);
    over-capacity tokens get a zero MoE output). With all_to_all the
    returned callable yields ``(out, dropped_total)``."""
    import jax
    try:
        from jax import shard_map
    except ImportError:  # pre-0.7 jax: experimental location
        from functools import partial as _partial

        from jax.experimental.shard_map import shard_map as _shard_map

        # check_rep rejects valid rep types around lax.cond on old jax
        # (the check no longer exists upstream); disable, same semantics
        shard_map = _partial(_shard_map, check_rep=False)
    from jax.sharding import NamedSharding, PartitionSpec as P

    axis_size = mesh.shape[axis_name]
    if n_experts % axis_size:
        raise ValueError(
            f"ep={axis_size} must divide n_experts={n_experts}")
    if dispatch not in ("gather", "all_to_all"):
        raise ValueError(f"unknown dispatch {dispatch!r}")
    param_spec = {"router": P(None, None),
                  "w1": P(axis_name, None, None),
                  "w2": P(axis_name, None, None)}
    token_spec = P(axis_name, None)

    def inner_gather(params_local, tokens_local):
        return moe_forward(params_local, tokens_local, axis_name,
                           axis_size, n_experts)

    def inner_a2a(params_local, tokens_local):
        # per-(source shard, expert) capacity from the local batch
        import math

        from jax import lax

        capacity = max(1, math.ceil(
            tokens_local.shape[0] * capacity_factor / n_experts))
        out, dropped = moe_forward_a2a(
            params_local, tokens_local, axis_name, axis_size,
            n_experts, capacity)
        return out, lax.psum(dropped, axis_name)

    if dispatch == "gather":
        inner = inner_gather
        out_specs = token_spec
    else:
        inner = inner_a2a
        out_specs = (token_spec, P())

    sharded = shard_map(inner, mesh=mesh,
                        in_specs=(param_spec, token_spec),
                        out_specs=out_specs)

    def place(params, tokens):
        placed = {
            name: jax.device_put(
                value, NamedSharding(mesh, param_spec[name]))
            for name, value in params.items()
        }
        data = jax.device_put(tokens, NamedSharding(mesh, token_spec))
        return sharded(placed, data)

    return jax.jit(place)


def dense_reference(params, tokens):
    """All experts on one device, for verification."""
    import jax.numpy as jnp

    choice, gate = _route(tokens, params["router"])
    out = jnp.zeros_like(tokens)
    for e in range(params["w1"].shape[0]):
        mine = (choice == e)[:, None]
        y = _expert_mlp(tokens, params["w1"][e], params["w2"][e])
        out = out + jnp.where(mine, y, 0.0)
    return out * gate[:, None]
