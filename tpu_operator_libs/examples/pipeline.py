"""Pipeline parallelism: layers sharded over a ``pp`` mesh axis,
activations flowing stage-to-stage on the ICI ring (GPipe schedule).

Each device holds one stage (a contiguous slice of layers). A batch is
split into M microbatches; on schedule step t, stage s processes
microbatch ``t - s`` (when in range) and hands its activation to stage
``s+1`` via ``ppermute`` — the classic bubble-filled GPipe forward:
``pp + M - 1`` steps total, bubble fraction ``(pp-1)/(pp+M-1)``.

The computation is exact: activations are selected by predicate, the
permutation only moves them, so the pipelined result equals running all
layers sequentially on one device to float tolerance (tests assert
this). The reference has no counterpart (it ships no model code); this
completes the workload family's parallelism axes (dp/tp/sp/pp/ep)
alongside the Megatron-split Llama block and ring attention.
"""

from __future__ import annotations


def init_stage_params(key, n_layers_total: int, d_model: int,
                      d_hidden: int, pp: int):
    """Stacked residual-MLP block weights, (n_layers, d, h) / (n_layers,
    h, d) — layer ``i`` belongs to stage ``i // (n_layers/pp)``."""
    import jax

    if n_layers_total % pp:
        raise ValueError(
            f"pp={pp} must divide n_layers={n_layers_total}")
    k1, k2 = jax.random.split(key)
    w1 = jax.random.normal(
        k1, (n_layers_total, d_model, d_hidden)) * d_model ** -0.5
    w2 = jax.random.normal(
        k2, (n_layers_total, d_hidden, d_model)) * d_hidden ** -0.5
    return {"w1": w1, "w2": w2}


def _block(x, w1, w2):
    """One residual MLP layer (B, d) -> (B, d)."""
    import jax.numpy as jnp

    return x + jnp.tanh(x @ w1) @ w2


def _stage_forward(x, w1_stack, w2_stack):
    """Apply this stage's layer stack sequentially."""
    from jax import lax

    def body(i, h):
        return _block(h, w1_stack[i], w2_stack[i])

    return lax.fori_loop(0, w1_stack.shape[0], body, x)


def pipeline_forward(params_local, microbatches, axis_name: str,
                     axis_size: int):
    """Call INSIDE shard_map. ``params_local``: this stage's stacked
    weights {"w1": (L/pp, d, h), "w2": (L/pp, h, d)}; ``microbatches``:
    the full (M, Bm, d) input, identical on every stage (stage 0 reads
    it; later stages consume upstream activations). Returns (M, Bm, d):
    the final activations, materialized on the LAST stage (zeros
    elsewhere — callers psum or read the last stage's shard).
    """
    import jax.numpy as jnp
    from jax import lax

    stage = lax.axis_index(axis_name)
    n_micro, _, _ = microbatches.shape
    ring = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    zero = jnp.zeros_like(microbatches[0])

    def varying(x):
        if not hasattr(lax, "pcast"):
            # pre-0.7 jax has no varying-type system (and its shard_map
            # runs with check_rep=False here) — identity is correct
            return x
        return lax.pcast(x, axis_name, to="varying")

    outputs0 = varying(jnp.zeros_like(microbatches))
    recv0 = varying(zero)

    def step(t, carry):
        recv, outputs = carry
        micro_idx = t - stage
        active = jnp.logical_and(micro_idx >= 0, micro_idx < n_micro)
        # stage 0 reads the schedule's microbatch; later stages consume
        # what the previous stage handed over last step
        feed = lax.dynamic_index_in_dim(
            microbatches, jnp.clip(micro_idx, 0, n_micro - 1), axis=0,
            keepdims=False)
        x_in = jnp.where(stage == 0, feed, recv)
        out = _stage_forward(x_in, params_local["w1"],
                             params_local["w2"])
        out = jnp.where(active, out, zero)
        # the last stage banks its finished microbatch...
        is_last = stage == axis_size - 1
        bank_idx = jnp.clip(micro_idx, 0, n_micro - 1)
        banked = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(jnp.logical_and(active, is_last),
                               out,
                               lax.dynamic_index_in_dim(
                                   outputs, bank_idx, axis=0,
                                   keepdims=False)),
            bank_idx, axis=0)
        # ...and every stage forwards to its successor (stage pp-1's
        # hand-off wraps to stage 0, which ignores it: x_in selects the
        # schedule feed there)
        handed = lax.ppermute(out, axis_name, ring)
        return handed, banked

    _, outputs = lax.fori_loop(0, axis_size + n_micro - 1, step,
                               (recv0, outputs0))
    return outputs


def make_pipeline(mesh, axis_name: str = "pp"):
    """jitted (stacked_params, microbatches) -> (M, Bm, d) final
    activations. ``stacked_params`` are the full-model stacks (L, ...)
    sharded over layers; the result is psum-combined so every stage
    returns the same full output (only the last stage's contribution is
    non-zero)."""
    import jax
    try:
        from jax import shard_map
    except ImportError:  # pre-0.7 jax: experimental location
        from functools import partial as _partial

        from jax.experimental.shard_map import shard_map as _shard_map

        # check_rep rejects valid rep types around lax.cond on old jax
        # (the check no longer exists upstream); disable, same semantics
        shard_map = _partial(_shard_map, check_rep=False)
    from jax.sharding import NamedSharding, PartitionSpec as P

    axis_size = mesh.shape[axis_name]
    param_spec = {"w1": P(axis_name, None, None),
                  "w2": P(axis_name, None, None)}
    data_spec = P()

    def inner(params_local, microbatches):
        import jax.numpy as jnp
        from jax import lax

        out = pipeline_forward(params_local, microbatches, axis_name,
                               axis_size)
        return lax.psum(out, axis_name)

    sharded = shard_map(inner, mesh=mesh,
                        in_specs=(param_spec, data_spec),
                        out_specs=data_spec)

    def place(params, microbatches):
        placed = {
            name: jax.device_put(
                value, NamedSharding(mesh, param_spec[name]))
            for name, value in params.items()
        }
        data = jax.device_put(microbatches, NamedSharding(mesh, P()))
        return sharded(placed, data)

    return jax.jit(place)


def sequential_reference(params, microbatches):
    """All layers on one device, for verification."""
    out = []
    for m in range(microbatches.shape[0]):
        h = microbatches[m]
        for i in range(params["w1"].shape[0]):
            h = _block(h, params["w1"][i], params["w2"][i])
        out.append(h)
    import jax.numpy as jnp

    return jnp.stack(out)
