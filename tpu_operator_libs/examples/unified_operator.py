#!/usr/bin/env python3
"""Unified GPU+TPU upgrade operator (BASELINE config #5).

One process, one policy document, one state machine per accelerator
runtime — the deployment shape the reference cannot take (its global
``DriverName``, util.go:87-95, pins a process to a single driver). Each
accelerator's state machine runs against its own label namespace
(``<domain>/<driver>-runtime-upgrade-*``), so a mixed cluster upgrades
its NVIDIA driver and libtpu DaemonSets side by side without the state
machines ever touching each other's labels.

Run against a live cluster:

    python examples/unified_operator.py --policy unified.yaml --kubeconfig

or watch a simulated mixed fleet converge:

    python examples/unified_operator.py --demo

Policy document shape: see ``tpu_operator_libs/api/unified_policy.py``
(YAML example in the module docstring) and
``examples/crd/unifiedupgradepolicy.yaml`` for the CRD schema.
"""

from __future__ import annotations

import argparse
import json
import logging
import signal
import sys
import threading
import time

from tpu_operator_libs.api.unified_policy import (  # noqa: E402
    MultiAcceleratorUpgradeManager,
    UnifiedUpgradePolicySpec,
)
from tpu_operator_libs.metrics import (  # noqa: E402
    MetricsRegistry,
    observe_cluster_state,
    observe_journeys,
)

logger = logging.getLogger("unified-operator")

DEMO_POLICY = {
    "accelerators": {
        "tpu": {
            "domain": "google.com", "driver": "libtpu",
            "namespace": "kube-system",
            "runtimeLabels": {"app": "libtpu"},
            "policy": {"autoUpgrade": True, "maxUnavailable": "50%",
                       "topologyMode": "slice",
                       "drain": {"enable": True, "force": True}},
        },
        "gpu": {
            "domain": "nvidia.com", "driver": "gpu",
            "namespace": "kube-system",
            "runtimeLabels": {"app": "nvidia-driver"},
            "policy": {"autoUpgrade": True, "maxParallelUpgrades": 1,
                       "drain": {"enable": True, "force": True}},
        },
    },
}


def load_unified_policy(path: str | None) -> UnifiedUpgradePolicySpec:
    if path is None:
        spec = UnifiedUpgradePolicySpec.from_dict(DEMO_POLICY)
    else:
        import yaml

        with open(path) as f:
            data = yaml.safe_load(f)
        if not isinstance(data, dict):
            raise ValueError(f"policy file {path!r} is not a mapping")
        inner = data.get("spec", data)
        if not isinstance(inner, dict):
            raise ValueError(
                f"policy file {path!r}: 'spec' must be a mapping")
        spec = UnifiedUpgradePolicySpec.from_dict(inner)
    spec.validate()
    return spec


def install_observability(multi: MultiAcceleratorUpgradeManager,
                          clock=None) -> None:
    """One journey tracer + decision audit per accelerator manager:
    each state machine traces its own label namespace (trace ids ride
    its own commit patches), and /explain answers per driver."""
    from tpu_operator_libs.obs import OperatorObservability

    for name, mgr in multi.managers.items():
        if mgr.observability is None:
            mgr.with_observability(OperatorObservability(
                mgr.keys, clock=clock or mgr.clock))


def explain_node(multi: MultiAcceleratorUpgradeManager,
                 node_name: str) -> dict:
    """/explain/<node> backing for the unified operator: one
    blocking-reason chain per accelerator whose manager knows the node
    (a GPU node shows up under "gpu" only; a node nobody knows still
    answers, per accelerator, with the not-in-snapshot reason)."""
    return {name: mgr.explain(node_name)
            for name, mgr in multi.managers.items()}


def reconcile_pass(multi: MultiAcceleratorUpgradeManager,
                   registry: MetricsRegistry,
                   latest_status: dict) -> dict:
    """One reconcile over every accelerator. One snapshot per accelerator
    serves the transition pass, the /status block, and the metrics —
    three consumers of the SAME state, and 1x the apiserver list load.
    Failures stay per-accelerator (MultiAcceleratorUpgradeManager
    semantics): one runtime's error never blocks the others."""
    errors: dict = {}
    for name, spec in multi.policy.accelerators.items():
        mgr = multi.managers[name]
        try:
            state = mgr.build_state(spec.namespace, spec.runtime_labels)
            # status before apply: it must not freeze on the last good
            # block while transition passes fail
            latest_status[name] = mgr.cluster_status(state)
            mgr.apply_state(state, spec.policy)
            observe_cluster_state(registry, mgr, state, driver=spec.driver)
            if mgr.observability is not None:
                observe_journeys(registry, mgr.observability,
                                 driver=spec.driver)
            errors[name] = None
        except Exception as exc:  # noqa: BLE001 — per-accelerator
            errors[name] = exc
            latest_status[name] = {
                **latest_status.get(name, {}), "error": str(exc)}
            logger.warning("accelerator %s: reconcile error: %s", name, exc)
    return errors


def build_demo_cluster():
    """A mixed fleet: one 2x2-host TPU slice pool + 2 GPU nodes, both
    runtime DaemonSets one revision behind."""
    from tpu_operator_libs.consts import (
        GKE_NODEPOOL_LABEL,
        GKE_TPU_ACCELERATOR_LABEL,
        GKE_TPU_TOPOLOGY_LABEL,
    )
    from tpu_operator_libs.k8s.fake import FakeCluster
    from tpu_operator_libs.k8s.objects import (
        ContainerStatus,
        DaemonSet,
        DaemonSetSpec,
        DaemonSetStatus,
        Node,
        ObjectMeta,
        OwnerReference,
        Pod,
        PodPhase,
        PodSpec,
        PodStatus,
    )
    from tpu_operator_libs.util import FakeClock

    ns = "kube-system"
    clock = FakeClock()
    cluster = FakeCluster(clock=clock)
    cluster.enable_ds_controller(recreate_delay=10.0, ready_delay=20.0)

    def add_ds(name, labels, desired):
        return cluster.add_daemon_set(DaemonSet(
            metadata=ObjectMeta(name=name, namespace=ns, labels=labels),
            spec=DaemonSetSpec(selector=dict(labels)),
            status=DaemonSetStatus(desired_number_scheduled=desired)),
            revision_hash="old")

    tpu_ds = add_ds("libtpu", {"app": "libtpu"}, desired=4)
    gpu_ds = add_ds("nvidia-driver", {"app": "nvidia-driver"}, desired=2)

    def add_node(name, labels, ds, pod_prefix):
        cluster.add_node(Node(metadata=ObjectMeta(name=name, labels=labels)))
        cluster.add_pod(Pod(
            metadata=ObjectMeta(
                name=f"{pod_prefix}-{name}", namespace=ns,
                labels={**ds.spec.selector,
                        "controller-revision-hash": "old"},
                owner_references=[OwnerReference(
                    kind="DaemonSet", name=ds.metadata.name,
                    uid=ds.metadata.uid)]),
            spec=PodSpec(node_name=name),
            status=PodStatus(phase=PodPhase.RUNNING, container_statuses=[
                ContainerStatus(name="runtime", ready=True)])))

    for s in range(2):
        for h in range(2):
            add_node(f"tpu-s{s}-h{h}", {
                GKE_NODEPOOL_LABEL: f"tpu-pool-{s}",
                GKE_TPU_ACCELERATOR_LABEL: "tpu-v5-lite-podslice",
                GKE_TPU_TOPOLOGY_LABEL: "2x2",
                "google.com/tpu": "true"}, tpu_ds, "libtpu")
    for i in range(2):
        add_node(f"gpu-n{i}", {}, gpu_ds, "nvdrv")

    cluster.bump_daemon_set_revision(ns, "libtpu", "new")
    cluster.bump_daemon_set_revision(ns, "nvidia-driver", "new")
    return cluster, clock


def _seed_dag_artifact(cluster, revision: str) -> None:
    """Add the demo's second artifact: a tpu-device-plugin DaemonSet
    with one ready pod per TPU node at ``revision``."""
    from tpu_operator_libs.k8s.objects import (
        ContainerStatus,
        DaemonSet,
        DaemonSetSpec,
        DaemonSetStatus,
        ObjectMeta,
        OwnerReference,
        Pod,
        PodPhase,
        PodSpec,
        PodStatus,
    )

    ns = "kube-system"
    labels = {"app": "tpu-device-plugin"}
    tpu_nodes = [n for n in cluster.list_nodes()
                 if n.metadata.name.startswith("tpu-")]
    ds = cluster.add_daemon_set(DaemonSet(
        metadata=ObjectMeta(name="tpu-device-plugin", namespace=ns,
                            labels=dict(labels)),
        spec=DaemonSetSpec(selector=dict(labels)),
        status=DaemonSetStatus(
            desired_number_scheduled=len(tpu_nodes))),
        revision_hash=revision)
    for node in tpu_nodes:
        cluster.add_pod(Pod(
            metadata=ObjectMeta(
                name=f"tpu-device-plugin-{node.metadata.name}",
                namespace=ns,
                labels={**labels,
                        "controller-revision-hash": revision},
                owner_references=[OwnerReference(
                    kind="DaemonSet", name="tpu-device-plugin",
                    uid=ds.metadata.uid)]),
            spec=PodSpec(node_name=node.metadata.name),
            status=PodStatus(
                phase=PodPhase.RUNNING,
                container_statuses=[
                    ContainerStatus(name="plugin", ready=True)])))


def run_dag_episode(cluster, clock, multi,
                    registry: MetricsRegistry, latest_status: dict,
                    interval_sim_s: float = 10.0) -> int:
    """Episode 2: a TWO-ARTIFACT upgrade DAG, purely declarative.

    The TPU accelerator's policy document grows an ``artifactDAG``
    (libtpu -> tpu-device-plugin) and a sandboxed ``policyHooks``
    admission program — zero operator-code changes — then both
    DaemonSets bump one revision and every TPU node advances BOTH
    artifacts through ONE shared cordon/drain cycle in dependency
    order, leaving durable per-artifact revision stamps.
    """
    from tpu_operator_libs.api.policy_spec import (
        ArtifactDAGSpec,
        ArtifactSpec,
        HookProgramSpec,
        PolicyHooksSpec,
    )

    ns = "kube-system"
    logger.info("episode 2: declarative two-artifact DAG upgrade "
                "(libtpu -> tpu-device-plugin)")
    _seed_dag_artifact(cluster, revision="dp1")
    tpu = multi.policy.accelerators["tpu"]
    tpu.policy.artifact_dag = ArtifactDAGSpec(
        enable=True,
        artifacts=[
            ArtifactSpec(name="libtpu",
                         runtime_labels={"app": "libtpu"}),
            ArtifactSpec(name="device-plugin",
                         runtime_labels={"app": "tpu-device-plugin"},
                         depends_on=["libtpu"]),
        ])
    tpu.policy.policy_hooks = PolicyHooksSpec(hooks=[
        HookProgramSpec(hook="planner.admission",
                        program="fleet.unavailable <= fleet.budget")])
    tpu.policy.validate()
    # both artifacts roll one revision forward
    cluster.bump_daemon_set_revision(ns, "libtpu", "new2")
    cluster.bump_daemon_set_revision(ns, "tpu-device-plugin", "dp2")

    manager = multi.managers["tpu"]
    stamp_prefix = manager.keys.artifact_stamp_prefix
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        reconcile_pass(multi, registry, latest_status)
        tpu_nodes = [n for n in cluster.list_nodes()
                     if n.metadata.name.startswith("tpu-")]
        complete = all(
            n.metadata.labels.get(manager.keys.state_label)
            == "upgrade-done"
            and n.metadata.annotations.get(
                stamp_prefix + "libtpu") == "new2"
            and n.metadata.annotations.get(
                stamp_prefix + "device-plugin") == "dp2"
            for n in tpu_nodes)
        if complete:
            block = latest_status.get("tpu", {})
            logger.info("DAG episode complete in %.0fs simulated: "
                        "both artifacts advanced through one shared "
                        "cordon/drain cycle per node", clock.now())
            print(json.dumps({
                "artifactDAG": block.get("artifactDAG"),
                "policy": block.get("policy"),
                "stamps": {
                    n.metadata.name: {
                        "libtpu": n.metadata.annotations.get(
                            stamp_prefix + "libtpu"),
                        "device-plugin": n.metadata.annotations.get(
                            stamp_prefix + "device-plugin"),
                    } for n in tpu_nodes},
            }, indent=2))
            return 0
        clock.advance(interval_sim_s)
        cluster.step()
    logger.error("DAG episode did not converge; status: %s",
                 latest_status.get("tpu"))
    return 1


def run_demo(registry: MetricsRegistry, latest_status: dict,
             interval_sim_s: float = 10.0) -> int:
    cluster, clock = build_demo_cluster()
    policy = load_unified_policy(None)
    multi = MultiAcceleratorUpgradeManager(
        cluster, policy, async_workers=False, clock=clock,
        poll_interval=0.0)
    install_observability(multi, clock=clock)

    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        reconcile_pass(multi, registry, latest_status)
        done = all(
            isinstance(block, dict)
            and block.get("totalNodes", 0) > 0
            and block.get("upgradesDone") == block.get("totalNodes")
            and block.get("unavailableNodes") == 0
            for block in latest_status.values())
        if done and len(latest_status) == len(policy.accelerators):
            logger.info("demo complete in %.0fs simulated", clock.now())
            print(json.dumps(latest_status, indent=2))
            # episode 2: the declarative two-artifact DAG upgrade
            return run_dag_episode(cluster, clock, multi, registry,
                                   latest_status, interval_sim_s)
        clock.advance(interval_sim_s)
        cluster.step()
    logger.error("demo did not converge; status: %s", latest_status)
    return 1


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--policy", help="unified policy YAML file")
    parser.add_argument("--interval", type=float, default=30.0)
    parser.add_argument("--metrics-port", type=int, default=0)
    parser.add_argument("--kubeconfig", action="store_true")
    parser.add_argument("--demo", action="store_true",
                        help="simulated mixed GPU+TPU fleet")
    args = parser.parse_args()

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    registry = MetricsRegistry()
    latest_status: dict = {}
    # bound once a MultiAcceleratorUpgradeManager exists; the server
    # starts first, so /explain routes through this holder
    explain_binding: dict = {"fn": None}
    server = None
    if args.metrics_port:
        from tpu_operator_libs.examples.libtpu_operator import serve_metrics

        server = serve_metrics(
            registry, args.metrics_port, status_source=latest_status,
            explain_source=lambda node: (
                explain_binding["fn"](node)
                if explain_binding["fn"] is not None
                else {"node": node, "error": "operator not started"}))
    try:
        if args.demo:
            return run_demo(registry, latest_status)

        from tpu_operator_libs.k8s.real import RealCluster

        cluster = (RealCluster.from_kubeconfig() if args.kubeconfig
                   else RealCluster.in_cluster())
        policy = load_unified_policy(args.policy)
        multi = MultiAcceleratorUpgradeManager(cluster, policy)
        install_observability(multi)
        explain_binding["fn"] = lambda node: explain_node(multi, node)
        stop = threading.Event()
        signal.signal(signal.SIGTERM, lambda *a: stop.set())
        signal.signal(signal.SIGINT, lambda *a: stop.set())
        while not stop.is_set():
            try:
                reconcile_pass(multi, registry, latest_status)
            except Exception:  # noqa: BLE001 — keep the loop alive
                logger.exception("reconcile pass failed; retrying")
            stop.wait(args.interval)
        return 0
    finally:
        if server is not None:
            server.shutdown()


if __name__ == "__main__":
    sys.exit(main())
