"""Ring attention: causal self-attention with the sequence sharded
over a mesh axis, K/V blocks rotating on the ICI ring.

Long-context workloads shard the *sequence* dimension (context/sequence
parallelism): each device holds one block of Q/K/V, computes its block's
attention against every K/V block as they rotate past via ``ppermute``,
and folds partial results with the flash-attention online-softmax
recurrence — numerically exact, never materializing the full S×S score
matrix or the full K/V on any device. Communication is one K/V block
per step on the ring, which rides ICI neighbor links (the layout the
scaling book prescribes for sequence parallelism on TPU).

The reference has no counterpart (it ships no model code); this is the
beyond-reference long-context side of the workload family, verified
exactly against dense attention in tests (the rotation is a
permutation and the softmax recurrence is exact, so results match to
float tolerance, not just statistically).
"""

from __future__ import annotations

from functools import partial


def _block_attention(q, k, v, q_pos, k_pos, m, l, acc, causal: bool):
    """Fold one K/V block into the online-softmax state.

    q: (B, Sq, H, D); k/v: (B, Sk, Hkv, D) where Hkv divides H
    (grouped-query attention — the repeat to full head count happens
    HERE, after the ring transfer, so each ppermute hop moves only the
    narrow KV heads); positions are global token indices used for
    causal masking across blocks. State: m (running max, B,H,Sq),
    l (running denominator), acc (B,H,Sq,D), all f32.
    """
    import jax.numpy as jnp

    if k.shape[2] != q.shape[2]:
        group = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    scores = scores * (q.shape[-1] ** -0.5)
    if causal:
        mask = q_pos[None, None, :, None] >= k_pos[None, None, None, :]
        scores = jnp.where(mask, scores, -jnp.inf)
    block_max = jnp.max(scores, axis=-1)
    m_new = jnp.maximum(m, block_max)
    # m_new is -inf only while nothing has attended at all; substituting
    # 0 there makes every downstream exp(-inf - 0) an exact 0 instead of
    # the nan exp(-inf - -inf) would give — the one guard this needs
    safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    correction = jnp.exp(m - safe)
    weights = jnp.exp(scores - safe[..., None])
    l_new = l * correction + jnp.sum(weights, axis=-1)
    acc_new = acc * correction[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", weights, v.astype(jnp.float32))
    return m_new, l_new, acc_new


def ring_attention(q, k, v, axis_name: str, axis_size: int,
                   causal: bool = True):
    """Blockwise ring attention; call INSIDE ``shard_map``.

    q/k/v: the local sequence block, (B, S_local, H, D), sequence
    sharded over ``axis_name`` (size ``axis_size``). Returns the local
    attention output block (B, S_local, H, D) in q's dtype.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    batch, s_local, heads, head_dim = q.shape
    my_block = lax.axis_index(axis_name)
    q_pos = my_block * s_local + jnp.arange(s_local)

    # The accumulators are device-local state and must carry exactly
    # the varying-manual-axes q does (jax >= 0.8 type-checks vma
    # through scan/cond carries; a hand-pcast over just the ring axis
    # breaks when the caller's shard_map also spans other axes, e.g. a
    # dp x sp mesh) — deriving them arithmetically from q inherits the
    # right vma automatically.
    zeros_bhs = jnp.transpose(q[..., 0] * 0.0,
                              (0, 2, 1)).astype(jnp.float32)
    m0 = zeros_bhs - jnp.inf
    l0 = zeros_bhs
    acc0 = jnp.transpose(q * 0.0, (0, 2, 1, 3)).astype(jnp.float32)
    ring = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(i, carry):
        k_cur, v_cur, m, l, acc = carry
        src_block = (my_block - i) % axis_size

        def fold(state):
            m, l, acc = state
            k_pos = src_block * s_local + jnp.arange(s_local)
            return _block_attention(q, k_cur, v_cur, q_pos, k_pos,
                                    m, l, acc, causal)

        if causal:
            # a block entirely in the future contributes exact zeros;
            # skip its einsum+exp rather than computing masked work.
            # (Devices early in the ring still idle while late ones
            # fold — the zigzag block layout is the balanced variant.)
            m, l, acc = lax.cond(src_block > my_block,
                                 lambda state: state, fold, (m, l, acc))
        else:
            m, l, acc = fold((m, l, acc))
        # rotate K/V one hop around the ring for the next step (the
        # final rotation is wasted but keeps the loop body uniform);
        # the collective stays OUTSIDE the cond — every device must
        # participate in every ppermute
        k_nxt = lax.ppermute(k_cur, axis_name, ring)
        v_nxt = lax.ppermute(v_cur, axis_name, ring)
        return k_nxt, v_nxt, m, l, acc

    _, _, m, l, acc = lax.fori_loop(0, axis_size, step,
                                    (k, v, m0, l0, acc0))
    # fully-masked rows (none under causal self-attention, where every
    # query sees at least itself) would divide 0/0; guard anyway
    denom = jnp.where(l == 0.0, 1.0, l)
    out = acc / denom[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def make_ring_attention(mesh, axis_name: str = "sp",
                        causal: bool = True):
    """A jitted (q, k, v) -> out over sequence-sharded global arrays.

    Inputs/outputs are global (B, S, H, D) arrays sharded
    ``P(None, axis_name, None, None)``; internally runs the ring via
    ``shard_map``.
    """
    import jax
    try:
        from jax import shard_map
    except ImportError:  # pre-0.7 jax: experimental location
        from functools import partial as _partial

        from jax.experimental.shard_map import shard_map as _shard_map

        # check_rep rejects valid rep types around lax.cond on old jax
        # (the check no longer exists upstream); disable, same semantics
        shard_map = _partial(_shard_map, check_rep=False)
    from jax.sharding import NamedSharding, PartitionSpec as P

    axis_size = mesh.shape[axis_name]
    spec = P(None, axis_name, None, None)

    inner = partial(ring_attention, axis_name=axis_name,
                    axis_size=axis_size, causal=causal)
    sharded = shard_map(inner, mesh=mesh,
                        in_specs=(spec, spec, spec), out_specs=spec)

    def place(x):
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.jit(lambda q, k, v: sharded(place(q), place(k),
                                           place(v)))


def dense_reference(q, k, v, causal: bool = True):
    """Unsharded exact attention for verification."""
    import jax
    import jax.numpy as jnp

    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    scores = scores * (q.shape[-1] ** -0.5)
    if causal:
        seq = q.shape[1]
        mask = jnp.tril(jnp.ones((seq, seq), jnp.bool_))
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    attn = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", attn,
                      v.astype(jnp.float32)).astype(q.dtype)
