#!/usr/bin/env python3
"""Admission webhook for the upgrade-policy CRDs.

The reference relies on kubebuilder markers compiled into CRD schemas for
defaulting and validation (api/upgrade/v1alpha1/upgrade_spec.go:27-110);
this build additionally ships the admission-side implementations
(tpu_operator_libs/api/crd.py: ``apply_defaults`` /
``validate_against_schema``), and this webhook serves them the way a
cluster consumes them:

- ``POST /validate`` — ValidatingWebhook: reject a TPUUpgradePolicy /
  UnifiedUpgradePolicy whose spec fails schema validation *or* semantic
  validation (``UpgradePolicySpec.validate``, e.g. negative percent
  strings the reference silently accepts).
- ``POST /mutate`` — MutatingWebhook: fill in schema defaults
  (maxParallelUpgrades=1, maxUnavailable="25%", timeouts) as a JSONPatch,
  so stored objects are fully defaulted like kubebuilder CRDs.

Both speak ``admission.k8s.io/v1 AdmissionReview``. TLS (required by
real apiservers) via ``--tls-cert/--tls-key``; plain HTTP without, for
tests and port-forward experiments.
"""

from __future__ import annotations

import argparse
import base64
import json
import logging
import sys
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from tpu_operator_libs.api.crd import (  # noqa: E402
    apply_defaults,
    unified_policy_schema,
    upgrade_policy_schema,
    validate_against_schema,
)
from tpu_operator_libs.api.unified_policy import (  # noqa: E402
    UnifiedUpgradePolicySpec,
)
from tpu_operator_libs.api.upgrade_policy import (  # noqa: E402
    PolicyValidationError,
    UpgradePolicySpec,
)

logger = logging.getLogger("admission-webhook")

#: kind -> (schema, semantic validator over the defaulted spec dict)
_KINDS = {
    "TPUUpgradePolicy": (
        upgrade_policy_schema,
        lambda spec: UpgradePolicySpec.from_dict(spec).validate()),
    "UnifiedUpgradePolicy": (
        unified_policy_schema,
        lambda spec: UnifiedUpgradePolicySpec.from_dict(spec).validate()),
}


def review_response(request: dict, *, allowed: bool,
                    message: str = "", patch: list | None = None) -> dict:
    response: dict = {"uid": request.get("uid", ""), "allowed": allowed}
    if message:
        response["status"] = {"message": message}
    if patch is not None:
        response["patchType"] = "JSONPatch"
        response["patch"] = base64.b64encode(
            json.dumps(patch).encode()).decode()
    return {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
            "response": response}


def handle_review(body: dict, mutate: bool) -> dict:
    request = body.get("request") or {}
    if request.get("operation") == "DELETE":
        # DELETE reviews carry object: null (the old object is in
        # oldObject); there is nothing to validate or default, and
        # denying would make policies undeletable
        return review_response(request, allowed=True)
    kind = (request.get("kind") or {}).get("kind", "")
    entry = _KINDS.get(kind)
    if entry is None:
        return review_response(
            request, allowed=False,
            message=f"unsupported kind {kind!r}; expected one of "
                    f"{sorted(_KINDS)}")
    schema_fn, semantic = entry
    schema = schema_fn()
    obj = request.get("object") or {}
    spec = obj.get("spec")
    if spec is None or not isinstance(spec, dict):
        return review_response(request, allowed=False,
                               message="spec: required and must be an "
                                       "object")
    try:
        validate_against_schema(spec, schema)
        defaulted = apply_defaults(spec, schema)
        semantic(defaulted)
    except PolicyValidationError as exc:
        return review_response(request, allowed=False, message=str(exc))
    if not mutate or defaulted == spec:
        return review_response(request, allowed=True)
    return review_response(
        request, allowed=True,
        patch=[{"op": "replace", "path": "/spec", "value": defaulted}])


def make_server(port: int, tls_cert: str = "",
                tls_key: str = "") -> ThreadingHTTPServer:
    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):  # noqa: N802 - stdlib API
            if self.path not in ("/validate", "/mutate"):
                self.send_response(404)
                self.end_headers()
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
                body = json.loads(self.rfile.read(length))
                review = handle_review(body, mutate=self.path == "/mutate")
            except Exception as exc:  # noqa: BLE001 — malformed review
                self.send_response(400)
                self.end_headers()
                self.wfile.write(str(exc).encode())
                return
            payload = json.dumps(review).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            self.wfile.write(payload)

        def log_message(self, *args):  # quiet
            pass

    server = ThreadingHTTPServer(("", port), Handler)
    if tls_cert and tls_key:
        import ssl

        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(tls_cert, tls_key)
        server.socket = ctx.wrap_socket(server.socket, server_side=True)
    return server


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--port", type=int, default=8443)
    parser.add_argument("--tls-cert", default="",
                        help="PEM cert (apiservers require TLS)")
    parser.add_argument("--tls-key", default="")
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    server = make_server(args.port, args.tls_cert, args.tls_key)
    logger.info("admission webhook on :%d (/validate, /mutate)%s",
                args.port, "" if args.tls_cert else " [no TLS]")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
