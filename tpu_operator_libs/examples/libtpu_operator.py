#!/usr/bin/env python3
"""Example consumer operator (the reference's out-of-tree L5 layer).

The reference library has no main(); GPU-Operator-style controllers import
it and call SetDriverName → NewClusterUpgradeStateManager → BuildState →
ApplyState per reconcile (SURVEY.md §3.1). This example is that consumer
for libtpu on GKE, runnable two ways:

    # against a live cluster (requires the `kubernetes` package):
    python examples/libtpu_operator.py --kubeconfig --policy policy.yaml

    # demo: simulated 4-slice fleet with a rolling libtpu upgrade
    python examples/libtpu_operator.py --demo

It wires everything this library offers: topology-aware planning, the
Orbax checkpoint eviction gate, the ICI fabric validator, Prometheus
metrics on --metrics-port, and a reconcile loop that treats every error as
retryable (the state machine is stateless/idempotent by design).
"""

from __future__ import annotations

import argparse
import json
import logging
import signal
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from tpu_operator_libs.api.upgrade_policy import UpgradePolicySpec
from tpu_operator_libs.consts import UpgradeKeys
from tpu_operator_libs.metrics import (
    MetricsRegistry,
    observe_client_health,
    observe_cluster_state,
    observe_journeys,
    observe_rollout,
)
from tpu_operator_libs.upgrade.state_manager import (
    BuildStateError,
    ClusterUpgradeStateManager,
)

logger = logging.getLogger("libtpu-operator")


def load_policy(path: str | None) -> UpgradePolicySpec:
    if path is None:
        return UpgradePolicySpec(
            auto_upgrade=True, max_parallel_upgrades=0,
            max_unavailable="25%", topology_mode="slice")
    with open(path) as f:
        text = f.read()
    try:
        data = json.loads(text)
    except json.JSONDecodeError:
        import yaml

        data = yaml.safe_load(text)
    if not isinstance(data, dict):
        raise ValueError(
            f"policy file {path!r} is empty or not a mapping")
    inner = data.get("upgradePolicy", data)
    if not isinstance(inner, dict):
        raise ValueError(
            f"policy file {path!r}: 'upgradePolicy' must be a mapping")
    spec = UpgradePolicySpec.from_dict(inner)
    spec.validate()
    return spec


#: Latest CRD-style status block per driver, refreshed each reconcile and
#: served at /status (the operator-side view of cluster_status()).
latest_status: dict = {}

#: The live manager's explain entry point, bound by build_manager once
#: the manager exists (the HTTP server starts earlier) — the default
#: backing for /explain/<node>.
explain_binding: dict = {"fn": None}

#: The live manager's preflight forecast, bound by build_manager — the
#: default backing for /preflight (the what-if picture next to
#: /explain: what would admitting the pending rollout do?).
preflight_binding: dict = {"fn": None}


def _default_explain(node_name: str) -> dict:
    fn = explain_binding["fn"]
    if fn is None:
        return {"node": node_name,
                "error": "operator not started yet — no manager bound"}
    return fn(node_name)


def _default_preflight() -> dict:
    fn = preflight_binding["fn"]
    if fn is None:
        return {"error": "operator not started yet — no manager bound"}
    forecast = fn()
    if forecast is None:
        return {"mode": "off",
                "detail": "no preflight forecast: the policy does not "
                          "enable preflight (spec.preflight.mode)"}
    return forecast


def serve_metrics(registry: MetricsRegistry, port: int,
                  status_source=None,
                  explain_source=None,
                  preflight_source=None) -> ThreadingHTTPServer:
    """HTTP server for /metrics + /status + /explain/<node> +
    /preflight. ``status_source`` is the mutable status mapping to
    serve (default: this module's ``latest_status``) — passed
    explicitly so other operators (the unified example) don't have to
    rebind a cross-module global. ``explain_source`` is
    ``fn(node_name) -> dict`` (default: the manager bound via
    ``explain_binding``) — the decision-audit's public query: why is
    this node not upgrading? ``preflight_source`` is ``fn() -> dict``
    (default: the manager bound via ``preflight_binding``) — the
    what-if query: the most recent rollout forecast and the verdict
    the admission gate acted on."""
    if status_source is None:
        status_source = latest_status
    if explain_source is None:
        explain_source = _default_explain
    if preflight_source is None:
        preflight_source = _default_preflight

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - stdlib API
            import json as _json

            if self.path == "/metrics":
                body = registry.render_prometheus().encode()
                content_type = "text/plain; version=0.0.4"
            elif self.path == "/status":
                # shallow copy: the reconcile thread inserts keys
                # concurrently and dict iteration must not race it
                body = _json.dumps(dict(status_source), indent=2).encode()
                content_type = "application/json"
            elif self.path == "/preflight":
                try:
                    result = preflight_source()
                except Exception as exc:  # noqa: BLE001 — the debug
                    # surface must answer, not 500, mid-incident
                    result = {"error": str(exc)}
                body = _json.dumps(result, indent=2).encode()
                content_type = "application/json"
            elif self.path.startswith("/explain/"):
                from urllib.parse import unquote

                node = unquote(self.path[len("/explain/"):])
                try:
                    result = explain_source(node)
                except Exception as exc:  # noqa: BLE001 — the debug
                    # surface must answer, not 500, mid-incident
                    result = {"node": node, "error": str(exc)}
                body = _json.dumps(result, indent=2).encode()
                content_type = "application/json"
            else:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Type", content_type)
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # quiet
            pass

    server = ThreadingHTTPServer(("", port), Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    logger.info("metrics on :%d/metrics, status on :%d/status, "
                "explain on :%d/explain/<node>, preflight on "
                ":%d/preflight", port, port, port, port)
    return server


def build_manager(args, cluster, clock=None,
                  poll_interval: float = 1.0) -> ClusterUpgradeStateManager:
    keys = UpgradeKeys(driver=args.driver, domain=args.domain)
    # Correlated recorder: duplicate counting, similar-event
    # aggregation and per-object spam filtering (client-go
    # EventCorrelator semantics) so a fleet-wide wave cannot emit an
    # event storm. Surviving events land in the cluster's Events API
    # (kubectl describe node parity); the sink self-disables on
    # backends without one.
    from tpu_operator_libs.k8s.events import ClusterEventSink
    from tpu_operator_libs.util import Clock, CorrelatingEventRecorder

    mgr = ClusterUpgradeStateManager(
        cluster, keys, clock=clock, poll_interval=poll_interval,
        recorder=CorrelatingEventRecorder(
            clock=clock or Clock(),
            sink=ClusterEventSink(cluster, args.namespace)))
    # journey tracing + decision audit: spans/records assembled from
    # the same commit seam the predictor stamps ride; serves
    # /explain/<node> and the cluster_status "trace" block
    from tpu_operator_libs.obs import OperatorObservability

    mgr.with_observability(OperatorObservability(
        keys, clock=clock or Clock()))
    explain_binding["fn"] = mgr.explain
    preflight_binding["fn"] = lambda: mgr.last_preflight
    if args.job_selector:
        gate = None
        if args.checkpoint_dir:
            from tpu_operator_libs.health.checkpoint_gate import (
                CheckpointDurabilityGate,
            )

            gate = CheckpointDurabilityGate(
                args.checkpoint_dir,
                max_age_seconds=args.checkpoint_max_age)
        selector = args.job_selector

        def deletion_filter(pod, _selector=selector):
            from tpu_operator_libs.k8s.selectors import matches_labels

            return matches_labels(_selector, pod.metadata.labels)

        mgr.with_pod_deletion_enabled(deletion_filter, eviction_gate=gate)
    if args.validator_selector or args.ici_probe:
        extra = None
        if args.ici_probe:
            from tpu_operator_libs.health.ici_probe import ICIFabricValidator

            extra = ICIFabricValidator(
                min_bandwidth_gbytes_per_s=getattr(
                    args, "min_bandwidth_gbytes_per_s", None))
        mgr.with_validation_enabled(args.validator_selector or "",
                                    extra_validator=extra)
    return mgr


def parse_runtime_labels(args) -> dict[str, str]:
    return dict(kv.split("=", 1)
                for kv in args.runtime_labels.split(",") if kv)


def reconcile_once(mgr, args, policy, registry, runtime_labels) -> None:
    """One build_state+apply_state pass with metrics/logging; shared by
    the polling and watch-driven loops. BuildStateError (incomplete
    snapshot) is retryable and only logged."""
    started = time.monotonic()
    try:
        state = mgr.build_state(args.namespace, runtime_labels)
        # status reflects the snapshot even when the transition pass below
        # fails — /status must not freeze on the last-good block during
        # exactly the incident it exists to expose
        latest_status[args.driver] = mgr.cluster_status(state)
        mgr.apply_state(state, policy)
        observe_cluster_state(registry, mgr, state, driver=args.driver)
        # canary/halt/rollback accounting rides the same scrape: the
        # rollout_halted gauge flipping to 1 is the on-call page
        observe_rollout(registry, mgr.rollout_guard, driver=args.driver)
        if mgr.observability is not None:
            # journey spans + decision-audit accounting, with trace-id
            # exemplars on the phase-duration histograms
            observe_journeys(registry, mgr.observability,
                             driver=args.driver)
        logger.info("reconciled: %d/%d done, %d in progress, %d failed",
                    mgr.get_upgrades_done(state),
                    mgr.get_total_managed_nodes(state),
                    mgr.get_upgrades_in_progress(state),
                    mgr.get_upgrades_failed(state))
    except BuildStateError as exc:
        logger.info("snapshot incomplete (%s); retrying", exc)
    finally:
        # histogram, not gauge: same metric family the watch-driven
        # Controller records, so dashboards see one latency series
        registry.observe_histogram("reconcile_duration_seconds",
                                   time.monotonic() - started,
                                   "Wall-clock seconds per reconcile pass",
                                   {"driver": args.driver})
        # client-side health: throttle time (on the write client behind
        # any read cache) + event-correlation drop counters
        write_client = getattr(mgr.client, "delegate", mgr.client)
        observe_client_health(
            registry, args.driver,
            limiter=getattr(write_client, "rate_limiter", None),
            recorder=mgr.recorder)


def reconcile_forever(mgr, args, policy, registry, stop: threading.Event,
                      step_hook=None) -> None:
    runtime_labels = parse_runtime_labels(args)
    while not stop.is_set():
        try:
            reconcile_once(mgr, args, policy, registry, runtime_labels)
        except Exception:
            logger.exception("reconcile failed; retrying")
        if step_hook is not None:
            if step_hook():
                return
        stop.wait(args.interval)


def run_demo(args, registry) -> int:
    """Simulated fleet, two episodes end to end:

    1. a full slice-atomic rolling upgrade (old -> new), then
    2. a canary-halt-rollback walk: the DaemonSet rolls to a BROKEN
       revision whose pods can never become Ready; the canary cohort
       probes it, fails, the RolloutGuard halts the fleet, quarantines
       the revision, re-pins the previous one, and every touched node
       rolls back — the fleet converges on the old revision with the
       quarantine annotation as the durable record.
    """
    from tpu_operator_libs.api.upgrade_policy import (
        CanaryRolloutSpec,
        RollbackSpec,
    )
    from tpu_operator_libs.consts import POD_CONTROLLER_REVISION_HASH_LABEL
    from tpu_operator_libs.simulate import (
        NS,
        RUNTIME_LABELS,
        FleetSpec,
        build_fleet,
    )

    fleet = FleetSpec(n_slices=args.demo_slices, hosts_per_slice=4)
    cluster, clock, keys = build_fleet(fleet)
    args.namespace = NS
    args.runtime_labels = ",".join(f"{k}={v}"
                                   for k, v in RUNTIME_LABELS.items())
    mgr = build_manager(args, cluster, clock=clock, poll_interval=0.0)
    policy = load_policy(args.policy)

    virtual_interval = args.interval  # simulated seconds between passes
    deadline = time.monotonic() + 120  # real-time safety stop
    args.interval = 0.0  # no real-time sleep between simulated passes

    def drive(done, what: str) -> bool:
        """Run reconcile passes over virtual time until ``done()``."""
        stop = threading.Event()
        outcome = {"ok": False}

        def step_hook() -> bool:
            clock.advance(virtual_interval)
            cluster.step()
            if done():
                outcome["ok"] = True
                stop.set()
                return True
            if time.monotonic() > deadline:
                logger.error("demo %s did not converge within the "
                             "safety window", what)
                stop.set()
                return True
            return False

        reconcile_forever(mgr, args, policy, registry, stop, step_hook)
        return outcome["ok"]

    def fleet_done_on(revision: str) -> bool:
        nodes = cluster.list_nodes()
        if not all(n.metadata.labels.get(keys.state_label, "")
                   == "upgrade-done" and not n.is_unschedulable()
                   for n in nodes):
            return False
        pods = [p for p in cluster.list_pods(namespace=NS)
                if p.controller_owner() is not None]
        return len(pods) == len(nodes) and all(
            p.metadata.labels.get(POD_CONTROLLER_REVISION_HASH_LABEL)
            == revision and p.is_ready() for p in pods)

    # ---- episode 1: the plain rolling upgrade (old -> new) ----------
    if not drive(lambda: fleet_done_on("new"), "rolling upgrade"):
        return 1
    logger.info("demo episode 1 complete: all %d nodes upgraded in "
                "%.0fs simulated", len(cluster.list_nodes()), clock.now())

    # ---- episode 2: canary wave -> halt -> automatic rollback -------
    policy.canary = CanaryRolloutSpec(enable=True, canary_count=1,
                                      bake_seconds=60,
                                      failure_threshold=1)
    policy.rollback = RollbackSpec(enable=True)
    # the broken build: pods of this revision never become Ready
    cluster.add_pod_ready_gate(
        lambda pod: pod.metadata.labels.get(
            POD_CONTROLLER_REVISION_HASH_LABEL) != "broken")
    cluster.bump_daemon_set_revision(NS, "libtpu", "broken")
    logger.info("demo episode 2: DaemonSet rolled to BROKEN revision; "
                "canary wave begins")

    def rolled_back() -> bool:
        if not fleet_done_on("new"):
            return False
        return any(
            ds.metadata.annotations.get(
                keys.quarantined_revision_annotation) == "broken"
            for ds in cluster.list_daemon_sets(NS))

    if not drive(rolled_back, "canary rollback"):
        return 1
    guard = mgr.rollout_guard
    logger.info(
        "demo episode 2 complete in %.0fs simulated: %d failure "
        "verdict(s), %d halt(s), %d rollback(s) — fleet back on the "
        "previous revision, 'broken' quarantined",
        clock.now(), guard.canary_failure_verdicts_total,
        guard.halts_total, guard.rollbacks_started_total)
    print(registry.render_prometheus())
    return 0


def election_config(args):
    """The one LeaderElectionConfig both run paths share — the watch and
    poll variants of the same deployment must contend for the SAME
    lease."""
    import os
    import socket

    from tpu_operator_libs.k8s.leaderelection import LeaderElectionConfig

    identity = args.leader_identity \
        or f"{socket.gethostname()}-{os.getpid()}"
    return LeaderElectionConfig(namespace=args.namespace,
                                name="tpu-operator-leader",
                                identity=identity)


def run_leader_elected(args, cluster, stop: threading.Event,
                       run_loop) -> None:
    """Gate the reconcile loop on a coordination.k8s.io Lease, the way a
    controller-runtime manager does for the reference's consumers. The
    reconcile loop starts when leadership is acquired and the process
    exits when it is lost (the standard HA-operator pattern: let the
    replica controller restart us as a follower)."""
    from tpu_operator_libs.k8s.leaderelection import LeaderElector

    config = election_config(args)
    identity = config.identity
    loop_thread: list[threading.Thread] = []

    def on_started():
        logger.info("leader election: became leader as %s", identity)
        thread = threading.Thread(target=run_loop, daemon=True)
        thread.start()
        loop_thread.append(thread)

    def on_stopped():
        logger.warning("leader election: leadership lost; stopping")
        stop.set()

    elector = LeaderElector(
        cluster, config,
        on_started_leading=on_started,
        on_stopped_leading=on_stopped,
        on_new_leader=lambda leader: logger.info(
            "leader election: current leader is %s", leader))
    elector.run(stop)
    for thread in loop_thread:
        thread.join(timeout=5.0)


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--namespace", default="tpu-system")
    parser.add_argument("--runtime-labels", default="app=libtpu",
                        help="k=v[,k=v] selecting the runtime DaemonSet")
    parser.add_argument("--driver", default="libtpu")
    parser.add_argument("--domain", default="google.com")
    parser.add_argument("--policy", help="policy YAML/JSON file")
    parser.add_argument("--interval", type=float, default=30.0)
    parser.add_argument("--metrics-port", type=int, default=0,
                        help="serve /metrics on this port (0 = off)")
    parser.add_argument("--job-selector", default="",
                        help="label selector for workload pods to delete")
    parser.add_argument("--checkpoint-dir", default="",
                        help="Orbax checkpoint root gating eviction")
    parser.add_argument("--checkpoint-max-age", type=float, default=0.0)
    parser.add_argument("--validator-selector", default="",
                        help="label selector for validation pods")
    parser.add_argument("--min-bandwidth-gbytes-per-s", type=float,
                        default=None,
                        help="fail validation when measured per-link ICI "
                             "throughput is below this floor (GByte/s); "
                             "requires --ici-probe")
    parser.add_argument("--ici-probe", action="store_true",
                        help="gate validation on the local ICI fabric probe")
    parser.add_argument("--api-qps", type=float, default=20.0,
                        help="client-side API rate limit in requests/s "
                             "(controller-runtime default 20; 0 disables)")
    parser.add_argument("--api-burst", type=int, default=30,
                        help="client-side API burst size "
                             "(controller-runtime default 30)")
    parser.add_argument("--api-server", default="",
                        help="apiserver base URL (e.g. a kubectl proxy "
                             "at http://127.0.0.1:8001): run on the "
                             "dependency-free HTTP adapter instead of "
                             "the kubernetes client package")
    parser.add_argument("--token-file", default="",
                        help="bearer-token file for --api-server")
    parser.add_argument("--ca-file", default="",
                        help="CA bundle for --api-server TLS")
    parser.add_argument("--kubeconfig", action="store_true",
                        help="connect via local kubeconfig (else in-cluster)")
    parser.add_argument("--leader-elect", action="store_true",
                        help="run only while holding the Lease "
                             "<namespace>/tpu-operator-leader (HA replicas)")
    parser.add_argument("--leader-identity", default="",
                        help="contender identity (default: hostname+pid)")
    parser.add_argument("--no-cache", action="store_true",
                        help="read straight from the apiserver instead of "
                             "the informer-backed read cache")
    parser.add_argument("--poll", action="store_true",
                        help="fixed-interval polling instead of the "
                             "default watch-driven reconcile loop")
    parser.add_argument("--demo", action="store_true",
                        help="run against a simulated fleet")
    parser.add_argument("--demo-slices", type=int, default=4)
    args = parser.parse_args()
    if args.min_bandwidth_gbytes_per_s is not None and not args.ici_probe:
        # without the probe the floor would be silently unenforced
        parser.error("--min-bandwidth-gbytes-per-s requires --ici-probe")
    if args.api_qps > 0 and args.api_burst < 1:
        parser.error("--api-burst must be >= 1 when --api-qps is enabled "
                     "(use --api-qps 0 to disable client-side throttling)")

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    registry = MetricsRegistry()
    server = serve_metrics(registry, args.metrics_port) \
        if args.metrics_port else None

    try:
        if args.demo:
            return run_demo(args, registry)

        limiter = None
        if args.api_qps > 0:
            # client-go charges every HTTP request against a token
            # bucket at the transport; the Python client has no such
            # layer, so RealCluster mounts ours in the same place
            from tpu_operator_libs.k8s.flowcontrol import (
                TokenBucketRateLimiter,
            )

            limiter = TokenBucketRateLimiter(
                qps=args.api_qps, burst=args.api_burst)
        if args.api_server:
            # dependency-free path: no `kubernetes` package required;
            # the token file is re-read on rotation (bound SA tokens
            # expire ~hourly)
            from tpu_operator_libs.k8s.http import HttpCluster

            cluster = HttpCluster(args.api_server,
                                  token_file=args.token_file or None,
                                  ca_file=args.ca_file or None,
                                  rate_limiter=limiter)
        else:
            from tpu_operator_libs.k8s.real import RealCluster

            cluster = (
                RealCluster.from_kubeconfig(rate_limiter=limiter)
                if args.kubeconfig
                else RealCluster.in_cluster(rate_limiter=limiter))
        policy = load_policy(args.policy)
        stop = threading.Event()
        signal.signal(signal.SIGTERM, lambda *a: stop.set())
        signal.signal(signal.SIGINT, lambda *a: stop.set())

        exit_code = [0]

        if not args.poll:
            # Watch-driven default: OperatorManager packages the cached
            # client, controller, and (optionally) leader election the
            # way controller-runtime's manager does — caches are built
            # only after leadership is won.
            from tpu_operator_libs.controller import ReconcileResult
            from tpu_operator_libs.manager import OperatorManager

            runtime_labels = parse_runtime_labels(args)
            held = {}
            # Completion-driven wakeups: drain/eviction workers and the
            # deadline timer wheel (validation / wait-for-jobs / canary
            # bake expiries) enqueue a reconcile the moment an outcome
            # lands — the resync interval remains only as a safety net.
            from tpu_operator_libs.upgrade.nudger import ReconcileNudger

            nudger = ReconcileNudger()

            def reconcile(_key):
                if "mgr" not in held:
                    held["mgr"] = build_manager(
                        args, op_mgr.client).with_nudger(nudger)
                nudger.pop_due()  # consume deadline slots this pass acts on
                reconcile_once(held["mgr"], args, policy, registry,
                               runtime_labels)
                if held["mgr"].last_pass_deferrals:
                    # a transiently-deferred node produced no cluster
                    # change, hence no watch event — requeue with the
                    # controller's error backoff instead of waiting
                    # out the resync interval
                    return ReconcileResult(requeue=True)
                return ReconcileResult()

            election = election_config(args) if args.leader_elect else None
            op_mgr = OperatorManager(
                cluster, args.namespace, reconcile,
                name=f"{args.driver}-operator",
                use_cache=not args.no_cache,
                resync_period=args.interval,
                leader_election=election, metrics=registry,
                nudger=nudger)
            try:
                op_mgr.run(stop)
            except TimeoutError as exc:
                logger.error("startup failed: %s", exc)
                exit_code[0] = 1
            return exit_code[0]

        def run_loop():
            # Polling fallback (--poll). Built here — after leader
            # election is won — so standby replicas hold no informer
            # caches or watch streams.
            client = cluster
            cached = None
            if not args.no_cache:
                from tpu_operator_libs.k8s.cached import CachedReadClient

                client = cached = CachedReadClient(cluster, args.namespace)
                if not cached.has_synced(timeout=60.0):
                    logger.error("informer caches failed to sync "
                                 "within 60s")
                    cached.stop()
                    exit_code[0] = 1  # startup failure must not exit 0
                    stop.set()
                    return
            try:
                mgr = build_manager(args, client)
                reconcile_forever(mgr, args, policy, registry, stop)
            finally:
                if cached is not None:
                    cached.stop()

        if args.leader_elect:
            run_leader_elected(args, cluster, stop, run_loop)
        else:
            run_loop()
        return exit_code[0]
    finally:
        if server is not None:
            server.shutdown()


if __name__ == "__main__":
    sys.exit(main())
