"""ICI-topology-aware upgrade planning.

The reference treats nodes as independent and throttles purely by count
(GetUpgradesAvailable, upgrade_state.go:1073-1102). On multi-host TPU
slices that model is wrong: all hosts of a slice are coupled by the ICI
fabric, and draining any one host idles the entire slice (SURVEY.md §5
"long-context / topology-coupled upgrade ordering"; BASELINE config #3).
This package changes the unit of work from node to slice.
"""

from tpu_operator_libs.topology.slice_topology import (  # noqa: F401
    SliceInfo,
    SliceTopology,
    decode_degraded_slices,
    encode_degraded_slices,
    slice_id_for_node,
)
from tpu_operator_libs.topology.planner import SlicePlanner  # noqa: F401
from tpu_operator_libs.topology.reconfigurer import (  # noqa: F401
    SliceReconfigurer,
)
