"""SliceReconfigurer: route a slice around a condemned node.

The Ironwood retrospective credits optical-circuit-switch
reconfiguration — remapping a slice around failed hosts rather than
waiting on repair — as a primary fleet-resilience mechanism. This module
is the GKE-label analogue: slice membership IS the nodepool label, so a
remap is a pair of crash-ordered label patches instead of an OCS
program.

When remediation condemns a node (attempt budget exhausted, wedge signal
still present — the durable ``condemned-at`` annotation plus the
``NodeCondemned`` Event), the node enters the remediation machine's
``reconfigure-required`` state and this class drives the remap:

1. **Reserve** a spare from the spare pool (``TopologyKeys.
   spare_pool_label``, matching accelerator/topology labels) by stamping
   ``reserved-for: <slice>/<condemned-host>:<epoch>`` on it — the
   durable booking no second remap can double-claim.
2. **Joint plan**: wait until the spare is on the target revision
   (``upgrade-done``, runtime pod ready on the DaemonSet's newest
   ControllerRevision). The upgrade planners prioritize reserved spares
   (and pass them through an active canary wave), so the spare takes its
   one cordon/drain cycle while still OUT of the slice — joining it
   never disrupts the slice again.
3. **Join then release**: one patch joins the spare to the pool (and
   stamps ``remapped-at``), a second removes the condemned node from the
   pool. Join-before-release means the slice is never observed short of
   hosts; a crash between the two resumes from the ``remapped-at``
   marker.
4. **Degraded admission**: with no eligible spare (or after the
   spare-provision deadline), the lost host is recorded in the runtime
   DaemonSet's ``degraded-slices`` annotation in ONE patch (the
   RolloutGuard quarantine idiom) BEFORE the release — planners and the
   serving gate see a documented reduced shape, never a silently short
   slice. A spare appearing later heals the entry back to full shape.

Every decision re-derives from cluster state (annotations + labels), so
a crashed operator resumes a half-finished remap for free; the object
itself holds only metrics accumulators. Deadlines (spare provision,
remap settle) register nudger wakeups so reconfiguration never waits on
a resync tick.
"""

from __future__ import annotations

import logging
from typing import TYPE_CHECKING, Callable, Optional

from tpu_operator_libs.consts import (
    GKE_NODEPOOL_LABEL,
    GKE_TPU_ACCELERATOR_LABEL,
    GKE_TPU_TOPOLOGY_LABEL,
    POD_CONTROLLER_REVISION_HASH_LABEL,
    TRUE_STRING,
    RemediationKeys,
    TopologyKeys,
    UpgradeKeys,
    UpgradeState,
)
from tpu_operator_libs.k8s.client import K8sClient
from tpu_operator_libs.k8s.objects import DaemonSet, Node
from tpu_operator_libs.k8s.selectors import selector_from_labels
from tpu_operator_libs.topology.slice_topology import (
    decode_degraded_slices,
    encode_degraded_slices,
)
from tpu_operator_libs.util import Clock, Event, EventRecorder, log_event

if TYPE_CHECKING:  # pragma: no cover - types only
    from tpu_operator_libs.api.remediation_policy import (
        ReconfigurationPolicySpec,
    )
    from tpu_operator_libs.remediation.state_machine import (
        NodeRemediationState,
        RemediationSnapshot,
    )
    from tpu_operator_libs.upgrade.nudger import ReconcileNudger

logger = logging.getLogger(__name__)

#: advance() verdicts the remediation machine commits on.
RELEASED = "released"
PENDING = "pending"


class SliceReconfigurer:
    """Remaps slices of condemned nodes onto spares (or degraded shapes).

    ``guard`` wraps every durable write (chaos harnesses pass the crash
    fuse here so remap commits crash mid-sequence exactly like the state
    machines' label writes do).
    """

    def __init__(self, client: K8sClient,
                 keys: Optional[TopologyKeys] = None,
                 remediation_keys: Optional[RemediationKeys] = None,
                 upgrade_keys: Optional[UpgradeKeys] = None,
                 recorder: Optional[EventRecorder] = None,
                 clock: Optional[Clock] = None,
                 nudger: Optional["ReconcileNudger"] = None,
                 guard: Optional[Callable[[Callable[[], object]], object]]
                 = None) -> None:
        self.client = client
        self.keys = keys or TopologyKeys()
        self.remediation_keys = remediation_keys or RemediationKeys(
            driver=self.keys.driver, domain=self.keys.domain)
        self.upgrade_keys = upgrade_keys or UpgradeKeys(
            driver=self.keys.driver, domain=self.keys.domain)
        self.recorder = recorder
        self.clock = clock or Clock()
        self.nudger = nudger
        self._guard = guard or (lambda write: write())
        # fleet counters (exported via metrics.observe_topology)
        self.reconfigurations_total = 0
        self.degraded_admissions_total = 0
        self.degraded_healed_total = 0
        self.spares_reserved_total = 0
        self._remap_seconds: list[float] = []
        # per-pass working set (begin_pass)
        self._by_name: dict[str, "NodeRemediationState"] = {}
        self._daemon_sets: list[DaemonSet] = []
        self._newest: dict[str, Optional[str]] = {}

    def drain_remap_durations(self) -> "list[float]":
        """Pop condemned→remapped durations (seconds) accumulated since
        the last call — the time-to-remapped histogram feed."""
        out, self._remap_seconds = self._remap_seconds, []
        return out

    # ------------------------------------------------------------------
    # per-pass working set
    # ------------------------------------------------------------------
    def begin_pass(self, snapshot: "RemediationSnapshot") -> None:
        """Resolve the pass's runtime DaemonSets, their newest revisions
        and the per-node index once (the remap decisions below are pure
        in the snapshot plus these)."""
        self._by_name = {
            ns.node.metadata.name: ns
            for bucket in snapshot.node_states.values() for ns in bucket}
        self._daemon_sets = sorted(
            self.client.list_daemon_sets(
                snapshot.namespace,
                selector_from_labels(snapshot.runtime_labels)),
            key=lambda ds: (ds.metadata.namespace, ds.metadata.name))
        self._newest = {}

    def _newest_hash(self, ds: DaemonSet) -> Optional[str]:
        cached = self._newest.get(ds.metadata.uid, "unset")
        if cached != "unset":
            return cached
        revisions = self.client.list_controller_revisions(
            ds.metadata.namespace, selector_from_labels(ds.spec.selector))
        prefix = f"{ds.metadata.name}-"
        owned = [r for r in revisions
                 if r.metadata.name.startswith(prefix)
                 and "-" not in r.metadata.name[len(prefix):]]
        newest = (max(owned, key=lambda r: r.revision)
                  .metadata.name[len(prefix):] if owned else None)
        self._newest[ds.metadata.uid] = newest
        return newest

    def _degraded_record(self) -> dict[str, tuple[str, ...]]:
        """Union of the degraded-slices annotations across the pass's
        DaemonSets (one runtime DS is the deployed shape; the union
        keeps multi-DS setups readable)."""
        merged: dict[str, set[str]] = {}
        for ds in self._daemon_sets:
            value = ds.metadata.annotations.get(
                self.keys.degraded_slices_annotation, "")
            for sid, hosts in decode_degraded_slices(value).items():
                merged.setdefault(sid, set()).update(hosts)
        return {sid: tuple(sorted(hosts))
                for sid, hosts in merged.items()}

    def _patch_degraded(self, degraded: dict[str, tuple[str, ...]]) -> None:
        """Commit the degraded record in ONE DaemonSet annotation patch
        (crash-atomic; empty record deletes the annotation)."""
        if not self._daemon_sets:
            raise RuntimeError(
                "no runtime DaemonSet to carry the degraded-slices record")
        ds = self._daemon_sets[0]
        encoded = encode_degraded_slices(degraded) or None
        fresh = self._guard(
            lambda: self.client.patch_daemon_set_annotations(
                ds.metadata.namespace, ds.metadata.name,
                {self.keys.degraded_slices_annotation: encoded}))
        ds.metadata.annotations = fresh.metadata.annotations

    # ------------------------------------------------------------------
    # the reconfigure-required arc (driven by the remediation machine)
    # ------------------------------------------------------------------
    def advance(self, ns: "NodeRemediationState",
                spec: "ReconfigurationPolicySpec") -> str:
        """One step of the condemned node's remap. Returns ``RELEASED``
        once the slice no longer depends on the node (the machine then
        commits reconfigure-required → remediation-failed) or
        ``PENDING`` while a spare is provisioning."""
        node = ns.node
        name = node.metadata.name
        pool = node.metadata.labels.get(GKE_NODEPOOL_LABEL)
        if not pool:
            # already released (crash residue between release and the
            # state commit), or a single-host "slice" with nothing to
            # remap — either way the slice no longer depends on it
            return RELEASED

        degraded = self._degraded_record()
        if name in degraded.get(pool, ()):
            # crash residue: the degraded admission committed but the
            # release did not — finish it
            self._release(node, pool)
            return RELEASED
        joined = self._find_join(pool, name)
        if joined is not None:
            # crash residue: a spare already joined for this node
            self._finish_remap(node, pool, joined)
            return RELEASED

        spare = self._find_reservation(pool, name)
        now = self.clock.now()
        if spare is None:
            spare = self._pick_spare(node)
            if spare is not None:
                self._guard(lambda: self.client.patch_node_annotations(
                    spare.metadata.name,
                    {self.keys.reserved_for_annotation:
                     f"{pool}/{name}:{int(now)}"}))
                spare.metadata.annotations[
                    self.keys.reserved_for_annotation] = \
                    f"{pool}/{name}:{int(now)}"
                self.spares_reserved_total += 1
                logger.info(
                    "reserved spare %s to replace condemned node %s in "
                    "slice %s", spare.metadata.name, name, pool)
                log_event(self.recorder, node, Event.NORMAL,
                          self.keys.event_reason,
                          f"Spare {spare.metadata.name} reserved to "
                          f"replace this node in slice {pool}")
        if spare is None:
            if spec.allow_degraded:
                self._admit_degraded(node, pool, degraded)
                return RELEASED
            # wait for a spare to join the pool; re-checked every pass
            # (and on the next resync — there is no deadline to wake on)
            logger.info(
                "no eligible spare for slice %s (condemned node %s); "
                "waiting (allowDegraded=false)", pool, name)
            return PENDING

        if self._spare_ready(spare):
            self._join_spare(spare, pool, name, now)
            self._finish_remap(node, pool, spare.metadata.name)
            return RELEASED

        reserved_at = self._reservation_epoch(spare)
        timeout = spec.spare_provision_timeout_seconds
        if timeout and reserved_at is not None \
                and now - reserved_at > timeout:
            # the spare never provisioned: abandon the booking and fall
            # back to a degraded admission (or keep waiting next pass
            # with a fresh pick when degraded shapes are disallowed)
            self._guard(lambda: self.client.patch_node_annotations(
                spare.metadata.name,
                {self.keys.reserved_for_annotation: None}))
            spare.metadata.annotations.pop(
                self.keys.reserved_for_annotation, None)
            logger.warning(
                "spare %s missed the provision deadline (%gs) for slice "
                "%s; abandoning the reservation", spare.metadata.name,
                timeout, pool)
            if spec.allow_degraded:
                self._admit_degraded(node, pool, degraded)
                return RELEASED
            return PENDING
        if timeout and reserved_at is not None and self.nudger is not None:
            # act on the provision deadline at the deadline, not at
            # whatever resync follows it
            self.nudger.nudge_at(reserved_at + timeout, "spare-provision")
        return PENDING

    def abort(self, node: Node) -> None:
        """A condemned node was re-armed mid-reconfiguration: drop any
        spare booking made for it (the node itself re-enters
        revalidation; its slice membership is untouched)."""
        pool = node.metadata.labels.get(GKE_NODEPOOL_LABEL, "")
        spare = self._find_reservation(pool, node.metadata.name)
        if spare is None:
            return
        self._guard(lambda: self.client.patch_node_annotations(
            spare.metadata.name,
            {self.keys.reserved_for_annotation: None}))
        spare.metadata.annotations.pop(
            self.keys.reserved_for_annotation, None)

    def remap_committed(self, node: Node) -> bool:
        """True once the remap passed its point of no return for this
        node: a spare has already joined in its place (or the node has
        already left its pool). The at-risk arc may only stand down
        BEFORE this point — afterwards the slice has a new member and
        aborting would strand two nodes claiming one seat."""
        pool = node.metadata.labels.get(GKE_NODEPOOL_LABEL, "")
        if not pool:
            return True
        return self._find_join(pool, node.metadata.name) is not None

    # ------------------------------------------------------------------
    # post-bucket reconcile: settle expiry + degraded healing
    # ------------------------------------------------------------------
    def reconcile_extras(self, snapshot: "RemediationSnapshot",
                         spec: "ReconfigurationPolicySpec") -> None:
        """Pass-scoped follow-through that is not tied to a condemned
        node: heal degraded slices when a spare has become available,
        then clear settled ``remapped-at`` stamps (ending the multislice
        membership hold). Heal runs FIRST — it consumes join stamps to
        retire degraded entries, so the clear must never get there
        before it."""
        self._heal_degraded(spec)
        self._clear_settled_stamps(spec)

    def _clear_settled_stamps(self, spec: "ReconfigurationPolicySpec",
                              ) -> None:
        now = self.clock.now()
        key = self.keys.remapped_at_annotation
        degraded = self._degraded_record()
        for name, ns in sorted(self._by_name.items()):
            raw = ns.node.metadata.annotations.get(key)
            if raw is None:
                continue
            epoch_raw, _, missing = raw.partition(":")
            try:
                epoch = float(epoch_raw)
            except ValueError:
                epoch = 0.0  # corrupt stamp: clear immediately
            pool = ns.node.metadata.labels.get(GKE_NODEPOOL_LABEL, "")
            released = not any(
                other.node.metadata.labels.get(GKE_NODEPOOL_LABEL) == pool
                and other_name == missing
                for other_name, other in self._by_name.items())
            if not released:
                # the condemned host is still a pool member (release in
                # flight): the hold must outlive the join→release window
                continue
            if missing in degraded.get(pool, ()):
                # a heal join whose degraded-record retirement has not
                # committed yet: the stamp is that crash window's resume
                # marker — keep it until the entry is gone
                continue
            if now < epoch + spec.settle_seconds:
                if self.nudger is not None:
                    self.nudger.nudge_at(epoch + spec.settle_seconds,
                                         "reconfig-settle")
                continue
            self._guard(lambda n=name: self.client.patch_node_annotations(
                n, {key: None}))
            ns.node.metadata.annotations.pop(key, None)

    def _heal_degraded(self, spec: "ReconfigurationPolicySpec") -> None:
        """A spare that appeared after a degraded admission restores the
        slice to full shape: reserve → (joint-plan wait) → join → drop
        the lost host from the degraded record."""
        degraded = self._degraded_record()
        for pool, losts in sorted(degraded.items()):
            exemplar = next(
                (ns.node for ns in self._by_name.values()
                 if ns.node.metadata.labels.get(GKE_NODEPOOL_LABEL)
                 == pool), None)
            for lost in losts:
                joined = self._find_join(pool, lost)
                if joined is not None:
                    remaining = dict(degraded)
                    remaining[pool] = tuple(
                        h for h in remaining[pool] if h != lost)
                    self._patch_degraded(remaining)
                    degraded = remaining
                    self.degraded_healed_total += 1
                    logger.info(
                        "degraded slice %s healed: spare %s restored the "
                        "shape lost with host %s", pool, joined, lost)
                    continue
                if exemplar is None:
                    continue  # pool fully vanished; nothing to match
                spare = self._find_reservation(pool, lost)
                now = self.clock.now()
                if spare is None:
                    spare = self._pick_spare(exemplar)
                    if spare is None:
                        continue
                    self._guard(
                        lambda s=spare: self.client.patch_node_annotations(
                            s.metadata.name,
                            {self.keys.reserved_for_annotation:
                             f"{pool}/{lost}:{int(now)}"}))
                    spare.metadata.annotations[
                        self.keys.reserved_for_annotation] = \
                        f"{pool}/{lost}:{int(now)}"
                    self.spares_reserved_total += 1
                if self._spare_ready(spare):
                    self._join_spare(spare, pool, lost, now)

    # ------------------------------------------------------------------
    # remap mechanics
    # ------------------------------------------------------------------
    def _find_reservation(self, pool: str,
                          missing: str) -> Optional[Node]:
        """The spare durably booked for (pool, missing host), if any."""
        prefix = f"{pool}/{missing}:"
        for name, ns in sorted(self._by_name.items()):
            raw = ns.node.metadata.annotations.get(
                self.keys.reserved_for_annotation, "")
            if raw.startswith(prefix):
                return ns.node
        return None

    def _reservation_epoch(self, spare: Node) -> Optional[float]:
        raw = spare.metadata.annotations.get(
            self.keys.reserved_for_annotation, "")
        _, _, epoch = raw.rpartition(":")
        try:
            return float(epoch)
        except ValueError:
            return None

    def _find_join(self, pool: str, missing: str) -> Optional[str]:
        """Name of a pool member whose ``remapped-at`` stamp records it
        replaced ``missing`` (the crash-safe join marker)."""
        for name, ns in sorted(self._by_name.items()):
            if ns.node.metadata.labels.get(GKE_NODEPOOL_LABEL) != pool:
                continue
            raw = ns.node.metadata.annotations.get(
                self.keys.remapped_at_annotation, "")
            if raw.partition(":")[2] == missing:
                return name
        return None

    def _pick_spare(self, condemned: Node) -> Optional[Node]:
        """Deterministic spare choice: the first (sorted) unreserved
        spare-pool node matching the condemned node's accelerator and
        topology labels, healthy under both machines."""
        want = {key: condemned.metadata.labels.get(key, "")
                for key in (GKE_TPU_ACCELERATOR_LABEL,
                            GKE_TPU_TOPOLOGY_LABEL)}
        for name, ns in sorted(self._by_name.items()):
            node = ns.node
            labels = node.metadata.labels
            if labels.get(self.keys.spare_pool_label) != TRUE_STRING:
                continue
            if GKE_NODEPOOL_LABEL in labels:
                continue  # already a slice member
            if any(labels.get(key, "") != value
                   for key, value in want.items()):
                continue
            annotations = node.metadata.annotations
            if self.keys.reserved_for_annotation in annotations:
                continue  # booked for another remap
            if self.remediation_keys.condemned_annotation in annotations:
                continue
            if labels.get(self.remediation_keys.state_label, ""):
                continue  # under remediation itself
            if not node.is_ready():
                continue
            return node
        return None

    def _spare_ready(self, spare: Node) -> bool:
        """The joint-planning gate: the spare joins only once it is
        upgrade-done, schedulable, and its runtime pod is Ready on the
        DaemonSet's newest revision — its one cordon/drain cycle happened
        while it was still out of the slice."""
        if spare.is_unschedulable() or not spare.is_ready():
            return False
        if spare.metadata.labels.get(
                self.upgrade_keys.state_label, "") \
                != str(UpgradeState.DONE):
            return False
        ns = self._by_name.get(spare.metadata.name)
        pod = ns.runtime_pod if ns is not None else None
        if pod is None or not pod.is_ready():
            return False
        pod_hash = pod.metadata.labels.get(
            POD_CONTROLLER_REVISION_HASH_LABEL)
        ds = (None if pod.controller_owner() is None else next(
            (d for d in self._daemon_sets
             if d.metadata.uid == pod.controller_owner().uid), None))
        if ds is None:
            return False
        return pod_hash is not None and pod_hash == self._newest_hash(ds)

    def _join_spare(self, spare: Node, pool: str, missing: str,
                    now: float) -> None:
        """ONE patch joins the spare: pool membership, spare label off,
        reservation cleared, remapped-at stamped. Committed BEFORE the
        condemned node's release so the slice is never observed short."""
        stamp = f"{int(now)}:{missing}"
        self._guard(lambda: self.client.patch_node_meta(
            spare.metadata.name,
            labels={GKE_NODEPOOL_LABEL: pool,
                    self.keys.spare_pool_label: None},
            annotations={self.keys.reserved_for_annotation: None,
                         self.keys.remapped_at_annotation: stamp}))
        spare.metadata.labels[GKE_NODEPOOL_LABEL] = pool
        spare.metadata.labels.pop(self.keys.spare_pool_label, None)
        spare.metadata.annotations.pop(
            self.keys.reserved_for_annotation, None)
        spare.metadata.annotations[self.keys.remapped_at_annotation] = stamp
        if self.nudger is not None:
            self.nudger.nudge("reconfig-join")
        logger.warning(
            "SLICE REMAP: spare %s joined slice %s replacing host %s",
            spare.metadata.name, pool, missing)
        log_event(self.recorder, spare, Event.NORMAL,
                  self.keys.event_reason,
                  f"Joined slice {pool} as replacement for condemned "
                  f"host {missing}")

    def _release(self, node: Node, pool: str) -> None:
        """Remove the condemned node from its pool (it becomes its own
        single-node 'slice', parked for repair)."""
        self._guard(lambda: self.client.patch_node_meta(
            node.metadata.name,
            labels={GKE_NODEPOOL_LABEL: None},
            annotations={self.keys.released_from_annotation: pool}))
        node.metadata.labels.pop(GKE_NODEPOOL_LABEL, None)
        node.metadata.annotations[
            self.keys.released_from_annotation] = pool

    def _finish_remap(self, node: Node, pool: str, spare_name: str) -> None:
        self._release(node, pool)
        self.reconfigurations_total += 1
        # MTTR anchor: the reactive arc measures from the condemned
        # stamp; the predictive (condemn-before-fail) arc has no
        # condemned stamp yet at release time — it measures from the
        # at-risk verdict, which is when the operator committed to the
        # remap.
        condemned_raw = node.metadata.annotations.get(
            self.remediation_keys.condemned_annotation)
        if condemned_raw is None:
            condemned_raw = node.metadata.annotations.get(
                self.remediation_keys.at_risk_annotation)
        if condemned_raw is not None:
            try:
                self._remap_seconds.append(
                    max(0.0, self.clock.now() - float(condemned_raw)))
            except ValueError:
                pass  # corrupt stamp: lose the sample, not the remap
        logger.info("slice %s released from condemned node %s (replaced "
                    "by %s)", pool, node.metadata.name, spare_name)
        log_event(self.recorder, node, Event.NORMAL,
                  self.keys.event_reason,
                  f"Released from slice {pool}: remapped onto spare "
                  f"{spare_name}")

    def _admit_degraded(self, node: Node, pool: str,
                        degraded: dict[str, tuple[str, ...]]) -> None:
        """No spare: record the lost host durably (ONE DaemonSet patch)
        then release the node — the slice runs a documented reduced
        shape instead of parking."""
        updated = dict(degraded)
        updated[pool] = tuple(sorted(
            set(updated.get(pool, ())) | {node.metadata.name}))
        self._patch_degraded(updated)
        self._release(node, pool)
        self.degraded_admissions_total += 1
        logger.warning(
            "DEGRADED ADMISSION: slice %s continues without host %s "
            "(no eligible spare)", pool, node.metadata.name)
        log_event(self.recorder, node, Event.WARNING,
                  self.keys.event_reason,
                  f"Slice {pool} admitted in degraded shape: host "
                  f"{node.metadata.name} lost, no spare available")

    # ------------------------------------------------------------------
    # status feed
    # ------------------------------------------------------------------
    def status(self) -> dict:
        """CRD-embeddable lifetime counters (point-in-time spare-pool
        gauges come from the snapshot via cluster_status /
        observe_topology)."""
        return {
            "reconfigurations": self.reconfigurations_total,
            "degradedAdmissions": self.degraded_admissions_total,
            "degradedHealed": self.degraded_healed_total,
            "sparesReserved": self.spares_reserved_total,
        }
