"""TPU slice topology derived from GKE node labels.

On GKE, a multi-host TPU slice maps 1:1 to a node pool: every node carries
``cloud.google.com/gke-nodepool`` plus the TPU shape labels
``cloud.google.com/gke-tpu-accelerator`` (e.g. ``tpu-v5p-slice``) and
``cloud.google.com/gke-tpu-topology`` (e.g. ``4x4x8``). All hosts of a
slice share one ICI domain: the slice is available only while *every* host
is schedulable and healthy.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import reduce
from typing import Iterable, Optional

from tpu_operator_libs.consts import (
    GKE_NODEPOOL_LABEL,
    GKE_TPU_ACCELERATOR_LABEL,
    GKE_TPU_TOPOLOGY_LABEL,
)
from tpu_operator_libs.k8s.objects import Node


def slice_id_for_node(node: Node) -> str:
    """The slice a node belongs to.

    Nodes with TPU shape labels group by node pool (one multi-host slice
    per pool on GKE); anything else is its own single-node "slice", which
    makes non-TPU and single-host nodes degrade to exactly the reference's
    per-node semantics.
    """
    labels = node.metadata.labels
    if GKE_TPU_TOPOLOGY_LABEL in labels and GKE_NODEPOOL_LABEL in labels:
        return labels[GKE_NODEPOOL_LABEL]
    return f"node:{node.metadata.name}"


def parse_chip_topology(topology: str) -> Optional[tuple[int, ...]]:
    """Parse a GKE TPU topology string like ``4x4x8`` into dims."""
    try:
        dims = tuple(int(part) for part in topology.lower().split("x"))
    except ValueError:
        return None
    return dims if dims else None


def decode_degraded_slices(value: str) -> dict[str, tuple[str, ...]]:
    """Parse a degraded-slices annotation value into
    ``{slice_id: (lost host names...)}``.

    Wire format (one DaemonSet annotation, crash-atomic to patch):
    ``slice:host[+host...]`` entries joined by commas, everything
    sorted. Malformed fragments are dropped rather than raising — the
    annotation is operator-visible and hand-editable."""
    out: dict[str, tuple[str, ...]] = {}
    for entry in (value or "").split(","):
        slice_id, sep, hosts = entry.strip().partition(":")
        if not sep or not slice_id:
            continue
        names = tuple(sorted({h for h in hosts.split("+") if h}))
        if names:
            out[slice_id] = names
    return out


def encode_degraded_slices(degraded: dict[str, tuple[str, ...]]) -> str:
    """Inverse of :func:`decode_degraded_slices`; "" when empty (an
    empty value deletes the annotation on a merge patch)."""
    return ",".join(
        f"{slice_id}:{'+'.join(sorted(set(hosts)))}"
        for slice_id, hosts in sorted(degraded.items()) if hosts)


@dataclass
class SliceInfo:
    """One ICI domain: the atomic unit of upgrade."""

    slice_id: str
    nodes: list[Node] = field(default_factory=list)
    accelerator: str = ""
    topology: str = ""
    #: Host names the slice durably lost to degraded admissions (the
    #: SliceReconfigurer found no spare for a condemned member). The
    #: slice runs a documented reduced shape: ``nodes`` holds only the
    #: remaining hosts, so availability math over them stays truthful,
    #: and consumers that need the full-shape picture read this field.
    lost_hosts: tuple[str, ...] = ()

    @property
    def is_multi_host(self) -> bool:
        return len(self.nodes) > 1

    @property
    def declared_degraded(self) -> bool:
        return bool(self.lost_hosts)

    @property
    def chip_count(self) -> Optional[int]:
        dims = parse_chip_topology(self.topology) if self.topology else None
        if dims is None:
            return None
        return reduce(lambda a, b: a * b, dims, 1)

    def unavailable_host_count(self) -> int:
        return sum(1 for n in self.nodes
                   if n.is_unschedulable() or not n.is_ready())

    @property
    def is_available(self) -> bool:
        """A slice serves traffic only when every host is up — one cordoned
        host idles the whole ICI domain."""
        return self.unavailable_host_count() == 0


class SliceTopology:
    """Groups nodes into slices."""

    def __init__(self, slices: dict[str, SliceInfo]) -> None:
        self._slices = slices

    @classmethod
    def from_nodes(cls, nodes: Iterable[Node],
                   degraded: Optional[dict[str, tuple[str, ...]]] = None,
                   ) -> "SliceTopology":
        """``degraded`` (slice id -> lost host names, the decoded
        degraded-slices DaemonSet annotation) marks slices running a
        documented reduced shape."""
        degraded = degraded or {}
        slices: dict[str, SliceInfo] = {}
        for node in nodes:
            sid = slice_id_for_node(node)
            info = slices.get(sid)
            if info is None:
                labels = node.metadata.labels
                info = SliceInfo(
                    slice_id=sid,
                    accelerator=labels.get(GKE_TPU_ACCELERATOR_LABEL, ""),
                    topology=labels.get(GKE_TPU_TOPOLOGY_LABEL, ""),
                    lost_hosts=degraded.get(sid, ()))
                slices[sid] = info
            info.nodes.append(node)
        return cls(slices)

    @property
    def slices(self) -> dict[str, SliceInfo]:
        return self._slices

    def slice_of(self, node: Node) -> SliceInfo:
        return self._slices[slice_id_for_node(node)]

    def availability(self) -> float:
        """Fraction of slices currently fully available — the north-star
        "slice availability %" numerator (BASELINE.md)."""
        if not self._slices:
            return 1.0
        available = sum(1 for s in self._slices.values() if s.is_available)
        return available / len(self._slices)
