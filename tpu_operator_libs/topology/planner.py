"""SlicePlanner: slice-atomic node selection for upgrade-required nodes.

Replaces the reference's flat per-node slot loop (upgrade_state.go:587-631)
when ``topologyMode: slice`` is set. Rationale: on a multi-host TPU slice,
cordoning host 1 already idles hosts 2..N's chips — upgrading hosts one at
a time multiplies slice downtime by N for zero availability benefit. The
planner therefore:

1. Groups upgrade-required candidates into slices (ICI domains).
2. Charges the availability budget only for *newly* unavailable hosts —
   hosts of an already-broken slice upgrade "for free", generalizing the
   reference's manual-cordon override (upgrade_state.go:606-616).
3. Advances whole slices atomically, preferring (a) slices already
   partially unavailable (finish what is already down), then (b) cheaper
   slices (maximize number of fully-available slices at all times).
4. Never deadlocks: when the budget is positive but smaller than the
   cheapest slice, that one slice may overdraw the budget — a partial
   upgrade would hurt availability strictly more than a brief overdraw,
   since the slice becomes unusable at the first cordoned host either way.
5. Optionally consults a :class:`MultisliceConstraint`: a slice whose
   DCN-spanning job already has ``maxUnavailableSlicesPerJob`` member
   slices down is deferred this round (it stays a candidate and is
   retried once a down member recovers).
"""

from __future__ import annotations

import logging
from typing import TYPE_CHECKING, Optional

from tpu_operator_libs.consts import IN_PROGRESS_STATES, TopologyKeys
from tpu_operator_libs.topology.multislice import MultisliceConstraint
from tpu_operator_libs.topology.slice_topology import slice_id_for_node

if TYPE_CHECKING:  # pragma: no cover
    from tpu_operator_libs.upgrade.state_manager import (
        ClusterUpgradeState,
        NodeUpgradeState,
        UpgradePlanner,
    )

logger = logging.getLogger(__name__)


class CanaryWavePlanner:
    """Restricts any inner planner to the canary cohort.

    While a canary wave is active (cohort not yet done + baked on the
    new revision, see ``upgrade.rollout_guard``), only cohort members
    may be admitted into the upgrade flow; everything else stays parked
    in ``upgrade-required``. Composes with both the flat and the
    slice-atomic planner — a slice-mode canary probes whole cohort
    slices, budget rules unchanged, because the inner planner still
    makes the admission decision over the filtered candidate list.

    ``passthrough`` names nodes admitted ALONGSIDE the cohort: spares
    reserved for a slice remap (topology/reconfigurer.py) must reach the
    target revision while still out of their slice — parking them behind
    a canary wave would stall the remap (and the condemned slice) for
    the whole bake, for no safety benefit since a spare serves nothing
    yet.
    """

    def __init__(self, inner: "UpgradePlanner",
                 cohort: "frozenset[str]",
                 passthrough: "frozenset[str]" = frozenset()) -> None:
        self.inner = inner
        self.cohort = cohort
        self.passthrough = passthrough

    def plan(self, candidates: list["NodeUpgradeState"], available: int,
             state: "ClusterUpgradeState") -> list["NodeUpgradeState"]:
        gated = [ns for ns in candidates
                 if ns.node.metadata.name in self.cohort
                 or ns.node.metadata.name in self.passthrough]
        held = len(candidates) - len(gated)
        if held:
            logger.info(
                "canary wave: holding %d node(s) outside the %d-node "
                "cohort", held, len(self.cohort))
        if not gated:
            return []
        return self.inner.plan(gated, available, state)


class SlicePlanner:
    """Slice-atomic implementation of the UpgradePlanner protocol.

    ``constraint`` (optional) adds multislice-job awareness: construct
    the :class:`MultisliceConstraint` once and keep the planner (or at
    least the constraint) alive across reconciles so its sticky-down
    membership memory works (see topology/multislice.py).

    ``topology_keys`` (optional) adds slice-reconfiguration awareness:

    - Spares reserved for a remap (``reserved-for`` annotation) are
      planned FIRST — the condemned slice they will heal waits on their
      upgrade, so every pass they sit in the queue extends that slice's
      outage for zero benefit.
    - Slices holding a fresh ``remapped-at`` settle stamp keep their
      multislice sticky-down membership until the stamp clears, so the
      planner cannot take a second member slice in the window where the
      remapped slice is up but its job's replacement pods are still
      Pending.
    """

    def __init__(self,
                 constraint: Optional[MultisliceConstraint] = None,
                 topology_keys: Optional[TopologyKeys] = None) -> None:
        self.constraint = constraint
        self.topology_keys = topology_keys

    def plan(self, candidates: list["NodeUpgradeState"], available: int,
             state: "ClusterUpgradeState") -> list["NodeUpgradeState"]:
        if self.constraint is not None:
            # reset before any early return: a round with nothing to
            # plan has, by definition, no multislice deferrals
            self.constraint.last_deferred = ()
        if not candidates:
            return []

        # The topology covers every known node, not just candidates, so
        # hosts of the same slice that are mid-upgrade count toward
        # "slice already down"; it comes from the snapshot's per-pass
        # cache, shared with cluster_status/metrics.
        all_nodes = state.all_nodes()
        topology = state.topology()
        down_slices = {sid for sid, info in topology.slices.items()
                       if not info.is_available}
        # For the multislice constraint, "down" must also cover slices
        # *committed* to going down — a host selected last pass sits in
        # cordon-required but is not yet unschedulable; admitting a
        # sibling member in that window would break the per-job
        # guarantee the moment both cordons land.
        committed_down = down_slices | {
            slice_id_for_node(ns.node)
            for st in IN_PROGRESS_STATES
            for ns in state.bucket(st)}
        # Freshly remapped slices (settle stamp not yet cleared) hold
        # their job membership AND count against their job's down
        # budget even though their hosts are back up: the job's
        # replacement pods are still Pending there, so for the job the
        # slice is down in every way that matters — taking a second
        # member in that window is exactly the double-outage the budget
        # exists to prevent. (The map releases a held slice early once
        # live pods re-bind it, which also removes it from the job's
        # counted set here.)
        hold_slices: set[str] = set()
        if self.topology_keys is not None:
            stamp_key = self.topology_keys.remapped_at_annotation
            hold_slices = {slice_id_for_node(node) for node in all_nodes
                           if stamp_key in node.metadata.annotations}
        counted_down = committed_down | hold_slices
        if self.constraint is not None:
            self.constraint.begin_round(all_nodes, committed_down,
                                        hold_slices)

        by_slice: dict[str, list["NodeUpgradeState"]] = {}
        for ns in candidates:
            by_slice.setdefault(slice_id_for_node(ns.node), []).append(ns)

        def reserved_spare(slice_id: str) -> bool:
            """Candidate slice is a reserved remap spare (spares carry
            no pool label, so each is its own single-node slice)."""
            if self.topology_keys is None:
                return False
            key = self.topology_keys.reserved_for_annotation
            return any(key in ns.node.metadata.annotations
                       for ns in by_slice[slice_id])

        def cost(slice_id: str) -> int:
            """Hosts that would *newly* become unavailable."""
            return sum(1 for ns in by_slice[slice_id]
                       if not ns.node.is_unschedulable())

        def already_broken(slice_id: str) -> bool:
            info = topology.slices.get(slice_id)
            return info is not None and not info.is_available

        order = sorted(
            by_slice,
            key=lambda sid: (
                not reserved_spare(sid),  # remap spares first
                not already_broken(sid),  # then broken slices
                cost(sid),                # then cheapest
                sid,                      # deterministic tie-break
            ))

        selected: list["NodeUpgradeState"] = []
        selected_down: set[str] = set()  # slices newly taken down this round
        deferred: list[str] = []
        budget = available
        paid = False
        for sid in order:
            c = cost(sid)
            if c == 0:
                # every candidate host already unavailable — free progress
                # (the slice is in down_slices, so the multislice
                # constraint already charges its job for it)
                selected.extend(by_slice[sid])
                continue
            if budget <= 0:
                continue
            if c > budget and paid:
                # Overdraw is only allowed for the first PAYING slice;
                # free slices selected above don't consume that right.
                continue
            if (self.constraint is not None
                    and not self.constraint.admits(
                        sid, counted_down, selected_down)):
                # This slice's multislice job already has its budget of
                # member slices down; defer — it stays upgrade-required
                # and is reconsidered next round.
                deferred.append(sid)
                continue
            selected.extend(by_slice[sid])
            selected_down.add(sid)
            budget = max(0, budget - c)
            paid = True
        if self.constraint is not None:
            # persisted on the constraint (it outlives this per-pass
            # planner) so status/metrics can report the deferrals
            self.constraint.last_deferred = tuple(sorted(deferred))
        if deferred:
            logger.info(
                "multislice constraint deferred slice(s) %s "
                "(max %d member(s) down per job)",
                ", ".join(sorted(deferred)),
                self.constraint.max_down if self.constraint else 0)
        if selected:
            logger.info(
                "slice planner advancing %d nodes across %d slice(s)",
                len(selected),
                len({slice_id_for_node(ns.node) for ns in selected}))
        return selected
