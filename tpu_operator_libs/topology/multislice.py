"""Multislice (DCN-spanning) job awareness for upgrade planning.

A multislice JAX job spans several ICI slices connected over DCN (one
JobSet replica per slice on GKE). Losing one member slice already forces
the job to pause or restart from checkpoint; losing a *second* member
concurrently buys no additional upgrade progress for the job while
doubling its blast radius and delaying its recovery. The planner
therefore enforces: **per multislice job, at most
``max_unavailable_slices_per_job`` member slices unavailable at a time**
(default 1) — generalizing the reference's budget logic
(upgrade_state.go:606-616) from host-counts to DCN job membership.

Membership is derived from workload pod labels: every pod carrying one
of the configured job-label keys (default: JobSet's
``jobset.sigs.k8s.io/jobset-name``) ties the slice its node belongs to
into the job identified by ``(namespace, label value)``.

Pod-derived membership has a known transient gap: a drained member's
pods are evicted, and their replacements stay Pending (no nodeName)
until the slice is schedulable again — so the live map alone would
"forget" the down member and let the planner take a second one.
:class:`MultisliceJobMap` therefore carries membership of currently
*unavailable* slices forward from round to round (sticky-down memory),
forgetting a slice only once it is available again. This requires the
map (and the planner holding it) to live across reconciles.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Callable, Iterable, Optional

from tpu_operator_libs.k8s.objects import Node, Pod

if TYPE_CHECKING:  # pragma: no cover - types only
    from tpu_operator_libs.k8s.client import K8sClient
from tpu_operator_libs.topology.slice_topology import slice_id_for_node

#: Default pod label keys identifying the multislice job a pod belongs
#: to, tried in order. JobSet is the GKE-blessed multislice launcher.
DEFAULT_JOB_LABEL_KEYS: tuple[str, ...] = (
    "jobset.sigs.k8s.io/jobset-name",
)

JobId = tuple[str, str]  # (namespace, job name)


def default_workload_pods(client: "K8sClient",
                          keys: Iterable[str] = DEFAULT_JOB_LABEL_KEYS
                          ) -> Callable[[], list[Pod]]:
    """A workload-pod source that lists only pods carrying one of the
    job-label keys (bare-key existence selector), instead of every pod
    in the cluster — on a real apiserver a full-namespace-less LIST per
    reconcile pass would be the dominant cost of slice planning.

    Pods matching several keys are deduplicated by (namespace, name).
    """
    key_list = tuple(keys)

    def source() -> list[Pod]:
        seen: dict[tuple[str, str], Pod] = {}
        for key in key_list:
            for pod in client.list_pods(label_selector=key):
                seen.setdefault(
                    (pod.metadata.namespace, pod.metadata.name), pod)
        return list(seen.values())

    return source


def job_id_for_pod(pod: Pod,
                   keys: Iterable[str] = DEFAULT_JOB_LABEL_KEYS
                   ) -> Optional[JobId]:
    for key in keys:
        value = pod.metadata.labels.get(key)
        if value:
            return (pod.metadata.namespace, value)
    return None


class MultisliceJobMap:
    """job → member slices, built from live pods each round with
    sticky-down memory (see module docstring)."""

    def __init__(self, job_label_keys: Iterable[str] = DEFAULT_JOB_LABEL_KEYS
                 ) -> None:
        self._keys = tuple(job_label_keys)
        self._last: dict[JobId, set[str]] = {}

    def refresh(self, pods: Iterable[Pod], nodes: Iterable[Node],
                down_slices: set[str],
                hold_slices: "set[str] | frozenset[str]" = frozenset(),
                ) -> dict[JobId, set[str]]:
        """Rebuild the map from live pods, carrying forward membership of
        slices in ``down_slices`` from the previous round.

        ``hold_slices`` extends the carry to slices that are back UP but
        whose membership must not be forgotten yet — the remap case: a
        slice reconfigured onto a spare is immediately available, while
        its job's replacement pods are still Pending, and a map that
        forgot the member there would let the planner take a second
        member of the same job. A held slice is released early once live
        pods re-bind it (the hold can never pin stale membership a
        running pod contradicts); otherwise the hold lasts until the
        reconfigurer clears the remap settle stamp."""
        node_slice = {node.metadata.name: slice_id_for_node(node)
                      for node in nodes}
        live: dict[JobId, set[str]] = {}
        for pod in pods:
            job = job_id_for_pod(pod, self._keys)
            if job is None:
                continue
            sid = node_slice.get(pod.spec.node_name)
            if sid is None:
                continue  # Pending/unscheduled or foreign node
            live.setdefault(job, set()).add(sid)
        for job, members in self._last.items():
            for sid in members:
                if sid in down_slices:
                    # its pods may be evicted right now; the slice is
                    # still this job's member until it comes back up
                    live.setdefault(job, set()).add(sid)
                elif sid in hold_slices and sid not in live.get(job, ()):
                    # freshly remapped: up, but the job has not re-bound
                    # it yet — keep the membership through the settle
                    live.setdefault(job, set()).add(sid)
        self._last = live
        return live


class MultisliceConstraint:
    """The planner-side admission check.

    ``workload_pods`` supplies the current workload pods (typically
    ``lambda: client.list_pods()`` across namespaces); construct once
    and reuse across reconciles so the sticky-down memory works.
    """

    def __init__(self, workload_pods: Callable[[], list[Pod]],
                 job_label_keys: Iterable[str] = DEFAULT_JOB_LABEL_KEYS,
                 max_unavailable_slices_per_job: int = 1) -> None:
        if max_unavailable_slices_per_job < 1:
            raise ValueError(
                "max_unavailable_slices_per_job must be >= 1")
        self._workload_pods = workload_pods
        self._map = MultisliceJobMap(job_label_keys)
        self.max_down = max_unavailable_slices_per_job
        self._job_slices: dict[JobId, set[str]] = {}
        #: Slices the planner deferred on the most recent round because
        #: their job's member-slice budget was exhausted (written by
        #: SlicePlanner.plan; surfaced via cluster_status and the
        #: multislice_deferred_slices metric so operators can see WHY an
        #: upgrade is pacing instead of progressing).
        self.last_deferred: tuple[str, ...] = ()

    def begin_round(self, nodes: Iterable[Node],
                    down_slices: set[str],
                    hold_slices: "set[str] | frozenset[str]" = frozenset(),
                    ) -> None:
        self._job_slices = self._map.refresh(
            self._workload_pods(), nodes, down_slices, hold_slices)

    def admits(self, slice_id: str, down_slices: set[str],
               selected_slices: set[str]) -> bool:
        """May ``slice_id`` be taken (fully) down, given already-down
        slices and slices selected earlier this round?

        A slice already counted down (partially cordoned, or selected
        earlier) adds nothing new to its job's blast radius — finishing
        an already-broken member is always admitted, mirroring the
        planner's broken-slices-first preference.
        """
        counted = down_slices | selected_slices
        extra = 0 if slice_id in counted else 1
        if extra == 0:
            return True
        for members in self._job_slices.values():
            if slice_id not in members:
                continue
            if len(counted & members) + extra > self.max_down:
                return False
        return True
