"""Upgrade policy types — the declarative configuration surface.

TPU-native equivalent of ``api/upgrade/v1alpha1/upgrade_spec.go`` in the
reference: a policy object consumers embed in their own CRD and pass to
``apply_state`` on every reconcile (upgrade_state.go:364-365).  Field names,
defaults and validation mirror the reference's kubebuilder markers
(upgrade_spec.go:27-110); serialization uses the same camelCase JSON keys so
existing GPU-operator-style policy YAML round-trips unchanged.

Implemented as plain dataclasses with explicit ``to_dict``/``from_dict`` and
``deep_copy`` (the reference generates DeepCopy via controller-gen,
zz_generated.deepcopy.go:29-69 — here it is one honest method instead of
generated code).
"""

from __future__ import annotations

import copy
import math
import re
from dataclasses import dataclass, field
from typing import Any, Optional, Union

IntOrString = Union[int, str]


class PolicyValidationError(ValueError):
    """Raised when a policy spec fails validation."""


def scaled_value_from_int_or_percent(value: Optional[IntOrString],
                                     total: int,
                                     round_up: bool = True) -> int:
    """Resolve an int-or-percent value against a total.

    Equivalent of apimachinery's ``intstr.GetScaledValueFromIntOrPercent`` as
    used for maxUnavailable scaling (upgrade_state.go:395-401).  Percentages
    round up by default, matching the reference call site.
    """
    if value is None:
        return total
    if isinstance(value, bool):  # bool is an int subclass; reject explicitly
        raise PolicyValidationError(f"invalid int-or-percent value: {value!r}")
    if isinstance(value, int):
        return value
    text = value.strip()
    if not text.endswith("%"):
        try:
            return int(text)
        except ValueError:
            raise PolicyValidationError(
                f"invalid int-or-percent value: {value!r}") from None
    try:
        percent = float(text[:-1])
    except ValueError:
        raise PolicyValidationError(
            f"invalid percentage value: {value!r}") from None
    scaled = percent * total / 100.0
    return math.ceil(scaled) if round_up else math.floor(scaled)


@dataclass
class WaitForCompletionSpec:
    """Wait for selected workload pods to finish before disruption.

    Mirrors WaitForCompletionSpec (upgrade_spec.go:52-64).
    """

    # Label selector for the pods to wait on; empty = don't wait.
    pod_selector: str = ""
    # Seconds to wait before giving up; 0 = wait forever.
    timeout_seconds: int = 0

    def validate(self) -> None:
        if self.timeout_seconds < 0:
            raise PolicyValidationError(
                "waitForCompletion.timeoutSeconds must be >= 0")

    def to_dict(self) -> dict[str, Any]:
        return {"podSelector": self.pod_selector,
                "timeoutSeconds": self.timeout_seconds}

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "WaitForCompletionSpec":
        return cls(pod_selector=data.get("podSelector", ""),
                   timeout_seconds=data.get("timeoutSeconds", 0))

    def deep_copy(self) -> "WaitForCompletionSpec":
        return copy.deepcopy(self)


@dataclass
class PodDeletionSpec:
    """Configuration for the optional pod-deletion state.

    Mirrors PodDeletionSpec (upgrade_spec.go:67-83).
    """

    # Allow deleting pods that have no controller (would not be recreated).
    force: bool = False
    # Seconds to wait for pod termination; 0 = infinite.
    timeout_seconds: int = 300
    # Proceed even if pods use emptyDir volumes (data is lost on delete).
    delete_empty_dir: bool = False

    def validate(self) -> None:
        if self.timeout_seconds < 0:
            raise PolicyValidationError(
                "podDeletion.timeoutSeconds must be >= 0")

    def to_dict(self) -> dict[str, Any]:
        return {"force": self.force,
                "timeoutSeconds": self.timeout_seconds,
                "deleteEmptyDir": self.delete_empty_dir}

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "PodDeletionSpec":
        return cls(force=data.get("force", False),
                   timeout_seconds=data.get("timeoutSeconds", 300),
                   delete_empty_dir=data.get("deleteEmptyDir", False))

    def deep_copy(self) -> "PodDeletionSpec":
        return copy.deepcopy(self)


@dataclass
class DrainSpec:
    """Configuration for node drain during upgrade.

    Mirrors DrainSpec (upgrade_spec.go:86-110).
    """

    # Master switch; when False the drain state is skipped entirely
    # (upgrade_state.go:734-747).
    enable: bool = False
    # Evict pods without a controller.
    force: bool = False
    # Label selector restricting which pods are drained; empty = all.
    pod_selector: str = ""
    # Seconds before giving up the drain; 0 = infinite.
    timeout_seconds: int = 300
    # Evict pods using emptyDir volumes (their data is deleted).
    delete_empty_dir: bool = False

    def validate(self) -> None:
        if self.timeout_seconds < 0:
            raise PolicyValidationError("drain.timeoutSeconds must be >= 0")

    def to_dict(self) -> dict[str, Any]:
        return {"enable": self.enable,
                "force": self.force,
                "podSelector": self.pod_selector,
                "timeoutSeconds": self.timeout_seconds,
                "deleteEmptyDir": self.delete_empty_dir}

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "DrainSpec":
        return cls(enable=data.get("enable", False),
                   force=data.get("force", False),
                   pod_selector=data.get("podSelector", ""),
                   timeout_seconds=data.get("timeoutSeconds", 300),
                   delete_empty_dir=data.get("deleteEmptyDir", False))

    def deep_copy(self) -> "DrainSpec":
        return copy.deepcopy(self)


@dataclass
class CanaryRolloutSpec:
    """Canary-gated rollout: probe a new revision on a small cohort
    before opening the fleet waves (beyond-reference; the reference
    upgrades every node with no notion of "the revision itself is bad").

    The canary cohort is chosen deterministically from the managed node
    names, so a restarted operator derives the same cohort from cluster
    state alone. While the cohort is upgrading (and for ``bakeSeconds``
    after it completes) no other node is admitted; once
    ``failureThreshold`` nodes fail on the new revision the fleet HALTS
    (see :class:`RollbackSpec` for what happens next).
    """

    # Master switch; when False rollout proceeds reference-style.
    enable: bool = False
    # Cohort size: node count (int) or fleet percentage ("10%"), min 1.
    canary_count: IntOrString = 1
    # Seconds the completed cohort must bake before fleet waves open.
    bake_seconds: int = 300
    # Failure verdicts (validation timeout, pod crash-loop) on one
    # revision that flip the fleet to HALTED.
    failure_threshold: int = 1

    def validate(self) -> None:
        if scaled_value_from_int_or_percent(self.canary_count, 100) < 1:
            raise PolicyValidationError("canary.canaryCount must be >= 1")
        if self.bake_seconds < 0:
            raise PolicyValidationError("canary.bakeSeconds must be >= 0")
        if self.failure_threshold < 1:
            raise PolicyValidationError(
                "canary.failureThreshold must be >= 1")

    def to_dict(self) -> dict[str, Any]:
        return {"enable": self.enable,
                "canaryCount": self.canary_count,
                "bakeSeconds": self.bake_seconds,
                "failureThreshold": self.failure_threshold}

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "CanaryRolloutSpec":
        return cls(enable=data.get("enable", False),
                   canary_count=data.get("canaryCount", 1),
                   bake_seconds=data.get("bakeSeconds", 300),
                   failure_threshold=data.get("failureThreshold", 1))

    def deep_copy(self) -> "CanaryRolloutSpec":
        return copy.deepcopy(self)


@dataclass
class RollbackSpec:
    """What a canary HALT does beyond freezing admissions.

    With ``enable`` the operator re-pins the DaemonSet's previous
    ControllerRevision and drives every node stuck on the condemned
    revision through ``rollback-required`` (pod delete → restart on the
    old revision → revalidate → uncordon). Disabled, the fleet stays
    halted for a human: the quarantine annotation keeps reconcile from
    re-attempting the bad hash either way.
    """

    # Automatically roll the fleet back to the previous revision.
    enable: bool = True

    def validate(self) -> None:
        pass  # nothing to range-check yet; symmetry with sibling specs

    def to_dict(self) -> dict[str, Any]:
        return {"enable": self.enable}

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "RollbackSpec":
        return cls(enable=data.get("enable", True))

    def deep_copy(self) -> "RollbackSpec":
        return copy.deepcopy(self)


@dataclass
class PredictorSpec:
    """Cost-aware predictive wave planning (beyond-reference;
    upgrade/predictor.py).

    With ``enable`` the operator learns online per-node/per-phase
    upgrade durations (drain, pod-restart, validation — stamped with
    durable phase-start annotations so learning survives crashes and
    shard takeovers) and composes waves longest-predicted-first, so
    stragglers start first instead of pacing the last wave. Zero
    history degrades to the flat admission order exactly.
    """

    # Master switch; when False admission order is reference-style.
    enable: bool = False
    # EWMA weight of the newest per-node sample, in (0, 1].
    smoothing: float = 0.5
    # Per-phase prior (seconds) while NOTHING has been learned; also
    # the cold-fleet cost the maintenance-window gate assumes.
    prior_seconds: float = 120.0

    def validate(self) -> None:
        if not 0.0 < self.smoothing <= 1.0:
            raise PolicyValidationError(
                "predictor.smoothing must be in (0, 1]")
        if self.prior_seconds < 0:
            raise PolicyValidationError(
                "predictor.priorSeconds must be >= 0")

    def to_dict(self) -> dict[str, Any]:
        return {"enable": self.enable,
                "smoothing": self.smoothing,
                "priorSeconds": self.prior_seconds}

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "PredictorSpec":
        return cls(enable=data.get("enable", False),
                   smoothing=data.get("smoothing", 0.5),
                   prior_seconds=data.get("priorSeconds", 120.0))

    def deep_copy(self) -> "PredictorSpec":
        return copy.deepcopy(self)


#: Preflight gate modes: ``off`` (no forecast), ``advisory`` (forecast
#: surfaced in status/explain but never blocks), ``required`` (a
#: threshold breach parks the rollout before node one is admitted).
PREFLIGHT_MODES: tuple[str, ...] = ("off", "advisory", "required")


@dataclass
class PreflightSpec:
    """What-if forecast gating admission (beyond-reference;
    upgrade/preflight.py).

    Before the first node of a rollout is admitted, the operator
    replays the proposed revision in-process against a FROZEN clone of
    the cluster picture — the learned phase-duration model, the
    capacity/traffic picture, and the policy engine — and produces a
    structured forecast (makespan with confidence bounds, per-class SLO
    risk, expected aborts/holds/window deferrals, per-wave breakdown).
    In ``required`` mode a forecast breaching either threshold parks
    the rollout with an audited ``preflight-rejected`` reason; in
    ``advisory`` mode the forecast is surfaced but never blocks.
    """

    # Gate mode: "off", "advisory", or "required".
    mode: str = "off"
    # Highest tolerable forecast SLO-risk fraction (worst class's
    # predicted peak shortfall over the rollout), in [0, 1].
    max_forecast_slo_risk_fraction: float = 0.2
    # Highest tolerable forecast makespan (seconds); 0 = unbounded.
    max_forecast_makespan_seconds: float = 0.0
    # Confidence level for the forecast's error-widened bounds; the
    # REQUIRED-mode threshold compares against the UPPER bound, so a
    # noisy model gates earlier, never later.
    confidence: float = 0.9

    @property
    def enabled(self) -> bool:
        return self.mode != "off"

    def validate(self) -> None:
        if self.mode not in PREFLIGHT_MODES:
            raise PolicyValidationError(
                f"preflight.mode must be one of {PREFLIGHT_MODES}, "
                f"got {self.mode!r}")
        if not 0.0 <= self.max_forecast_slo_risk_fraction <= 1.0:
            raise PolicyValidationError(
                "preflight.maxForecastSloRiskFraction must be in [0, 1]")
        if self.max_forecast_makespan_seconds < 0:
            raise PolicyValidationError(
                "preflight.maxForecastMakespanSeconds must be >= 0")
        if not 0.0 < self.confidence < 1.0:
            raise PolicyValidationError(
                "preflight.confidence must be in (0, 1)")

    def to_dict(self) -> dict[str, Any]:
        return {"mode": self.mode,
                "maxForecastSloRiskFraction":
                    self.max_forecast_slo_risk_fraction,
                "maxForecastMakespanSeconds":
                    self.max_forecast_makespan_seconds,
                "confidence": self.confidence}

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "PreflightSpec":
        return cls(mode=data.get("mode", "off"),
                   max_forecast_slo_risk_fraction=data.get(
                       "maxForecastSloRiskFraction", 0.2),
                   max_forecast_makespan_seconds=data.get(
                       "maxForecastMakespanSeconds", 0.0),
                   confidence=data.get("confidence", 0.9))

    def deep_copy(self) -> "PreflightSpec":
        return copy.deepcopy(self)


@dataclass
class MaintenanceWindowSpec:
    """"Finish by the window close or don't start" (beyond-reference).

    A node is only admitted into the upgrade flow when its
    *conservatively* predicted completion (predictor EWMA x safety
    factor, pooled p95 for unknown nodes) lands before the window
    close plus ``marginSeconds`` of slack; otherwise it is deferred —
    left untouched in upgrade-required, never started-and-stranded
    mid-flow at the close. Requires the predictor (the gate needs
    duration estimates); without one the window is ignored with a
    warning. The close is either an absolute instant
    (``closeEpochSeconds`` — also the form benches/chaos use on
    virtual clocks) or a recurring daily wall-clock close
    (``dailyCloseUtc: "06:00"``), whichever is set.
    """

    # Master switch; when False (or no close configured) nothing is
    # gated.
    enable: bool = False
    # Absolute close instant (epoch seconds, same clock domain the
    # operator runs on). Takes precedence over dailyCloseUtc.
    close_epoch_seconds: Optional[float] = None
    # Recurring daily close, "HH:MM" UTC ("finish by 06:00").
    daily_close_utc: str = ""
    # Safety slack subtracted from the window: predicted completion
    # must land this many seconds BEFORE the close.
    margin_seconds: int = 0

    def close_at(self, now: float) -> Optional[float]:
        """The next window close at/after ``now`` (None = no close
        configured). An absolute close in the past is returned as-is:
        the window is shut, nothing may start."""
        if not self.enable:
            return None
        if self.close_epoch_seconds is not None:
            return float(self.close_epoch_seconds)
        if not self.daily_close_utc:
            return None
        import datetime

        hour, _, minute = self.daily_close_utc.partition(":")
        base = datetime.datetime.fromtimestamp(
            now, tz=datetime.timezone.utc)
        close = base.replace(hour=int(hour), minute=int(minute or 0),
                             second=0, microsecond=0)
        if close.timestamp() <= now:
            close += datetime.timedelta(days=1)
        return close.timestamp()

    def validate(self) -> None:
        if self.margin_seconds < 0:
            raise PolicyValidationError(
                "maintenanceWindow.marginSeconds must be >= 0")
        if self.daily_close_utc:
            hour, sep, minute = self.daily_close_utc.partition(":")
            try:
                ok = (sep and 0 <= int(hour) <= 23
                      and 0 <= int(minute) <= 59)
            except ValueError:
                ok = False
            if not ok:
                raise PolicyValidationError(
                    "maintenanceWindow.dailyCloseUtc must be \"HH:MM\"")

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {"enable": self.enable,
                               "marginSeconds": self.margin_seconds}
        if self.close_epoch_seconds is not None:
            out["closeEpochSeconds"] = self.close_epoch_seconds
        if self.daily_close_utc:
            out["dailyCloseUtc"] = self.daily_close_utc
        return out

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "MaintenanceWindowSpec":
        return cls(enable=data.get("enable", False),
                   close_epoch_seconds=data.get("closeEpochSeconds"),
                   daily_close_utc=data.get("dailyCloseUtc", ""),
                   margin_seconds=data.get("marginSeconds", 0))

    def deep_copy(self) -> "MaintenanceWindowSpec":
        return copy.deepcopy(self)


#: DNS-label shape every traffic-class name must take (lowercase
#: alphanumerics and dashes, no leading/trailing dash) — the same
#: constraint a Kubernetes label VALUE carries, so class names can ride
#: node labels and metric labels unchanged.
_CLASS_NAME_RE = re.compile(r"^[a-z0-9]([a-z0-9-]{0,61}[a-z0-9])?$")


@dataclass
class TrafficClassSpec:
    """One serving traffic class (beyond-reference; upgrade/handover.py).

    A class groups serving endpoints by disruption sensitivity:
    ``interactive`` classes carry a strict admission SLO (a user is
    waiting on every generation), ``batch`` classes a relaxed one
    (queued work tolerates deferral). The DisruptionCostRanker drains
    nodes serving only cheap classes first and HOLDS a node whose
    drain would leave one of its models below ``minReplicas`` admitting
    replicas (for interactive classes the prewarm arc then brings a
    replacement replica up before the hold lifts).
    """

    # Class name; must match the traffic_class the ServingEndpoints
    # declare (DNS-label shaped, validated).
    name: str = "batch"
    # Strict-SLO class: admission shortfall is a violation, and
    # sole-replica models are held behind the prewarm arc.
    interactive: bool = False
    # A node may drain only while each of its models keeps at least
    # this many OTHER admitting replicas (1 = only sole replicas held).
    min_replicas: int = 1
    # Router-side drain deadline: generations still in flight on a
    # draining endpoint past this many seconds are handed over to a
    # peer replica (never dropped) so the drain can quiesce.
    drain_deadline_seconds: float = 120.0
    # Fraction of the class's offered load that may go unplaced at a
    # tick before the class SLO counts as breached (0 = strict;
    # interactive classes must be 0).
    max_shortfall_fraction: float = 0.0

    def validate(self) -> None:
        if not isinstance(self.name, str) \
                or not _CLASS_NAME_RE.match(self.name):
            raise PolicyValidationError(
                f"trafficClasses[].name {self.name!r} is malformed: "
                f"must be a lowercase DNS label "
                f"(alphanumerics and dashes)")
        if isinstance(self.min_replicas, bool) or self.min_replicas < 1:
            raise PolicyValidationError(
                f"trafficClasses[{self.name}].minReplicas must be >= 1")
        if self.drain_deadline_seconds <= 0:
            raise PolicyValidationError(
                f"trafficClasses[{self.name}].drainDeadlineSeconds "
                f"must be > 0")
        if not 0.0 <= self.max_shortfall_fraction < 1.0:
            raise PolicyValidationError(
                f"trafficClasses[{self.name}].maxShortfallFraction "
                f"must be in [0, 1)")
        if self.interactive and self.max_shortfall_fraction != 0.0:
            raise PolicyValidationError(
                f"trafficClasses[{self.name}]: an interactive class's "
                f"maxShortfallFraction must be 0 (strict SLO)")

    def to_dict(self) -> dict[str, Any]:
        return {"name": self.name,
                "interactive": self.interactive,
                "minReplicas": self.min_replicas,
                "drainDeadlineSeconds": self.drain_deadline_seconds,
                "maxShortfallFraction": self.max_shortfall_fraction}

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "TrafficClassSpec":
        return cls(name=data.get("name", "batch"),
                   interactive=data.get("interactive", False),
                   min_replicas=data.get("minReplicas", 1),
                   drain_deadline_seconds=data.get(
                       "drainDeadlineSeconds", 120.0),
                   max_shortfall_fraction=data.get(
                       "maxShortfallFraction", 0.0))

    def deep_copy(self) -> "TrafficClassSpec":
        return copy.deepcopy(self)


@dataclass
class CapacityBudgetSpec:
    """Traffic-aware dynamic disruption budgets (beyond-reference;
    upgrade/capacity.py).

    With ``enable`` the operator aggregates live ``ServingEndpoint``
    load signals (in-flight generations, a QPS EWMA, per-node serving
    capacity) into fleet headroom and recomputes the EFFECTIVE
    disruption budget every pass: drain aggressively in traffic
    troughs, pause admission at peaks, and ABORT mid-flight drains
    (``abort-required``) when a spike or node loss collapses the
    budget below what is already unavailable. Without a wired endpoint
    source (``ClusterUpgradeStateManager.with_serving_signal``) the
    controller fails open to the static budget exactly — non-serving
    fleets see reference semantics, bit for bit.
    """

    # Master switch; when False the static budget applies unchanged.
    enable: bool = False
    # Required spare-capacity fraction over current demand: the
    # controller only leaves nodes drainable while
    # capacity >= demand * (1 + sloHeadroomFraction).
    slo_headroom_fraction: float = 0.25
    # Floor for the effective budget (nodes). 0 = the controller may
    # pause draining entirely at peaks.
    min_effective_budget: int = 0
    # Ceiling for the effective budget (nodes). 0 = clamped by the
    # static policy ``maxUnavailable`` alone; a positive value lets
    # traffic troughs exceed the static count (the point of
    # traffic-awareness: a peak-safe static budget wastes troughs).
    max_effective_budget: int = 0
    # Utilization (demand / live capacity) at or above which admission
    # pauses outright regardless of computed spare nodes.
    peak_pause_utilization: float = 0.85
    # Concurrent generations one serving node sustains (the default for
    # endpoints that do not declare their own ``capacity``).
    per_node_capacity: int = 8
    # EWMA weight of the newest demand/QPS sample, in (0, 1].
    smoothing: float = 0.3
    # Trough-window cadence: while the controller holds the budget
    # below the static count it registers a re-evaluation wakeup this
    # many seconds out on the deadline timer wheel, so the next trough
    # is caught without waiting out a resync interval.
    recheck_seconds: float = 30.0
    # Traffic classes (upgrade/handover.py): with any declared, the
    # DisruptionCostRanker wraps the planner chain and spends the
    # budget on the cheapest serving disruption first. Empty = the
    # class-blind PR 10 behavior, bit for bit.
    traffic_classes: list[TrafficClassSpec] = field(default_factory=list)
    # Prewarm arc: before a hold-worthy incumbent drains, reserve an
    # already-upgraded spare, bring a replacement replica up on it and
    # require readiness (durable stamps) before the incumbent's
    # eviction is admitted.
    prewarm: bool = False

    def class_map(self) -> "dict[str, TrafficClassSpec]":
        return {spec.name: spec for spec in self.traffic_classes}

    def validate(self) -> None:
        # NOTE on the headroom bound: a fraction >= 1 would demand more
        # spare capacity than the whole fleet provides at any nonzero
        # utilization — required = demand * (1 + f) can never be met,
        # so the budget would silently pin to the floor forever.
        # Rejected at policy-load time instead of misbehaving mid-pass.
        if not 0.0 <= self.slo_headroom_fraction < 1.0:
            raise PolicyValidationError(
                "capacityBudget.sloHeadroomFraction must be in [0, 1)")
        if self.min_effective_budget < 0:
            raise PolicyValidationError(
                "capacityBudget.minEffectiveBudget must be >= 0")
        if self.max_effective_budget < 0:
            raise PolicyValidationError(
                "capacityBudget.maxEffectiveBudget must be >= 0")
        if self.max_effective_budget \
                and self.max_effective_budget < self.min_effective_budget:
            raise PolicyValidationError(
                "capacityBudget.maxEffectiveBudget must be >= "
                "minEffectiveBudget")
        if not 0.0 < self.peak_pause_utilization <= 1.0:
            raise PolicyValidationError(
                "capacityBudget.peakPauseUtilization must be in (0, 1]")
        if self.per_node_capacity < 1:
            raise PolicyValidationError(
                "capacityBudget.perNodeCapacity must be >= 1")
        if not 0.0 < self.smoothing <= 1.0:
            raise PolicyValidationError(
                "capacityBudget.smoothing must be in (0, 1]")
        if self.recheck_seconds <= 0:
            raise PolicyValidationError(
                "capacityBudget.recheckSeconds must be > 0")
        seen: set[str] = set()
        for spec in self.traffic_classes:
            spec.validate()
            if spec.name in seen:
                raise PolicyValidationError(
                    f"capacityBudget.trafficClasses: duplicate class "
                    f"name {spec.name!r}")
            seen.add(spec.name)

    def to_dict(self) -> dict[str, Any]:
        return {"enable": self.enable,
                "sloHeadroomFraction": self.slo_headroom_fraction,
                "minEffectiveBudget": self.min_effective_budget,
                "maxEffectiveBudget": self.max_effective_budget,
                "peakPauseUtilization": self.peak_pause_utilization,
                "perNodeCapacity": self.per_node_capacity,
                "smoothing": self.smoothing,
                "recheckSeconds": self.recheck_seconds,
                "trafficClasses": [spec.to_dict()
                                   for spec in self.traffic_classes],
                "prewarm": self.prewarm}

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "CapacityBudgetSpec":
        return cls(enable=data.get("enable", False),
                   slo_headroom_fraction=data.get(
                       "sloHeadroomFraction", 0.25),
                   min_effective_budget=data.get("minEffectiveBudget", 0),
                   max_effective_budget=data.get("maxEffectiveBudget", 0),
                   peak_pause_utilization=data.get(
                       "peakPauseUtilization", 0.85),
                   per_node_capacity=data.get("perNodeCapacity", 8),
                   smoothing=data.get("smoothing", 0.3),
                   recheck_seconds=data.get("recheckSeconds", 30.0),
                   traffic_classes=[
                       TrafficClassSpec.from_dict(item)
                       for item in data.get("trafficClasses", [])],
                   prewarm=data.get("prewarm", False))

    def deep_copy(self) -> "CapacityBudgetSpec":
        return copy.deepcopy(self)


@dataclass
class ShardingPolicySpec:
    """Sharded HA control plane (beyond-reference; k8s/sharding.py).

    ``replicas`` operator replicas each claim a member slot plus the
    per-shard Leases of a ``replicas * shardsPerReplica``-shard
    consistent-hash ring; a dead replica's orphaned shards must be
    adopted by the survivors within ``takeoverGraceSeconds``. The
    global maxUnavailable budget is coordinated through durable budget
    shares on the runtime DaemonSet, so shards can never jointly
    overdraw it — see docs/sharded-control-plane.md.
    """

    # Master switch; when False the operator runs single-owner.
    enable: bool = False
    # Expected replica count (member slots contended for).
    replicas: int = 2
    # Ring granularity: total shards = replicas * shardsPerReplica.
    # More shards per replica smooth takeover (a dead peer's load
    # spreads over every survivor instead of landing on one).
    shards_per_replica: int = 1
    # Seconds an orphaned shard may go ownerless before the operator
    # (and the chaos gate) treat it as a liveness violation. Budget for
    # member-slot expiry + shard-lease expiry + election rounds + one
    # composed crash-restart: ~5 lease durations.
    takeover_grace_seconds: int = 150
    # Per-shard Lease duration; renew deadline is derived (2/3).
    lease_duration_seconds: int = 30

    @property
    def num_shards(self) -> int:
        return self.replicas * self.shards_per_replica

    def validate(self) -> None:
        if self.replicas < 1:
            raise PolicyValidationError("sharding.replicas must be >= 1")
        if self.shards_per_replica < 1:
            raise PolicyValidationError(
                "sharding.shardsPerReplica must be >= 1")
        if self.lease_duration_seconds < 1:
            raise PolicyValidationError(
                "sharding.leaseDurationSeconds must be >= 1")
        if self.takeover_grace_seconds < self.lease_duration_seconds:
            raise PolicyValidationError(
                "sharding.takeoverGraceSeconds must be >= "
                "leaseDurationSeconds (a takeover cannot beat lease "
                "expiry)")

    def to_dict(self) -> dict[str, Any]:
        return {"enable": self.enable,
                "replicas": self.replicas,
                "shardsPerReplica": self.shards_per_replica,
                "takeoverGraceSeconds": self.takeover_grace_seconds,
                "leaseDurationSeconds": self.lease_duration_seconds}

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "ShardingPolicySpec":
        return cls(enable=data.get("enable", False),
                   replicas=data.get("replicas", 2),
                   shards_per_replica=data.get("shardsPerReplica", 1),
                   takeover_grace_seconds=data.get(
                       "takeoverGraceSeconds", 150),
                   lease_duration_seconds=data.get(
                       "leaseDurationSeconds", 30))

    def deep_copy(self) -> "ShardingPolicySpec":
        return copy.deepcopy(self)


@dataclass
class UpgradePolicySpec:
    """Top-level rolling-upgrade policy.

    Mirrors DriverUpgradePolicySpec (upgrade_spec.go:27-49) with identical
    defaults: autoUpgrade=False, maxParallelUpgrades=1 (0 = unlimited),
    maxUnavailable="25%".
    """

    # Global switch; when False apply_state is a no-op
    # (upgrade_state.go:372-375).
    auto_upgrade: bool = False
    # How many nodes may upgrade concurrently; 0 = no limit.
    max_parallel_upgrades: int = 1
    # Max nodes (int) or fraction of fleet (percent string) that may be
    # unavailable during the upgrade, cordoned/not-ready nodes included.
    max_unavailable: Optional[IntOrString] = "25%"
    pod_deletion: Optional[PodDeletionSpec] = None
    wait_for_completion: Optional[WaitForCompletionSpec] = None
    drain: Optional[DrainSpec] = None
    # Beyond-reference: name of the topology grouping mode ("flat" keeps
    # reference per-node semantics; "slice" upgrades whole ICI domains
    # atomically — see tpu_operator_libs.topology).
    topology_mode: str = "flat"
    # Beyond-reference (topology_mode="slice" only): per multislice
    # (DCN-spanning, JobSet-launched) job, at most this many member
    # slices may be unavailable concurrently — generalizing the
    # reference's per-node budget (upgrade_state.go:606-616) to DCN job
    # membership. See tpu_operator_libs.topology.multislice.
    max_unavailable_slices_per_job: int = 1
    # Beyond-reference: label selector scoping the managed node pool.
    # Pushed down into build_state's node LIST (and the incremental node
    # cursor) so a fleet sharing its cluster with unmanaged node pools
    # never pays — or acts on — their node metadata; also the
    # fleet-wide "managed node" definition the sharded canary cohort is
    # derived from under partition reads. "" = all nodes (reference
    # semantics).
    node_selector: str = ""
    # Beyond-reference: canary-gated rollout (probe a new revision on a
    # small cohort, halt the fleet when it fails). None = disabled.
    canary: Optional[CanaryRolloutSpec] = None
    # Beyond-reference: automatic rollback to the previous
    # ControllerRevision after a canary halt. None = rollback enabled
    # with defaults whenever canary is enabled.
    rollback: Optional[RollbackSpec] = None
    # Beyond-reference: sharded HA control plane (N replicas, per-shard
    # Leases, durable budget shares). None = single-owner semantics.
    sharding: Optional[ShardingPolicySpec] = None
    # Beyond-reference: learned per-node phase-duration prediction +
    # longest-processing-time-first wave packing. None = flat admission
    # order (reference semantics).
    predictor: Optional[PredictorSpec] = None
    # Beyond-reference: "finish by the close or don't start" gating on
    # predicted completion times. None = no window.
    maintenance_window: Optional[MaintenanceWindowSpec] = None
    # Beyond-reference: traffic-aware dynamic disruption budgets over
    # live serving-endpoint load signals, with safe mid-flight abort.
    # None = the static maxUnavailable applies unchanged.
    capacity: Optional[CapacityBudgetSpec] = None
    # Beyond-reference: what-if forecast gating admission (replay the
    # proposed revision against a frozen cluster clone BEFORE node one
    # is admitted). None = no preflight (reference semantics).
    preflight: Optional[PreflightSpec] = None
    # Beyond-reference: declarative CEL-style hook programs evaluated
    # sandboxed at the named policy hook points (policy/engine.py).
    # Typed "Any" to avoid an import cycle (api.policy_spec imports
    # this module); holds a PolicyHooksSpec. None = no programs.
    policy_hooks: Optional[Any] = None
    # Beyond-reference: dependency-ordered multi-artifact upgrade DAG
    # (policy/dag.py). Holds an ArtifactDAGSpec. None = only the
    # primary runtime is managed (reference semantics).
    artifact_dag: Optional[Any] = None

    def validate(self) -> None:
        if self.max_parallel_upgrades < 0:
            raise PolicyValidationError("maxParallelUpgrades must be >= 0")
        if self.max_unavailable is not None:
            # Raises on malformed values; negative budgets (int, "-5" or
            # "-10%") are rejected uniformly.
            if scaled_value_from_int_or_percent(self.max_unavailable, 100) < 0:
                raise PolicyValidationError("maxUnavailable must be >= 0")
        if self.topology_mode not in ("flat", "slice"):
            raise PolicyValidationError(
                f"unknown topologyMode {self.topology_mode!r}")
        if self.max_unavailable_slices_per_job < 1:
            raise PolicyValidationError(
                "maxUnavailableSlicesPerJob must be >= 1")
        if self.node_selector:
            from tpu_operator_libs.k8s.selectors import (
                parse_label_selector,
            )
            try:
                parse_label_selector(self.node_selector)
            except ValueError as exc:
                raise PolicyValidationError(
                    f"nodeSelector is not a valid label selector: {exc}")
        for sub in (self.pod_deletion, self.wait_for_completion, self.drain,
                    self.canary, self.rollback, self.sharding,
                    self.predictor, self.maintenance_window,
                    self.capacity, self.preflight, self.policy_hooks,
                    self.artifact_dag):
            if sub is not None:
                sub.validate()

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "autoUpgrade": self.auto_upgrade,
            "maxParallelUpgrades": self.max_parallel_upgrades,
            "maxUnavailable": self.max_unavailable,
            "topologyMode": self.topology_mode,
            "maxUnavailableSlicesPerJob": self.max_unavailable_slices_per_job,
        }
        if self.node_selector:
            out["nodeSelector"] = self.node_selector
        if self.pod_deletion is not None:
            out["podDeletion"] = self.pod_deletion.to_dict()
        if self.wait_for_completion is not None:
            out["waitForCompletion"] = self.wait_for_completion.to_dict()
        if self.drain is not None:
            out["drain"] = self.drain.to_dict()
        if self.canary is not None:
            out["canary"] = self.canary.to_dict()
        if self.rollback is not None:
            out["rollback"] = self.rollback.to_dict()
        if self.sharding is not None:
            out["sharding"] = self.sharding.to_dict()
        if self.predictor is not None:
            out["predictor"] = self.predictor.to_dict()
        if self.maintenance_window is not None:
            out["maintenanceWindow"] = self.maintenance_window.to_dict()
        if self.capacity is not None:
            out["capacityBudget"] = self.capacity.to_dict()
        if self.preflight is not None:
            out["preflight"] = self.preflight.to_dict()
        if self.policy_hooks is not None:
            out["policyHooks"] = self.policy_hooks.to_dict()
        if self.artifact_dag is not None:
            out["artifactDAG"] = self.artifact_dag.to_dict()
        return out

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "UpgradePolicySpec":
        spec = cls(
            auto_upgrade=data.get("autoUpgrade", False),
            max_parallel_upgrades=data.get("maxParallelUpgrades", 1),
            max_unavailable=data.get("maxUnavailable", "25%"),
            topology_mode=data.get("topologyMode", "flat"),
            max_unavailable_slices_per_job=data.get(
                "maxUnavailableSlicesPerJob", 1),
            node_selector=data.get("nodeSelector", ""),
        )
        if "podDeletion" in data and data["podDeletion"] is not None:
            spec.pod_deletion = PodDeletionSpec.from_dict(data["podDeletion"])
        if "waitForCompletion" in data and data["waitForCompletion"] is not None:
            spec.wait_for_completion = WaitForCompletionSpec.from_dict(
                data["waitForCompletion"])
        if "drain" in data and data["drain"] is not None:
            spec.drain = DrainSpec.from_dict(data["drain"])
        if data.get("canary") is not None:
            spec.canary = CanaryRolloutSpec.from_dict(data["canary"])
        if data.get("rollback") is not None:
            spec.rollback = RollbackSpec.from_dict(data["rollback"])
        if data.get("sharding") is not None:
            spec.sharding = ShardingPolicySpec.from_dict(data["sharding"])
        if data.get("predictor") is not None:
            spec.predictor = PredictorSpec.from_dict(data["predictor"])
        if data.get("maintenanceWindow") is not None:
            spec.maintenance_window = MaintenanceWindowSpec.from_dict(
                data["maintenanceWindow"])
        if data.get("capacityBudget") is not None:
            spec.capacity = CapacityBudgetSpec.from_dict(
                data["capacityBudget"])
        if data.get("preflight") is not None:
            spec.preflight = PreflightSpec.from_dict(data["preflight"])
        if data.get("policyHooks") is not None:
            from tpu_operator_libs.api.policy_spec import PolicyHooksSpec
            spec.policy_hooks = PolicyHooksSpec.from_dict(
                data["policyHooks"])
        if data.get("artifactDAG") is not None:
            from tpu_operator_libs.api.policy_spec import ArtifactDAGSpec
            spec.artifact_dag = ArtifactDAGSpec.from_dict(
                data["artifactDAG"])
        return spec

    def deep_copy(self) -> "UpgradePolicySpec":
        return copy.deepcopy(self)
