"""Federation policy types — the multi-cluster rollout configuration.

The federation controller (:mod:`tpu_operator_libs.federation`) treats
whole clusters/regions as ring members and drives each region's operator
purely through its CRD/policy surface. This spec is the federation
layer's own declarative configuration: the GLOBAL disruption budget the
per-region shares partition, the region-as-canary gate (which region
bakes a revision before the fleet, and for how long), the wave
concurrency, and the follow-the-sun trough gating. Same dataclass +
``to_dict``/``from_dict``/``deep_copy`` idiom as
:mod:`tpu_operator_libs.api.upgrade_policy`.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass
from typing import Any, Optional

from tpu_operator_libs.api.upgrade_policy import (
    IntOrString,
    PolicyValidationError,
    PreflightSpec,
    scaled_value_from_int_or_percent,
)


@dataclass
class FederationPolicySpec:
    """Top-level multi-cluster federated rollout policy.

    ``globalMaxUnavailable`` is scaled against the TOTAL fleet (the sum
    of every region's managed node count) and split into durable
    per-region budget-share stamps — a region's effective
    ``maxUnavailable`` IS its stamp, so the global inequality holds
    region-locally even across partitions and controller restarts.
    """

    # Master switch; when False the controller's reconcile is a no-op.
    enable: bool = True
    # Global disruption budget: max nodes (int) or fleet fraction
    # (percent string) unavailable across ALL regions combined.
    global_max_unavailable: IntOrString = "25%"
    # Region that bakes every new revision before the fleet ("" = the
    # lowest-utilization region at evaluation time, ties by name).
    canary_region: str = ""
    # Seconds the canary region must bake (every node done on the
    # revision) before any other region is admitted.
    bake_seconds: int = 600
    # Non-canary regions upgrading concurrently once the bake passed.
    max_concurrent_regions: int = 1
    # Follow-the-sun: admit a region only while its live utilization is
    # at or below troughUtilization (regions are ordered by current
    # utilization, so each upgrades in its own traffic trough). False =
    # admit in name order as slots free up.
    follow_the_sun: bool = True
    trough_utilization: float = 0.35
    # Liveness override: a region that never dips below the trough
    # threshold is admitted anyway after waiting this long.
    max_trough_wait_seconds: int = 3600
    # Watch mode (federation/region_watch.py): how stale a region's
    # change cursor may grow before the region stops counting as
    # freshly read — the staleness bound that replaces the per-pass
    # probe round-trip. A region past the bound freezes raises
    # fleet-wide and defers its own admission, exactly like a
    # rejected probe write in polling mode.
    watch_staleness_seconds: float = 30.0
    # Cross-region session pre-shift: before admitting a region,
    # reserve session capacity in an adjacent region (durable
    # reservation→ready stamp pair on the reserve region's DS),
    # require readiness, then admit — so a region admission drops
    # zero interactive sessions globally.
    session_pre_shift: bool = True
    # Liveness override for pre-shift: if no reserve region can reach
    # readiness within this wait, the admission proceeds anyway
    # (audited) — a missing spare region must not park the rollout
    # forever.
    max_preshift_wait_seconds: int = 3600
    # Region-admission preflight (upgrade/preflight.py semantics at
    # region granularity): before a region is rolled — and before its
    # budget share is stamped — its rollout is forecast against the
    # region's live traffic signal; a required-mode threshold breach
    # defers the region under an audited preflight-rejected hold.
    preflight: Optional[PreflightSpec] = None

    def validate(self) -> None:
        if scaled_value_from_int_or_percent(
                self.global_max_unavailable, 100) < 0:
            raise PolicyValidationError(
                "globalMaxUnavailable must be >= 0")
        if self.bake_seconds < 0:
            raise PolicyValidationError("bakeSeconds must be >= 0")
        if self.max_concurrent_regions < 1:
            raise PolicyValidationError(
                "maxConcurrentRegions must be >= 1")
        if not 0.0 <= self.trough_utilization <= 1.0:
            raise PolicyValidationError(
                "troughUtilization must be in [0, 1]")
        if self.max_trough_wait_seconds < 0:
            raise PolicyValidationError(
                "maxTroughWaitSeconds must be >= 0")
        if self.watch_staleness_seconds <= 0:
            raise PolicyValidationError(
                "watchStalenessSeconds must be > 0")
        if self.max_preshift_wait_seconds < 0:
            raise PolicyValidationError(
                "maxPreshiftWaitSeconds must be >= 0")
        if self.preflight is not None:
            self.preflight.validate()

    def to_dict(self) -> dict[str, Any]:
        out = {
            "enable": self.enable,
            "globalMaxUnavailable": self.global_max_unavailable,
            "canaryRegion": self.canary_region,
            "bakeSeconds": self.bake_seconds,
            "maxConcurrentRegions": self.max_concurrent_regions,
            "followTheSun": self.follow_the_sun,
            "troughUtilization": self.trough_utilization,
            "maxTroughWaitSeconds": self.max_trough_wait_seconds,
            "watchStalenessSeconds": self.watch_staleness_seconds,
            "sessionPreShift": self.session_pre_shift,
            "maxPreshiftWaitSeconds": self.max_preshift_wait_seconds,
        }
        if self.preflight is not None:
            out["preflight"] = self.preflight.to_dict()
        return out

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "FederationPolicySpec":
        spec = cls(
            enable=data.get("enable", True),
            global_max_unavailable=data.get("globalMaxUnavailable",
                                            "25%"),
            canary_region=data.get("canaryRegion", ""),
            bake_seconds=data.get("bakeSeconds", 600),
            max_concurrent_regions=data.get("maxConcurrentRegions", 1),
            follow_the_sun=data.get("followTheSun", True),
            trough_utilization=data.get("troughUtilization", 0.35),
            max_trough_wait_seconds=data.get("maxTroughWaitSeconds",
                                             3600),
            watch_staleness_seconds=data.get("watchStalenessSeconds",
                                             30.0),
            session_pre_shift=data.get("sessionPreShift", True),
            max_preshift_wait_seconds=data.get("maxPreshiftWaitSeconds",
                                               3600))
        if "preflight" in data:
            spec.preflight = PreflightSpec.from_dict(data["preflight"])
        return spec

    def deep_copy(self) -> "FederationPolicySpec":
        return copy.deepcopy(self)
