"""CRD manifest generation + structural-schema defaulting/validation.

The reference's policy types carry kubebuilder markers (``+kubebuilder:
default``, ``+kubebuilder:validation:Minimum`` — upgrade_spec.go:27-110)
and rely on controller-gen to turn them into a CustomResourceDefinition's
OpenAPI v3 schema, with the API server applying defaults and validation at
admission. This build has no controller-gen, so this module is that
pipeline, owned directly:

- ``upgrade_policy_schema()`` / ``unified_policy_schema()`` — OpenAPI v3
  structural schemas for the policy specs, with the same defaults and
  minimums the reference's markers declare (plus the beyond-reference
  ``topologyMode`` enum).
- ``build_crd()`` — wraps a spec schema into a complete CRD manifest a
  consumer can ``kubectl apply`` to get a standalone ``TPUUpgradePolicy``
  (or unified multi-accelerator) resource.
- ``apply_defaults()`` / ``validate_against_schema()`` — the API-server
  side of the contract for tests and offline policy linting; defaulting
  here must agree with ``from_dict`` defaulting (pinned by
  tests/test_crd.py).

Run ``python -m tpu_operator_libs.api.crd`` to (re)generate
``examples/crd/*.yaml``.
"""

from __future__ import annotations

import json
from typing import Any, Optional

from tpu_operator_libs.api.upgrade_policy import PolicyValidationError

DEFAULT_GROUP = "tpu-operator.dev"
DEFAULT_VERSION = "v1alpha1"


def _int_or_string(description: str, default: Any = None) -> dict[str, Any]:
    schema: dict[str, Any] = {
        "x-kubernetes-int-or-string": True,
        "description": description,
    }
    if default is not None:
        schema["default"] = default
    return schema


def wait_for_completion_schema() -> dict[str, Any]:
    """WaitForCompletionSpec (upgrade_spec.go:52-64)."""
    return {
        "type": "object",
        "description": "Wait for selected workload pods to finish before "
                       "disrupting the node.",
        "properties": {
            "podSelector": {
                "type": "string",
                "description": "Label selector for pods to wait on; empty "
                               "means don't wait.",
                "default": "",
            },
            "timeoutSeconds": {
                "type": "integer",
                "minimum": 0,
                "default": 0,
                "description": "Seconds to wait before giving up; 0 waits "
                               "forever.",
            },
        },
    }


def pod_deletion_schema() -> dict[str, Any]:
    """PodDeletionSpec (upgrade_spec.go:67-83)."""
    return {
        "type": "object",
        "description": "Configuration for the optional pod-deletion state.",
        "properties": {
            "force": {
                "type": "boolean",
                "default": False,
                "description": "Allow deleting pods that have no "
                               "controller.",
            },
            "timeoutSeconds": {
                "type": "integer",
                "minimum": 0,
                "default": 300,
                "description": "Seconds to wait for pod termination; 0 is "
                               "infinite.",
            },
            "deleteEmptyDir": {
                "type": "boolean",
                "default": False,
                "description": "Proceed even if pods use emptyDir volumes "
                               "(their data is lost).",
            },
        },
    }


def drain_schema() -> dict[str, Any]:
    """DrainSpec (upgrade_spec.go:86-110)."""
    return {
        "type": "object",
        "description": "Configuration for node drain during upgrade.",
        "properties": {
            "enable": {
                "type": "boolean",
                "default": False,
                "description": "Master switch; when false the drain state "
                               "is skipped entirely.",
            },
            "force": {
                "type": "boolean",
                "default": False,
                "description": "Evict pods without a controller.",
            },
            "podSelector": {
                "type": "string",
                "default": "",
                "description": "Label selector restricting which pods are "
                               "drained; empty means all.",
            },
            "timeoutSeconds": {
                "type": "integer",
                "minimum": 0,
                "default": 300,
                "description": "Seconds before giving up the drain; 0 is "
                               "infinite.",
            },
            "deleteEmptyDir": {
                "type": "boolean",
                "default": False,
                "description": "Evict pods using emptyDir volumes (their "
                               "data is deleted).",
            },
        },
    }


def canary_schema() -> dict[str, Any]:
    """CanaryRolloutSpec (beyond-reference: canary-gated rollout)."""
    return {
        "type": "object",
        "description": "Canary-gated rollout: probe a new revision on a "
                       "small cohort before opening the fleet waves.",
        "properties": {
            "enable": {
                "type": "boolean",
                "default": False,
                "description": "Master switch; when false rollout "
                               "proceeds reference-style.",
            },
            "canaryCount": _int_or_string(
                "Cohort size: node count (ex: 2) or fleet percentage "
                "(ex: \"10%\"), minimum 1.", default=1),
            "bakeSeconds": {
                "type": "integer",
                "minimum": 0,
                "default": 300,
                "description": "Seconds the completed cohort must bake "
                               "before fleet waves open.",
            },
            "failureThreshold": {
                "type": "integer",
                "minimum": 1,
                "default": 1,
                "description": "Failure verdicts on one revision that "
                               "flip the fleet to HALTED.",
            },
        },
    }


def rollback_schema() -> dict[str, Any]:
    """RollbackSpec (what a canary HALT does beyond freezing)."""
    return {
        "type": "object",
        "description": "Automatic rollback to the previous "
                       "ControllerRevision after a canary halt.",
        "properties": {
            "enable": {
                "type": "boolean",
                "default": True,
                "description": "Re-pin the previous revision and drive "
                               "affected nodes through rollback-required; "
                               "when false the fleet stays halted for a "
                               "human.",
            },
        },
    }


def sharding_schema() -> dict[str, Any]:
    """ShardingPolicySpec (beyond-reference: sharded HA control
    plane — per-shard Leases, crash-tolerant ownership, durable budget
    shares; docs/sharded-control-plane.md)."""
    return {
        "type": "object",
        "description": "Sharded HA control plane: N operator replicas "
                       "each own a partition of the fleet via "
                       "per-shard Leases, with the global budget "
                       "coordinated through durable shares.",
        "properties": {
            "enable": {
                "type": "boolean",
                "default": False,
                "description": "Master switch; when false the operator "
                               "runs single-owner.",
            },
            "replicas": {
                "type": "integer",
                "minimum": 1,
                "default": 2,
                "description": "Expected operator replica count "
                               "(member slots contended for).",
            },
            "shardsPerReplica": {
                "type": "integer",
                "minimum": 1,
                "default": 1,
                "description": "Ring granularity: total shards = "
                               "replicas * shardsPerReplica. More "
                               "shards per replica spread a dead "
                               "peer's load over every survivor.",
            },
            "takeoverGraceSeconds": {
                "type": "integer",
                "minimum": 1,
                "default": 150,
                "description": "Seconds an orphaned shard may go "
                               "ownerless before it counts as a "
                               "liveness violation; must exceed "
                               "leaseDurationSeconds.",
            },
            "leaseDurationSeconds": {
                "type": "integer",
                "minimum": 1,
                "default": 30,
                "description": "Per-shard Lease duration.",
            },
        },
    }


def predictor_schema() -> dict[str, Any]:
    """PredictorSpec (beyond-reference: cost-aware predictive wave
    planning — learned per-node phase durations + LPT packing;
    docs/predictive-planner.md)."""
    return {
        "type": "object",
        "description": "Cost-aware predictive wave planning: learn "
                       "per-node/per-phase upgrade durations online and "
                       "admit waves longest-predicted-first so "
                       "stragglers never pace the last wave.",
        "properties": {
            "enable": {
                "type": "boolean",
                "default": False,
                "description": "Master switch; when false admission "
                               "order is reference-style.",
            },
            "smoothing": {
                "type": "number",
                "exclusiveMinimum": 0,
                "maximum": 1,
                "default": 0.5,
                "description": "EWMA weight of the newest per-node "
                               "duration sample.",
            },
            "priorSeconds": {
                "type": "number",
                "minimum": 0,
                "default": 120,
                "description": "Per-phase prior (seconds) while nothing "
                               "has been learned; also the cold-fleet "
                               "cost the maintenance-window gate "
                               "assumes.",
            },
        },
    }


def maintenance_window_schema() -> dict[str, Any]:
    """MaintenanceWindowSpec (beyond-reference: finish-by-close-or-
    don't-start admission gating on predicted completion times)."""
    return {
        "type": "object",
        "description": "Maintenance window: a node is only admitted "
                       "when its conservatively predicted completion "
                       "lands before the window close; otherwise it is "
                       "deferred, never started-and-stranded. Requires "
                       "the predictor.",
        "properties": {
            "enable": {
                "type": "boolean",
                "default": False,
                "description": "Master switch; when false (or no close "
                               "is configured) nothing is gated.",
            },
            "closeEpochSeconds": {
                "type": "number",
                "description": "Absolute close instant (epoch seconds); "
                               "takes precedence over dailyCloseUtc.",
            },
            "dailyCloseUtc": {
                "type": "string",
                "default": "",
                "description": "Recurring daily close, \"HH:MM\" UTC "
                               "(\"finish by 06:00\").",
            },
            "marginSeconds": {
                "type": "integer",
                "minimum": 0,
                "default": 0,
                "description": "Safety slack: predicted completion must "
                               "land this many seconds before the "
                               "close.",
            },
        },
    }


def capacity_budget_schema() -> dict[str, Any]:
    """CapacityBudgetSpec (beyond-reference: traffic-aware dynamic
    disruption budgets over live serving load signals, with safe
    mid-flight abort; docs/traffic-aware-budgets.md)."""
    return {
        "type": "object",
        "description": "Traffic-aware disruption budgets: recompute the "
                       "effective maxUnavailable every pass from live "
                       "serving-endpoint load (in-flight generations, "
                       "QPS EWMA, per-node capacity) — drain "
                       "aggressively in traffic troughs, pause at "
                       "peaks, abort mid-flight drains on capacity "
                       "collapse.",
        "properties": {
            "enable": {
                "type": "boolean",
                "default": False,
                "description": "Master switch; when false the static "
                               "maxUnavailable applies unchanged.",
            },
            "sloHeadroomFraction": {
                "type": "number",
                "minimum": 0,
                "exclusiveMaximum": 1,
                "default": 0.25,
                "description": "Required spare-capacity fraction over "
                               "current demand before a node may be "
                               "taken unavailable (a fraction >= 1 "
                               "could never be satisfied at any "
                               "nonzero utilization; rejected at "
                               "policy-load time).",
            },
            "minEffectiveBudget": {
                "type": "integer",
                "minimum": 0,
                "default": 0,
                "description": "Floor for the effective budget (nodes); "
                               "0 lets peaks pause draining entirely.",
            },
            "maxEffectiveBudget": {
                "type": "integer",
                "minimum": 0,
                "default": 0,
                "description": "Ceiling for the effective budget "
                               "(nodes); 0 = clamped by the static "
                               "maxUnavailable alone, a positive value "
                               "lets troughs exceed the static count.",
            },
            "peakPauseUtilization": {
                "type": "number",
                "exclusiveMinimum": 0,
                "maximum": 1,
                "default": 0.85,
                "description": "Utilization (demand / live capacity) at "
                               "or above which admission pauses "
                               "outright.",
            },
            "perNodeCapacity": {
                "type": "integer",
                "minimum": 1,
                "default": 8,
                "description": "Concurrent generations one serving node "
                               "sustains (default for endpoints that do "
                               "not declare their own capacity).",
            },
            "smoothing": {
                "type": "number",
                "exclusiveMinimum": 0,
                "maximum": 1,
                "default": 0.3,
                "description": "EWMA weight of the newest demand/QPS "
                               "sample.",
            },
            "recheckSeconds": {
                "type": "number",
                "exclusiveMinimum": 0,
                "default": 30,
                "description": "Trough-window cadence: re-evaluation "
                               "wakeup registered on the deadline timer "
                               "wheel while the budget is held below "
                               "the static count.",
            },
            "trafficClasses": {
                "type": "array",
                "default": [],
                "description": "Serving traffic classes "
                               "(upgrade/handover.py): with any "
                               "declared, the DisruptionCostRanker "
                               "spends the budget on the cheapest "
                               "serving disruption first and holds "
                               "sole-replica interactive nodes behind "
                               "the prewarm arc.",
                "items": traffic_class_schema(),
            },
            "prewarm": {
                "type": "boolean",
                "default": False,
                "description": "Prewarm arc: reserve an already-"
                               "upgraded spare, bring a replacement "
                               "replica up on it and require readiness "
                               "(durable reserve/ready stamps) before "
                               "a hold-worthy incumbent's eviction is "
                               "admitted.",
            },
        },
    }


def traffic_class_schema() -> dict[str, Any]:
    """TrafficClassSpec (api/upgrade_policy.py)."""
    return {
        "type": "object",
        "description": "One serving traffic class: disruption "
                       "sensitivity, replication floor, drain "
                       "deadline and admission SLO.",
        "required": ["name"],
        "properties": {
            "name": {
                "type": "string",
                "pattern": "^[a-z0-9]([a-z0-9-]{0,61}[a-z0-9])?$",
                "description": "Class name the ServingEndpoints "
                               "declare (DNS-label shaped).",
            },
            "interactive": {
                "type": "boolean",
                "default": False,
                "description": "Strict-SLO class: admission shortfall "
                               "is a violation and sole-replica "
                               "models are held behind the prewarm "
                               "arc.",
            },
            "minReplicas": {
                "type": "integer",
                "minimum": 1,
                "default": 1,
                "description": "A node may drain only while each of "
                               "its models keeps at least this many "
                               "other admitting replicas.",
            },
            "drainDeadlineSeconds": {
                "type": "number",
                "exclusiveMinimum": 0,
                "default": 120,
                "description": "Router-side drain deadline: in-flight "
                               "generations past it are handed over "
                               "to a peer replica (never dropped).",
            },
            "maxShortfallFraction": {
                "type": "number",
                "minimum": 0,
                "exclusiveMaximum": 1,
                "default": 0,
                "description": "Fraction of the class's offered load "
                               "that may go unplaced at a tick before "
                               "its SLO counts as breached (0 = "
                               "strict; interactive must be 0).",
            },
        },
    }


def policy_hooks_schema() -> dict[str, Any]:
    """PolicyHooksSpec (api/policy_spec.py): declarative CEL-style
    programs at the named hook points, evaluated sandboxed
    (policy/expr.py) with per-hook step/wall budgets."""
    return {
        "type": "object",
        "description": "Declarative policy hooks: small CEL-style "
                       "programs attached to named, versioned hook "
                       "points (eviction.filter, planner.admission, "
                       "window.gate, validation.verdict, "
                       "canary.verdict, abort.audit), evaluated in a "
                       "sandbox with per-hook budgets. A failing or "
                       "over-budget program parks its node with an "
                       "audited policy-error/policy-budget reason — "
                       "it can never wedge a reconcile pass.",
        "properties": {
            "enable": {
                "type": "boolean",
                "default": True,
                "description": "Master switch; when false no program "
                               "is evaluated.",
            },
            "hooks": {
                "type": "array",
                "default": [],
                "description": "One program per hook point (compose "
                               "conditions with '&&').",
                "items": {
                    "type": "object",
                    "required": ["hook", "program"],
                    "properties": {
                        "hook": {
                            "type": "string",
                            "enum": ["eviction.filter",
                                     "planner.admission",
                                     "window.gate",
                                     "validation.verdict",
                                     "canary.verdict",
                                     "abort.audit"],
                            "description": "Named hook point from the "
                                           "catalog "
                                           "(docs/policy-engine.md §2).",
                        },
                        "version": {
                            "type": "string",
                            "enum": ["v1"],
                            "default": "v1",
                            "description": "Hook-point contract "
                                           "version.",
                        },
                        "program": {
                            "type": "string",
                            "description": "The CEL-style expression; "
                                           "admission hooks must "
                                           "return a boolean.",
                        },
                        "maxSteps": {
                            "type": "integer",
                            "minimum": 1,
                            "maximum": 100000,
                            "default": 2000,
                            "description": "Per-evaluation step "
                                           "budget.",
                        },
                        "maxMillis": {
                            "type": "number",
                            "exclusiveMinimum": 0,
                            "maximum": 1000,
                            "default": 5,
                            "description": "Per-evaluation wall budget "
                                           "(milliseconds).",
                        },
                    },
                },
            },
        },
    }


def artifact_dag_schema() -> dict[str, Any]:
    """ArtifactDAGSpec (api/policy_spec.py): dependency-ordered
    multi-artifact upgrades through one shared cordon/drain cycle per
    node (policy/dag.py)."""
    return {
        "type": "object",
        "description": "Multi-artifact upgrade DAG: every artifact "
                       "(libtpu, device plugin, network driver, node "
                       "OS image, ...) is a DaemonSet advanced through "
                       "the node's ONE cordon/drain cycle in "
                       "dependency order, with crash-ordered durable "
                       "per-artifact revision stamps; a crash-looping "
                       "artifact revision is quarantined and only its "
                       "un-started dependent suffix rolls back.",
        "properties": {
            "enable": {
                "type": "boolean",
                "default": False,
                "description": "Master switch; when false only the "
                               "primary runtime is managed (reference "
                               "semantics).",
            },
            "failureThreshold": {
                "type": "integer",
                "minimum": 1,
                "default": 1,
                "description": "Crash-looping nodes at an artifact's "
                               "target revision that quarantine the "
                               "revision.",
            },
            "artifacts": {
                "type": "array",
                "default": [],
                "description": "The DAG's artifacts; the entry whose "
                               "runtimeLabels equal the operator's "
                               "managed runtime labels is the primary "
                               "(driven by the state machine itself).",
                "items": {
                    "type": "object",
                    "required": ["name", "runtimeLabels"],
                    "properties": {
                        "name": {
                            "type": "string",
                            "pattern": "^[a-z0-9]"
                                       "([a-z0-9-]{0,61}[a-z0-9])?$",
                            "description": "Artifact name — also the "
                                           "per-node revision-stamp "
                                           "key suffix.",
                        },
                        "runtimeLabels": {
                            "type": "object",
                            "additionalProperties": {"type": "string"},
                            "description": "Labels selecting the "
                                           "artifact's DaemonSet.",
                        },
                        "namespace": {
                            "type": "string",
                            "default": "",
                            "description": "Namespace of the "
                                           "artifact's DaemonSet "
                                           "(empty = the reconcile "
                                           "namespace).",
                        },
                        "dependsOn": {
                            "type": "array",
                            "default": [],
                            "items": {"type": "string"},
                            "description": "Artifacts that must be "
                                           "stamped at their target "
                                           "on a node before this one "
                                           "may advance there "
                                           "(cycles are rejected at "
                                           "validation).",
                        },
                    },
                },
            },
        },
    }


def wedge_detection_schema() -> dict[str, Any]:
    """WedgeDetectionSpec (api/remediation_policy.py)."""
    return {
        "type": "object",
        "description": "Thresholds of the built-in wedge detectors.",
        "properties": {
            "notReadyGraceSeconds": {
                "type": "integer",
                "minimum": 0,
                "default": 300,
                "description": "Seconds a node may report NotReady "
                               "before it counts as wedged.",
            },
            "podRestartThreshold": {
                "type": "integer",
                "minimum": 1,
                "default": 10,
                "description": "Restart count beyond which a not-ready "
                               "runtime container is a crash loop.",
            },
            "terminatingStuckSeconds": {
                "type": "integer",
                "minimum": 0,
                "default": 600,
                "description": "Seconds a runtime pod may sit "
                               "Terminating before it counts as stuck.",
            },
            "unhealthyConditionTypes": {
                "type": "array",
                "items": {"type": "string"},
                "description": "Node condition types whose status != "
                               "True mark the node wedged immediately.",
            },
        },
    }


def reconfiguration_schema() -> dict[str, Any]:
    """ReconfigurationPolicySpec (degraded-slice topology
    reconfiguration — the Ironwood OCS analogue)."""
    return {
        "type": "object",
        "description": "Remap a condemned node's ICI slice onto a spare "
                       "host (or admit a documented degraded shape) "
                       "instead of parking the slice on its repair.",
        "properties": {
            "enable": {
                "type": "boolean",
                "default": False,
                "description": "Master switch; when false condemned "
                               "nodes park in remediation-failed with "
                               "their slice down.",
            },
            "spareProvisionTimeoutSeconds": {
                "type": "integer",
                "minimum": 0,
                "default": 1800,
                "description": "Seconds a reserved spare may take to "
                               "reach the target revision before the "
                               "slice falls back to a degraded "
                               "admission; 0 waits forever.",
            },
            "settleSeconds": {
                "type": "integer",
                "minimum": 0,
                "default": 120,
                "description": "Seconds a freshly remapped slice keeps "
                               "its multislice sticky-down membership "
                               "while its job's pods reschedule.",
            },
            "allowDegraded": {
                "type": "boolean",
                "default": True,
                "description": "Permit documented degraded shapes when "
                               "no spare is available.",
            },
            "takeOverFailedUpgrades": {
                "type": "boolean",
                "default": True,
                "description": "Let remediation take over nodes parked "
                               "in upgrade-failed whose wedge signal "
                               "persists (dead hardware mid-rollout).",
            },
        },
    }


def precursor_schema() -> dict[str, Any]:
    """PrecursorPolicySpec (predictive condemn-before-fail — the
    Ironwood proactive-routing analogue)."""
    return {
        "type": "object",
        "description": "Predictive condemn-before-fail: an online "
                       "failure-precursor model condemns nodes whose "
                       "hardware-health counter rates (ECC, link-flap, "
                       "thermal) cross threshold, remapping their slice "
                       "onto a spare while they still serve. Requires "
                       "reconfiguration.enable.",
        "properties": {
            "enable": {
                "type": "boolean",
                "default": False,
                "description": "Master switch; when false the "
                               "remediation machine stays purely "
                               "reactive.",
            },
            "maxAtRisk": _int_or_string(
                "Fleet-wide at-risk condemnation budget: nodes carrying "
                "the at-risk stamp may never exceed this count or fleet "
                "percentage — a signal storm can never mass-drain the "
                "fleet.", default="10%"),
            "rateThresholdPerHour": {
                "type": "number",
                "default": 6.0,
                "description": "Events/hour a per-node EWMA precursor "
                               "rate must reach before the node is a "
                               "condemnation candidate.",
            },
            "minObservations": {
                "type": "integer",
                "minimum": 1,
                "default": 3,
                "description": "Consecutive over-threshold observations "
                               "required before the at-risk verdict "
                               "fires (and the stand-down streak an "
                               "in-flight arc needs to abort).",
            },
            "smoothing": {
                "type": "number",
                "default": 0.5,
                "description": "EWMA smoothing factor in (0, 1].",
            },
        },
    }


def remediation_policy_schema() -> dict[str, Any]:
    """RemediationPolicySpec (api/remediation_policy.py): the
    unplanned-fault machine's declarative surface."""
    return {
        "type": "object",
        "description": "Auto-remediation policy for wedged nodes "
                       "(detection, escalation ladder, budgets, slice "
                       "reconfiguration).",
        "properties": {
            "enable": {
                "type": "boolean",
                "default": False,
                "description": "Global switch; when false the "
                               "remediation machine is a no-op.",
            },
            "maxConcurrent": {
                "type": "integer",
                "minimum": 0,
                "default": 1,
                "description": "Nodes actively remediated concurrently; "
                               "0 means no limit.",
            },
            "maxUnavailable": _int_or_string(
                "Availability budget for quarantining nodes that are "
                "still serving; already-unavailable nodes are exempt.",
                default="10%"),
            "restartAttempts": {
                "type": "integer",
                "minimum": 0,
                "default": 1,
                "description": "Attempts that run the runtime-restart "
                               "rung before escalating to reboot.",
            },
            "maxAttempts": {
                "type": "integer",
                "minimum": 1,
                "default": 3,
                "description": "Dispatched recovery attempts before the "
                               "node parks in remediation-failed.",
            },
            "actionTimeoutSeconds": {
                "type": "integer",
                "minimum": 0,
                "default": 600,
                "description": "Seconds a dispatched restart/reboot may "
                               "run before the attempt is written off.",
            },
            "settleSeconds": {
                "type": "integer",
                "minimum": 0,
                "default": 60,
                "description": "Seconds the wedge signal must stay "
                               "clear during revalidation.",
            },
            "revalidateTimeoutSeconds": {
                "type": "integer",
                "minimum": 0,
                "default": 900,
                "description": "Seconds revalidation may churn before "
                               "the attempt is written off.",
            },
            "drain": drain_schema(),
            "detection": wedge_detection_schema(),
            "reconfiguration": reconfiguration_schema(),
            "precursor": precursor_schema(),
        },
    }


def federation_policy_schema() -> dict[str, Any]:
    """FederationPolicySpec (beyond-reference: multi-cluster federated
    rollouts — region-as-canary waves, a global disruption budget split
    into durable per-region shares, follow-the-sun trough gating;
    docs/federation.md)."""
    return {
        "type": "object",
        "description": "Multi-cluster federated rollout policy: whole "
                       "regions are ring members, one low-traffic "
                       "region bakes each revision before the fleet, "
                       "and a global disruption budget is split into "
                       "durable per-region shares.",
        "properties": {
            "enable": {
                "type": "boolean",
                "default": True,
                "description": "Master switch; when false the "
                               "federation reconcile is a no-op.",
            },
            "globalMaxUnavailable": _int_or_string(
                "Maximum number (ex: 20) or fleet percentage (ex: "
                "\"25%\") of nodes that may be unavailable across ALL "
                "regions combined.", default="25%"),
            "canaryRegion": {
                "type": "string",
                "default": "",
                "description": "Region that bakes every new revision "
                               "before the fleet; empty selects the "
                               "lowest-utilization region at "
                               "evaluation time (ties by name).",
            },
            "bakeSeconds": {
                "type": "integer",
                "minimum": 0,
                "default": 600,
                "description": "Seconds the canary region must bake "
                               "(every node done on the revision) "
                               "before any other region is admitted.",
            },
            "maxConcurrentRegions": {
                "type": "integer",
                "minimum": 1,
                "default": 1,
                "description": "Non-canary regions upgrading "
                               "concurrently once the bake passed.",
            },
            "followTheSun": {
                "type": "boolean",
                "default": True,
                "description": "Admit each region only in its own "
                               "traffic trough (ordered by live "
                               "utilization); false admits in name "
                               "order as slots free up.",
            },
            "troughUtilization": {
                "type": "number",
                "minimum": 0,
                "maximum": 1,
                "default": 0.35,
                "description": "Utilization at or below which a region "
                               "counts as in its trough.",
            },
            "maxTroughWaitSeconds": {
                "type": "integer",
                "minimum": 0,
                "default": 3600,
                "description": "Liveness override: a region never "
                               "dipping below the trough threshold is "
                               "admitted anyway after this wait.",
            },
            "watchStalenessSeconds": {
                "type": "number",
                "exclusiveMinimum": 0,
                "default": 30.0,
                "description": "Watch mode: how stale a region's "
                               "change cursor may grow before the "
                               "region stops counting as freshly read "
                               "(freezes raises fleet-wide and defers "
                               "its own admission).",
            },
            "sessionPreShift": {
                "type": "boolean",
                "default": True,
                "description": "Reserve session capacity in an "
                               "adjacent region (durable "
                               "reservation→ready stamp pair) "
                               "and require readiness before "
                               "admitting a region, so an admission "
                               "drops zero sessions globally.",
            },
            "maxPreshiftWaitSeconds": {
                "type": "integer",
                "minimum": 0,
                "default": 3600,
                "description": "Liveness override: if no reserve "
                               "region reaches readiness within this "
                               "wait the admission proceeds anyway "
                               "(audited).",
            },
            "preflight": preflight_schema(),
        },
    }


def preflight_schema() -> dict[str, Any]:
    """PreflightSpec (beyond-reference: what-if forecast gating
    admission against a frozen cluster clone; docs/preflight.md)."""
    return {
        "type": "object",
        "description": "Rollout preflight: before node one is admitted, "
                       "replay the proposed revision in-process against "
                       "a frozen clone of the cluster picture (learned "
                       "durations, capacity/traffic, policy engine) and "
                       "gate admission on the forecast.",
        "properties": {
            "mode": {
                "type": "string",
                "enum": ["off", "advisory", "required"],
                "default": "off",
                "description": "off = no forecast; advisory = forecast "
                               "surfaced in status/explain but never "
                               "blocks; required = a threshold breach "
                               "parks the rollout with an audited "
                               "preflight-rejected reason.",
            },
            "maxForecastSloRiskFraction": {
                "type": "number",
                "minimum": 0,
                "maximum": 1,
                "default": 0.2,
                "description": "Highest tolerable forecast SLO-risk "
                               "fraction (worst traffic class's "
                               "predicted peak shortfall over the "
                               "rollout).",
            },
            "maxForecastMakespanSeconds": {
                "type": "number",
                "minimum": 0,
                "default": 0,
                "description": "Highest tolerable forecast makespan "
                               "(upper confidence bound, seconds); 0 "
                               "means unbounded.",
            },
            "confidence": {
                "type": "number",
                "exclusiveMinimum": 0,
                "exclusiveMaximum": 1,
                "default": 0.9,
                "description": "Confidence level for the error-widened "
                               "forecast bounds; required mode gates on "
                               "the upper bound so a noisy model gates "
                               "earlier, never later.",
            },
        },
    }


def upgrade_policy_schema() -> dict[str, Any]:
    """The embeddable policy spec (DriverUpgradePolicySpec,
    upgrade_spec.go:27-49) with reference defaults: autoUpgrade=false,
    maxParallelUpgrades=1, maxUnavailable="25%"."""
    return {
        "type": "object",
        "description": "Rolling-upgrade policy for an accelerator runtime "
                       "DaemonSet.",
        "properties": {
            "autoUpgrade": {
                "type": "boolean",
                "default": False,
                "description": "Global switch for the automatic upgrade "
                               "feature; when false all other options are "
                               "ignored.",
            },
            "maxParallelUpgrades": {
                "type": "integer",
                "minimum": 0,
                "default": 1,
                "description": "How many nodes may upgrade concurrently; "
                               "0 means no limit.",
            },
            "maxUnavailable": _int_or_string(
                "Maximum number (ex: 5) or percentage (ex: \"10%\") of "
                "nodes that may be unavailable during the upgrade, "
                "cordoned/not-ready nodes included. Percentages round up.",
                default="25%"),
            "podDeletion": pod_deletion_schema(),
            "waitForCompletion": wait_for_completion_schema(),
            "drain": drain_schema(),
            "canary": canary_schema(),
            "rollback": rollback_schema(),
            "sharding": sharding_schema(),
            "predictor": predictor_schema(),
            "maintenanceWindow": maintenance_window_schema(),
            "capacityBudget": capacity_budget_schema(),
            "preflight": preflight_schema(),
            "policyHooks": policy_hooks_schema(),
            "artifactDAG": artifact_dag_schema(),
            "topologyMode": {
                "type": "string",
                "enum": ["flat", "slice"],
                "default": "flat",
                "description": "Upgrade unit: 'flat' treats nodes as "
                               "independent (reference semantics); 'slice' "
                               "upgrades whole ICI domains atomically.",
            },
            "maxUnavailableSlicesPerJob": {
                "type": "integer",
                "minimum": 1,
                "default": 1,
                "description": "With topologyMode=slice: per multislice "
                               "(DCN-spanning, JobSet-launched) job, at "
                               "most this many member slices may be "
                               "unavailable concurrently.",
            },
            "nodeSelector": {
                "type": "string",
                "default": "",
                "description": "Label selector scoping the managed node "
                               "pool; pushed down into the operator's "
                               "node LIST/watch so unmanaged pools cost "
                               "nothing. Empty selects every node.",
            },
        },
    }


def unified_policy_schema() -> dict[str, Any]:
    """UnifiedUpgradePolicySpec: per-accelerator policies in one document
    (BASELINE config #5)."""
    return {
        "type": "object",
        "description": "Per-accelerator upgrade policies under one "
                       "resource (mixed GPU+TPU clusters).",
        "properties": {
            "accelerators": {
                "type": "object",
                "description": "Accelerator name -> runtime + policy.",
                "additionalProperties": {
                    "type": "object",
                    "required": ["domain", "runtimeLabels"],
                    "properties": {
                        "driver": {
                            "type": "string",
                            "description": "Driver name used in node "
                                           "label/annotation keys; "
                                           "defaults to the entry name.",
                        },
                        "domain": {
                            "type": "string",
                            "description": "Label-key domain, e.g. "
                                           "google.com or nvidia.com.",
                        },
                        "runtimeLabels": {
                            "type": "object",
                            "additionalProperties": {"type": "string"},
                            "description": "Labels selecting the runtime "
                                           "DaemonSet.",
                        },
                        "namespace": {
                            "type": "string",
                            "default": "kube-system",
                            "description": "Namespace of the runtime "
                                           "DaemonSet.",
                        },
                        "policy": upgrade_policy_schema(),
                        "remediation": remediation_policy_schema(),
                    },
                },
            },
        },
    }


def build_crd(kind: str = "TPUUpgradePolicy",
              plural: Optional[str] = None,
              group: str = DEFAULT_GROUP,
              version: str = DEFAULT_VERSION,
              spec_schema: Optional[dict[str, Any]] = None,
              scope: str = "Cluster") -> dict[str, Any]:
    """A complete CustomResourceDefinition manifest embedding the policy
    schema under .spec — what controller-gen would emit for a consumer
    CRD that embeds DriverUpgradePolicySpec."""
    singular = kind.lower()
    plural = plural or (singular[:-1] + "ies" if singular.endswith("y")
                        else singular + "s")
    spec_schema = spec_schema or upgrade_policy_schema()
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{plural}.{group}"},
        "spec": {
            "group": group,
            "names": {
                "kind": kind,
                "listKind": f"{kind}List",
                "plural": plural,
                "singular": singular,
            },
            "scope": scope,
            "versions": [{
                "name": version,
                "served": True,
                "storage": True,
                "subresources": {"status": {}},
                "schema": {
                    "openAPIV3Schema": {
                        "type": "object",
                        "properties": {
                            "apiVersion": {"type": "string"},
                            "kind": {"type": "string"},
                            "metadata": {"type": "object"},
                            "spec": spec_schema,
                            "status": {
                                "type": "object",
                                "x-kubernetes-preserve-unknown-fields": True,
                            },
                        },
                    },
                },
            }],
        },
    }


# ---------------------------------------------------------------------------
# The API-server side: structural defaulting + validation
# ---------------------------------------------------------------------------

def apply_defaults(data: Optional[dict[str, Any]],
                   schema: dict[str, Any]) -> dict[str, Any]:
    """Fill in schema defaults the way the API server does at admission:
    a property's default applies when the key is absent; defaults inside
    a sub-object apply only once the sub-object itself exists (absent
    optional sub-objects stay absent, matching nil sub-specs in the
    reference)."""
    out = dict(data or {})
    for name, prop in schema.get("properties", {}).items():
        if name not in out:
            if "default" in prop:
                out[name] = prop["default"]
            continue
        if prop.get("type") == "object" and isinstance(out[name], dict):
            out[name] = apply_defaults(out[name], prop)
    extra = schema.get("additionalProperties")
    if isinstance(extra, dict) and extra.get("type") == "object":
        for name, value in out.items():
            if name not in schema.get("properties", {}) \
                    and isinstance(value, dict):
                out[name] = apply_defaults(value, extra)
    return out


def validate_against_schema(data: Any, schema: dict[str, Any],
                            path: str = "spec") -> None:
    """Structural validation with the subset of OpenAPI the policy schemas
    use: type, minimum, enum, required, additionalProperties,
    x-kubernetes-int-or-string. Raises PolicyValidationError with the
    offending path."""
    if schema.get("x-kubernetes-int-or-string"):
        if not isinstance(data, (int, str)) or isinstance(data, bool):
            raise PolicyValidationError(
                f"{path}: expected integer or string, got "
                f"{type(data).__name__}")
        return
    expected = schema.get("type")
    if expected == "object":
        if not isinstance(data, dict):
            raise PolicyValidationError(
                f"{path}: expected object, got {type(data).__name__}")
        for req in schema.get("required", []):
            if req not in data:
                raise PolicyValidationError(f"{path}.{req}: required")
        props = schema.get("properties", {})
        extra = schema.get("additionalProperties")
        for key, value in data.items():
            if key in props:
                validate_against_schema(value, props[key], f"{path}.{key}")
            elif isinstance(extra, dict):
                validate_against_schema(value, extra, f"{path}.{key}")
            # unknown fields with no additionalProperties schema are
            # pruned by the server, not rejected; accept them here too
        return
    if expected == "array":
        if not isinstance(data, list):
            raise PolicyValidationError(
                f"{path}: expected array, got {type(data).__name__}")
        items = schema.get("items")
        if isinstance(items, dict):
            for index, item in enumerate(data):
                validate_against_schema(item, items,
                                        f"{path}[{index}]")
        return
    if expected == "integer":
        if not isinstance(data, int) or isinstance(data, bool):
            raise PolicyValidationError(
                f"{path}: expected integer, got {type(data).__name__}")
    elif expected == "boolean":
        if not isinstance(data, bool):
            raise PolicyValidationError(
                f"{path}: expected boolean, got {type(data).__name__}")
    elif expected == "string":
        if not isinstance(data, str):
            raise PolicyValidationError(
                f"{path}: expected string, got {type(data).__name__}")
    if "minimum" in schema and isinstance(data, (int, float)) \
            and not isinstance(data, bool):
        if data < schema["minimum"]:
            raise PolicyValidationError(
                f"{path}: {data} is less than minimum {schema['minimum']}")
    if "enum" in schema and data not in schema["enum"]:
        raise PolicyValidationError(
            f"{path}: {data!r} not one of {schema['enum']}")


def render_yaml(obj: dict[str, Any]) -> str:
    """Render a manifest as YAML (JSON fallback when pyyaml is absent —
    JSON is valid YAML)."""
    try:
        import yaml
    except ImportError:  # pragma: no cover
        return json.dumps(obj, indent=2, sort_keys=False) + "\n"
    return yaml.safe_dump(obj, sort_keys=False, default_flow_style=False)


def _main() -> None:  # pragma: no cover - exercised via test subprocess
    import os

    out_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "examples", "crd")
    os.makedirs(out_dir, exist_ok=True)
    manifests = {
        "tpuupgradepolicy.yaml": build_crd(),
        "unifiedupgradepolicy.yaml": build_crd(
            kind="UnifiedUpgradePolicy",
            spec_schema=unified_policy_schema()),
        "tpufederationpolicy.yaml": build_crd(
            kind="TPUFederationPolicy",
            spec_schema=federation_policy_schema()),
    }
    for name, manifest in manifests.items():
        path = os.path.join(out_dir, name)
        with open(path, "w") as f:
            f.write(render_yaml(manifest))
        print(f"wrote {path}")


if __name__ == "__main__":
    _main()
