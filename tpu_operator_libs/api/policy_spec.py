"""Declarative policy-engine specs: per-hook programs + artifact DAGs.

Two CRD-embeddable surfaces (ISSUE 15 / the ROADMAP's declarative-
policy-engine item):

- :class:`PolicyHooksSpec` — small CEL-style programs attached to the
  named hook points of :mod:`tpu_operator_libs.policy.hooks`, each with
  its own step/wall budget. Programs are parsed at validation time, so
  a malformed policy is rejected at admission instead of discovered
  mid-pass; evaluation is sandboxed (policy/expr.py), and a failing or
  over-budget program parks its node with an audited reason — it can
  never wedge or crash a reconcile pass.
- :class:`ArtifactDAGSpec` — a dependency-ordered multi-artifact
  upgrade (libtpu + device plugin + network driver + node OS image,
  ...): per-artifact DaemonSets advance through ONE shared cordon/
  drain cycle per node in DAG order, with crash-ordered per-artifact
  revision stamps so partial progress resumes from cluster state alone
  (policy/dag.py). Validation rejects cycles, unknown dependencies and
  duplicate artifacts structurally.

Both ride :class:`~tpu_operator_libs.api.upgrade_policy.
UpgradePolicySpec` (``policyHooks`` / ``artifactDAG`` JSON keys) so the
whole scenario ships as CRD data — no operator-code changes.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any

from tpu_operator_libs.api.upgrade_policy import PolicyValidationError
from tpu_operator_libs.policy.expr import (
    DEFAULT_MAX_MILLIS,
    DEFAULT_MAX_STEPS,
    MAX_MILLIS_CEILING,
    MAX_STEPS_CEILING,
    PolicyExprError,
    parse,
)


@dataclass
class HookProgramSpec:
    """One declarative program bound to one named hook point."""

    #: Hook point name ("planner.admission", "eviction.filter", ...);
    #: must exist in the hook catalog (policy/hooks.py).
    hook: str = ""
    #: Hook point contract version; only "v1" exists today. Versioned
    #: so a future env change ships as v2 while v1 programs keep their
    #: original contract.
    version: str = "v1"
    #: The CEL-style program (policy/expr.py). Admission hooks must
    #: return a boolean.
    program: str = ""
    #: Per-evaluation step budget (tree-node + container-cost units).
    max_steps: int = DEFAULT_MAX_STEPS
    #: Per-evaluation wall budget in milliseconds.
    max_millis: float = DEFAULT_MAX_MILLIS

    def validate(self) -> None:
        # local import: hooks.py imports this module's sibling types
        from tpu_operator_libs.policy.hooks import HOOK_POINTS

        if not self.hook:
            raise PolicyValidationError("policyHooks[].hook is required")
        point = HOOK_POINTS.get(self.hook)
        if point is None:
            raise PolicyValidationError(
                f"policyHooks[].hook {self.hook!r} is not a known hook "
                f"point (known: {', '.join(sorted(HOOK_POINTS))})")
        if self.version != point.version:
            raise PolicyValidationError(
                f"policyHooks[{self.hook}].version {self.version!r} is "
                f"not supported (hook point is {point.version})")
        if isinstance(self.max_steps, bool) \
                or not isinstance(self.max_steps, int) \
                or not 1 <= self.max_steps <= MAX_STEPS_CEILING:
            raise PolicyValidationError(
                f"policyHooks[{self.hook}].maxSteps must be an integer "
                f"in [1, {MAX_STEPS_CEILING}]")
        if not isinstance(self.max_millis, (int, float)) \
                or isinstance(self.max_millis, bool) \
                or not 0 < self.max_millis <= MAX_MILLIS_CEILING:
            raise PolicyValidationError(
                f"policyHooks[{self.hook}].maxMillis must be in "
                f"(0, {MAX_MILLIS_CEILING}]")
        try:
            program = parse(self.program)
        except PolicyExprError as exc:
            raise PolicyValidationError(
                f"policyHooks[{self.hook}].program does not parse: "
                f"{exc}") from None
        unknown = program.identifiers() - point.env
        if unknown:
            raise PolicyValidationError(
                f"policyHooks[{self.hook}].program references unknown "
                f"identifier(s) {sorted(unknown)}; the {self.hook} "
                f"environment provides {sorted(point.env)}")

    def to_dict(self) -> dict[str, Any]:
        return {"hook": self.hook,
                "version": self.version,
                "program": self.program,
                "maxSteps": self.max_steps,
                "maxMillis": self.max_millis}

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "HookProgramSpec":
        return cls(hook=data.get("hook", ""),
                   version=data.get("version", "v1"),
                   program=data.get("program", ""),
                   max_steps=data.get("maxSteps", DEFAULT_MAX_STEPS),
                   max_millis=data.get("maxMillis", DEFAULT_MAX_MILLIS))

    def deep_copy(self) -> "HookProgramSpec":
        return copy.deepcopy(self)


@dataclass
class PolicyHooksSpec:
    """Declarative hook programs shipped in the CRD."""

    #: Master switch; when False no program is evaluated.
    enable: bool = True
    hooks: list[HookProgramSpec] = field(default_factory=list)

    def validate(self) -> None:
        seen: set[str] = set()
        for spec in self.hooks:
            spec.validate()
            if spec.hook in seen:
                raise PolicyValidationError(
                    f"policyHooks: duplicate program for hook "
                    f"{spec.hook!r} (one program per hook point; "
                    f"compose with '&&' instead)")
            seen.add(spec.hook)

    def to_dict(self) -> dict[str, Any]:
        return {"enable": self.enable,
                "hooks": [spec.to_dict() for spec in self.hooks]}

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "PolicyHooksSpec":
        return cls(enable=data.get("enable", True),
                   hooks=[HookProgramSpec.from_dict(item)
                          for item in data.get("hooks", [])])

    def deep_copy(self) -> "PolicyHooksSpec":
        return copy.deepcopy(self)


@dataclass
class ArtifactSpec:
    """One artifact (DaemonSet-delivered node component) in the DAG."""

    #: Artifact name — also the per-node revision-stamp key suffix, so
    #: it must be label-value shaped.
    name: str = ""
    #: Labels selecting the artifact's DaemonSet (and its pods). The
    #: artifact whose labels equal the operator's managed runtime
    #: labels is the PRIMARY artifact — driven by the state machine's
    #: own pod-restart arc; every other artifact is advanced by the
    #: DAG coordinator inside the node's validation window.
    runtime_labels: dict[str, str] = field(default_factory=dict)
    #: Namespace of the artifact's DaemonSet ("" = the reconcile
    #: namespace).
    namespace: str = ""
    #: Names of artifacts that must be stamped at their target revision
    #: on a node before this artifact may advance there.
    depends_on: list[str] = field(default_factory=list)

    def validate(self) -> None:
        if not self.name or not all(
                ch.isalnum() or ch == "-" for ch in self.name) \
                or self.name.startswith("-") or self.name.endswith("-"):
            raise PolicyValidationError(
                f"artifactDAG.artifacts[].name {self.name!r} must be a "
                f"DNS-label (alphanumerics and dashes)")
        if not self.runtime_labels:
            raise PolicyValidationError(
                f"artifactDAG.artifacts[{self.name}].runtimeLabels "
                f"must select the artifact's DaemonSet")
        if self.name in self.depends_on:
            raise PolicyValidationError(
                f"artifactDAG.artifacts[{self.name}] depends on itself")

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {"name": self.name,
                               "runtimeLabels": dict(self.runtime_labels)}
        if self.namespace:
            out["namespace"] = self.namespace
        if self.depends_on:
            out["dependsOn"] = list(self.depends_on)
        return out

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "ArtifactSpec":
        return cls(name=data.get("name", ""),
                   runtime_labels=dict(data.get("runtimeLabels", {})),
                   namespace=data.get("namespace", ""),
                   depends_on=list(data.get("dependsOn", [])))

    def deep_copy(self) -> "ArtifactSpec":
        return copy.deepcopy(self)


@dataclass
class ArtifactDAGSpec:
    """Dependency-ordered multi-artifact upgrade, expressed as data."""

    #: Master switch; when False only the primary runtime is managed
    #: (reference semantics, bit for bit).
    enable: bool = False
    artifacts: list[ArtifactSpec] = field(default_factory=list)
    #: Crash-looping pods observed at an artifact's target revision on
    #: this many distinct nodes quarantine that revision and roll the
    #: artifact (plus its un-started dependent suffix) back.
    failure_threshold: int = 1

    def validate(self) -> None:
        if isinstance(self.failure_threshold, bool) \
                or self.failure_threshold < 1:
            raise PolicyValidationError(
                "artifactDAG.failureThreshold must be >= 1")
        names: set[str] = set()
        for artifact in self.artifacts:
            artifact.validate()
            if artifact.name in names:
                raise PolicyValidationError(
                    f"artifactDAG: duplicate artifact {artifact.name!r}")
            names.add(artifact.name)
        for artifact in self.artifacts:
            unknown = set(artifact.depends_on) - names
            if unknown:
                raise PolicyValidationError(
                    f"artifactDAG.artifacts[{artifact.name}] depends on "
                    f"unknown artifact(s) {sorted(unknown)}")
        self.topo_order()  # raises on cycles

    def topo_order(self) -> "list[ArtifactSpec]":
        """Deterministic topological order (Kahn's algorithm, ties by
        name). Raises :class:`PolicyValidationError` on a cycle —
        validation's cycle rejection and the coordinator's walk share
        this one implementation."""
        by_name = {a.name: a for a in self.artifacts}
        indegree = {a.name: len(set(a.depends_on)) for a in self.artifacts}
        dependents: dict[str, list[str]] = {a.name: [] for a in self.artifacts}
        for artifact in self.artifacts:
            for dep in set(artifact.depends_on):
                dependents.setdefault(dep, []).append(artifact.name)
        ready = sorted(name for name, deg in indegree.items() if deg == 0)
        order: list[ArtifactSpec] = []
        while ready:
            name = ready.pop(0)
            order.append(by_name[name])
            grew = False
            for dependent in dependents.get(name, ()):
                indegree[dependent] -= 1
                if indegree[dependent] == 0:
                    ready.append(dependent)
                    grew = True
            if grew:
                ready.sort()
        if len(order) != len(self.artifacts):
            cyclic = sorted(name for name, deg in indegree.items()
                            if deg > 0)
            raise PolicyValidationError(
                f"artifactDAG has a dependency cycle through "
                f"{cyclic}")
        return order

    def dependents_of(self, name: str) -> "list[str]":
        """Transitive dependents of ``name`` (the suffix a quarantine
        contains), deterministic order."""
        direct: dict[str, list[str]] = {}
        for artifact in self.artifacts:
            for dep in artifact.depends_on:
                direct.setdefault(dep, []).append(artifact.name)
        out: list[str] = []
        frontier = list(direct.get(name, ()))
        seen: set[str] = set()
        while frontier:
            current = frontier.pop(0)
            if current in seen:
                continue
            seen.add(current)
            out.append(current)
            frontier.extend(direct.get(current, ()))
        return sorted(out)

    def to_dict(self) -> dict[str, Any]:
        return {"enable": self.enable,
                "failureThreshold": self.failure_threshold,
                "artifacts": [a.to_dict() for a in self.artifacts]}

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "ArtifactDAGSpec":
        return cls(enable=data.get("enable", False),
                   failure_threshold=data.get("failureThreshold", 1),
                   artifacts=[ArtifactSpec.from_dict(item)
                              for item in data.get("artifacts", [])])

    def deep_copy(self) -> "ArtifactDAGSpec":
        return copy.deepcopy(self)
