"""Unified multi-accelerator upgrade policy (BASELINE config #5).

The reference is single-driver-per-process by construction (the global
``DriverName``, util.go:87-95). Because this build scopes keys per
:class:`~tpu_operator_libs.consts.UpgradeKeys` instance, one operator
process can run one state machine per accelerator runtime — GPU driver and
libtpu side by side in a mixed cluster — under a single CRD-embeddable
policy document:

.. code-block:: yaml

    accelerators:
      tpu:
        domain: google.com
        driver: libtpu
        runtimeLabels: {app: libtpu}
        policy: {autoUpgrade: true, maxUnavailable: "25%",
                 topologyMode: slice, drain: {enable: true}}
      gpu:
        domain: nvidia.com
        driver: gpu
        runtimeLabels: {app: nvidia-driver}
        policy: {autoUpgrade: true, maxParallelUpgrades: 1,
                 drain: {enable: true}}
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Any, Callable, Optional

if TYPE_CHECKING:  # pragma: no cover - types only
    from tpu_operator_libs.k8s.client import K8sClient

from tpu_operator_libs.api.remediation_policy import RemediationPolicySpec
from tpu_operator_libs.api.upgrade_policy import (
    PolicyValidationError,
    UpgradePolicySpec,
)
from tpu_operator_libs.consts import RemediationKeys, UpgradeKeys


@dataclass
class AcceleratorSpec:
    """One accelerator runtime entry in the unified policy."""

    name: str
    driver: str
    domain: str
    runtime_labels: dict[str, str] = field(default_factory=dict)
    namespace: str = "kube-system"
    policy: UpgradePolicySpec = field(default_factory=UpgradePolicySpec)
    # Optional unplanned-fault policy; None disables auto-remediation
    # for this accelerator (tpu_operator_libs.remediation).
    remediation: Optional[RemediationPolicySpec] = None

    @property
    def keys(self) -> UpgradeKeys:
        return UpgradeKeys(driver=self.driver, domain=self.domain)

    @property
    def remediation_keys(self) -> RemediationKeys:
        return RemediationKeys(driver=self.driver, domain=self.domain)

    def validate(self) -> None:
        if not self.driver or not self.domain:
            raise PolicyValidationError(
                f"accelerator {self.name!r}: driver and domain are required")
        if not self.runtime_labels:
            raise PolicyValidationError(
                f"accelerator {self.name!r}: runtimeLabels must select the "
                f"runtime DaemonSet")
        self.policy.validate()
        if self.remediation is not None:
            self.remediation.validate()

    def to_dict(self) -> dict[str, Any]:
        out = {"driver": self.driver, "domain": self.domain,
               "runtimeLabels": dict(self.runtime_labels),
               "namespace": self.namespace,
               "policy": self.policy.to_dict()}
        if self.remediation is not None:
            out["remediation"] = self.remediation.to_dict()
        return out

    @classmethod
    def from_dict(cls, name: str, data: dict[str, Any]) -> "AcceleratorSpec":
        spec = cls(
            name=name,
            driver=data.get("driver", name),
            domain=data.get("domain", ""),
            runtime_labels=dict(data.get("runtimeLabels", {})),
            namespace=data.get("namespace", "kube-system"),
            policy=UpgradePolicySpec.from_dict(data.get("policy", {})))
        if data.get("remediation") is not None:
            spec.remediation = RemediationPolicySpec.from_dict(
                data["remediation"])
        return spec


@dataclass
class UnifiedUpgradePolicySpec:
    """Per-accelerator upgrade policies under one document."""

    accelerators: dict[str, AcceleratorSpec] = field(default_factory=dict)

    def validate(self) -> None:
        seen: dict[tuple[str, str], str] = {}
        for name, spec in self.accelerators.items():
            spec.validate()
            key = (spec.domain, spec.driver)
            if key in seen:
                raise PolicyValidationError(
                    f"accelerators {seen[key]!r} and {name!r} share the "
                    f"same key namespace {spec.domain}/{spec.driver}")
            seen[key] = name

    def to_dict(self) -> dict[str, Any]:
        return {"accelerators": {name: spec.to_dict()
                                 for name, spec in self.accelerators.items()}}

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "UnifiedUpgradePolicySpec":
        return cls(accelerators={
            name: AcceleratorSpec.from_dict(name, spec)
            for name, spec in data.get("accelerators", {}).items()})


class MultiAcceleratorUpgradeManager:
    """One ClusterUpgradeStateManager per accelerator, one reconcile call.

    The downstream operator calls :meth:`reconcile` from its loop; each
    accelerator's state machine runs against its own label namespace, so a
    mixed GPU+TPU cluster upgrades both runtimes independently but under
    one policy document.
    """

    def __init__(self, client: "K8sClient",
                 unified_policy: UnifiedUpgradePolicySpec,
                 manager_factory: Optional[Callable[..., Any]] = None,
                 remediation_factory: Optional[Callable[..., Any]] = None,
                 remediation_kwargs: Optional[dict[str, Any]] = None,
                 **manager_kwargs: Any) -> None:
        from tpu_operator_libs.remediation.state_machine import (
            NodeRemediationManager,
        )
        from tpu_operator_libs.upgrade.state_manager import (
            ClusterUpgradeStateManager,
        )

        unified_policy.validate()
        self.policy = unified_policy
        factory = manager_factory or ClusterUpgradeStateManager
        self.managers: dict[str, ClusterUpgradeStateManager] = {
            name: factory(client, spec.keys, **manager_kwargs)
            for name, spec in unified_policy.accelerators.items()}
        # One remediation machine per accelerator that configures one —
        # keyed to the SAME driver/domain namespace as its upgrade
        # machine, so the two coordinate (upgrade-in-progress guard,
        # skip-label parking) per accelerator.
        rem_factory = remediation_factory or NodeRemediationManager
        self.remediation_managers: dict[str, NodeRemediationManager] = {
            name: rem_factory(client, spec.remediation_keys,
                              upgrade_keys=spec.keys,
                              **(remediation_kwargs or {}))
            for name, spec in unified_policy.accelerators.items()
            if spec.remediation is not None}

    def reconcile(self) -> dict[str, Optional[Exception]]:
        """Build + apply state for every accelerator — the upgrade
        machine and (when configured) the remediation machine. Failures
        are per-accelerator: one runtime's error does not block the
        others. Returns accelerator -> error (None on success)."""
        results: dict[str, Optional[Exception]] = {}
        for name, spec in self.policy.accelerators.items():
            mgr = self.managers[name]
            try:
                state = mgr.build_state(spec.namespace, spec.runtime_labels)
                mgr.apply_state(state, spec.policy)
                results[name] = None
            except Exception as exc:  # noqa: BLE001 — per-accelerator
                results[name] = exc
            rem = self.remediation_managers.get(name)
            if rem is None:
                continue
            try:
                snapshot = rem.build_state(spec.namespace,
                                           spec.runtime_labels)
                rem.apply_state(snapshot, spec.remediation)
            except Exception as exc:  # noqa: BLE001 — per-accelerator
                # remediation trouble must not mask an upgrade success,
                # but an upgrade error stays the headline
                if results[name] is None:
                    results[name] = exc
        return results

    def cluster_status(self) -> dict[str, dict]:
        """Fresh CRD-embeddable status block per accelerator (the unified
        analogue of ClusterUpgradeStateManager.cluster_status). A runtime
        whose snapshot is temporarily unbuildable reports an ``error``
        entry instead of hiding the accelerator."""
        out: dict[str, dict] = {}
        for name, spec in self.policy.accelerators.items():
            mgr = self.managers[name]
            try:
                state = mgr.build_state(spec.namespace, spec.runtime_labels)
                out[name] = mgr.cluster_status(state)
            except Exception as exc:  # noqa: BLE001 — per-accelerator
                out[name] = {"error": str(exc)}
            rem = self.remediation_managers.get(name)
            if rem is None:
                continue
            try:
                snapshot = rem.build_state(spec.namespace,
                                           spec.runtime_labels)
                out[name]["remediation"] = rem.remediation_status(snapshot)
            except Exception as exc:  # noqa: BLE001 — per-accelerator
                out[name]["remediation"] = {"error": str(exc)}
        return out
