"""Auto-remediation policy — the declarative surface of the
unplanned-fault state machine.

No reference counterpart: ``k8s-operator-libs`` only manages *planned*
disruptions (driver rollouts); a wedged node simply stalls there until a
human intervenes. TPU fleets cannot afford that — a single NotReady host
idles its whole ICI slice — so this build adds a remediation machine
(:mod:`tpu_operator_libs.remediation`) and this spec configures it.
Shape and conventions mirror :mod:`tpu_operator_libs.api.upgrade_policy`:
plain dataclasses, camelCase JSON keys, explicit ``to_dict`` /
``from_dict`` / ``validate`` / ``deep_copy``.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass
from typing import Any, Optional

from tpu_operator_libs.api.upgrade_policy import (
    DrainSpec,
    IntOrString,
    PolicyValidationError,
    scaled_value_from_int_or_percent,
)


@dataclass
class WedgeDetectionSpec:
    """Thresholds of the built-in wedge detectors
    (:func:`tpu_operator_libs.remediation.detectors.default_detector_chain`).
    """

    # Seconds a node may report NotReady before it counts as wedged
    # (kubelet restarts and brief network blips must not trigger
    # quarantine).
    not_ready_grace_seconds: int = 300
    # Restart count beyond which a not-ready runtime container is a
    # crash loop (same threshold the upgrade machine uses for
    # pod-restart failure, upgrade_state.go:966-978).
    pod_restart_threshold: int = 10
    # Seconds a runtime pod may sit Terminating before it counts as
    # stuck (a wedged TPU driver commonly blocks container teardown).
    terminating_stuck_seconds: int = 600
    # Node condition types (node-problem-detector style) whose status
    # != "True" marks the node wedged immediately.
    unhealthy_condition_types: tuple[str, ...] = ("TpuHealthy",)

    def validate(self) -> None:
        if self.not_ready_grace_seconds < 0:
            raise PolicyValidationError(
                "detection.notReadyGraceSeconds must be >= 0")
        if self.pod_restart_threshold < 1:
            raise PolicyValidationError(
                "detection.podRestartThreshold must be >= 1")
        if self.terminating_stuck_seconds < 0:
            raise PolicyValidationError(
                "detection.terminatingStuckSeconds must be >= 0")

    def to_dict(self) -> dict[str, Any]:
        return {
            "notReadyGraceSeconds": self.not_ready_grace_seconds,
            "podRestartThreshold": self.pod_restart_threshold,
            "terminatingStuckSeconds": self.terminating_stuck_seconds,
            "unhealthyConditionTypes": list(self.unhealthy_condition_types),
        }

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "WedgeDetectionSpec":
        return cls(
            not_ready_grace_seconds=data.get("notReadyGraceSeconds", 300),
            pod_restart_threshold=data.get("podRestartThreshold", 10),
            terminating_stuck_seconds=data.get(
                "terminatingStuckSeconds", 600),
            unhealthy_condition_types=tuple(data.get(
                "unhealthyConditionTypes", ("TpuHealthy",))))

    def deep_copy(self) -> "WedgeDetectionSpec":
        return copy.deepcopy(self)


@dataclass
class ReconfigurationPolicySpec:
    """Degraded-slice topology reconfiguration (the Ironwood OCS
    analogue): when remediation condemns a node, its ICI slice is
    remapped onto a spare host from the spare pool — or, when no spare
    exists, admitted as a documented degraded shape — instead of parking
    the whole slice on the node's repair. Consumed by
    :class:`~tpu_operator_libs.topology.reconfigurer.SliceReconfigurer`
    through the remediation machine's ``reconfigure-required`` arc.
    """

    # Master switch; when False condemned nodes park in
    # remediation-failed with their slice down (pre-reconfiguration
    # behavior).
    enable: bool = False
    # Seconds a reserved spare may take to reach the target revision
    # (upgrade-done, pod ready) before the reservation is abandoned and
    # the slice falls back to a degraded admission; 0 = wait forever.
    spare_provision_timeout_seconds: int = 1800
    # Seconds a freshly remapped slice holds its multislice sticky-down
    # membership (the job's replacement pods are still Pending right
    # after the remap; without the hold the planner could take a second
    # member slice in that window).
    settle_seconds: int = 120
    # Permit admitting a documented degraded shape when no spare is
    # available; when False the condemned node waits in
    # reconfigure-required until a spare appears.
    allow_degraded: bool = True
    # Let the remediation machine take over nodes parked in the upgrade
    # machine's terminal ``upgrade-failed`` state whose wedge signal
    # persists past its grace window. A node that failed its upgrade
    # because the hardware died can only be recovered (or condemned and
    # routed around) by the remediation ladder — without the takeover it
    # wedges both machines forever. The upgrade machine holds its own
    # FAILED recovery while the node carries the remediation skip label,
    # so the two machines never drive the node concurrently.
    take_over_failed_upgrades: bool = True

    def validate(self) -> None:
        if self.spare_provision_timeout_seconds < 0:
            raise PolicyValidationError(
                "reconfiguration.spareProvisionTimeoutSeconds must be "
                ">= 0")
        if self.settle_seconds < 0:
            raise PolicyValidationError(
                "reconfiguration.settleSeconds must be >= 0")

    def to_dict(self) -> dict[str, Any]:
        return {
            "enable": self.enable,
            "spareProvisionTimeoutSeconds":
                self.spare_provision_timeout_seconds,
            "settleSeconds": self.settle_seconds,
            "allowDegraded": self.allow_degraded,
            "takeOverFailedUpgrades": self.take_over_failed_upgrades,
        }

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "ReconfigurationPolicySpec":
        return cls(
            enable=data.get("enable", False),
            spare_provision_timeout_seconds=data.get(
                "spareProvisionTimeoutSeconds", 1800),
            settle_seconds=data.get("settleSeconds", 120),
            allow_degraded=data.get("allowDegraded", True),
            take_over_failed_upgrades=data.get(
                "takeOverFailedUpgrades", True))

    def deep_copy(self) -> "ReconfigurationPolicySpec":
        return copy.deepcopy(self)


@dataclass
class PrecursorPolicySpec:
    """Predictive condemn-before-fail (the Ironwood proactive-routing
    analogue): an online :class:`~tpu_operator_libs.health.precursor.
    FailurePrecursorModel` watches per-node hardware-health counter
    rates (ECC / link-flap / thermal) and, when a node's EWMA rate has
    stayed over threshold for ``minObservations`` consecutive samples,
    condemns it AT RISK — spare reserved, slice remapped, node drained
    as a planned low-cost candidate while it still serves. Requires
    ``reconfiguration.enable`` (the arc routes through the
    SliceReconfigurer).
    """

    # Master switch; when False the machine stays purely reactive.
    enable: bool = False
    # Fleet-wide at-risk condemnation budget: the count of nodes
    # carrying the at-risk stamp (in-flight or parked) may never exceed
    # this fraction/count of the fleet — a noisy signal storm can slow
    # remaps down but can never mass-drain the fleet.
    max_at_risk: IntOrString = "10%"
    # Events/hour a per-node EWMA rate must reach before the node is a
    # condemnation candidate.
    rate_threshold_per_hour: float = 6.0
    # Consecutive over-threshold observations required before the
    # verdict fires (a single noisy sample can never condemn a node);
    # also the stand-down streak an in-flight arc needs to abort.
    min_observations: int = 3
    # EWMA smoothing factor in (0, 1] (same semantics as the duration
    # predictor's).
    smoothing: float = 0.5

    def validate(self) -> None:
        if scaled_value_from_int_or_percent(self.max_at_risk, 100) < 0:
            raise PolicyValidationError(
                "precursor.maxAtRisk must be >= 0")
        if self.rate_threshold_per_hour <= 0:
            raise PolicyValidationError(
                "precursor.rateThresholdPerHour must be > 0")
        if self.min_observations < 1:
            raise PolicyValidationError(
                "precursor.minObservations must be >= 1")
        if not 0.0 < self.smoothing <= 1.0:
            raise PolicyValidationError(
                "precursor.smoothing must be in (0, 1]")

    def to_dict(self) -> dict[str, Any]:
        return {
            "enable": self.enable,
            "maxAtRisk": self.max_at_risk,
            "rateThresholdPerHour": self.rate_threshold_per_hour,
            "minObservations": self.min_observations,
            "smoothing": self.smoothing,
        }

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "PrecursorPolicySpec":
        return cls(
            enable=data.get("enable", False),
            max_at_risk=data.get("maxAtRisk", "10%"),
            rate_threshold_per_hour=data.get("rateThresholdPerHour", 6.0),
            min_observations=data.get("minObservations", 3),
            smoothing=data.get("smoothing", 0.5))

    def deep_copy(self) -> "PrecursorPolicySpec":
        return copy.deepcopy(self)


@dataclass
class RemediationPolicySpec:
    """Top-level auto-remediation policy.

    The escalation ladder: each recovery attempt ``n`` (1-based, stamped
    durably in a node annotation) runs the runtime-restart rung while
    ``n <= restartAttempts``, then the reboot rung; after
    ``maxAttempts`` dispatched attempts the node parks in
    ``remediation-failed`` for manual repair.
    """

    # Global switch; when False apply_state is a no-op (mirrors the
    # upgrade policy's autoUpgrade gate, upgrade_state.go:372-375).
    enable: bool = False
    # How many nodes may be actively remediated concurrently; 0 = no
    # limit.
    max_concurrent: int = 1
    # Availability budget for remediating nodes that are still serving
    # (Ready + schedulable, e.g. a crash-looping runtime pod on a live
    # node): such a node is only quarantined while fleet unavailability
    # stays under this cap. Nodes already unavailable (NotReady or
    # cordoned) are exempt — quarantining a dead node costs nothing.
    max_unavailable: Optional[IntOrString] = "10%"
    # Recovery-attempt ladder (see class docstring).
    restart_attempts: int = 1
    max_attempts: int = 3
    # Seconds a dispatched restart/reboot may run before the attempt is
    # written off and the node re-enters the wedged bucket.
    action_timeout_seconds: int = 600
    # Seconds the wedge signal must stay clear during revalidation
    # before the node returns to service.
    settle_seconds: int = 60
    # Seconds revalidation may churn (signal flapping) before the
    # attempt is written off.
    revalidate_timeout_seconds: int = 900
    # Workload eviction before recovery actions; None disables the
    # drain stage (the cordon still protects new workloads).
    drain: Optional[DrainSpec] = None
    detection: WedgeDetectionSpec = None  # type: ignore[assignment]
    # Degraded-slice topology reconfiguration after give-up; None
    # disables it (condemned nodes park with their slice down).
    reconfiguration: Optional[ReconfigurationPolicySpec] = None
    # Predictive condemn-before-fail; None disables it (reactive-only).
    precursor: Optional[PrecursorPolicySpec] = None

    def __post_init__(self) -> None:
        if self.detection is None:
            self.detection = WedgeDetectionSpec()

    def validate(self) -> None:
        if self.max_concurrent < 0:
            raise PolicyValidationError("maxConcurrent must be >= 0")
        if self.max_unavailable is not None:
            if scaled_value_from_int_or_percent(
                    self.max_unavailable, 100) < 0:
                raise PolicyValidationError("maxUnavailable must be >= 0")
        if self.restart_attempts < 0:
            raise PolicyValidationError("restartAttempts must be >= 0")
        if self.max_attempts < 1:
            raise PolicyValidationError("maxAttempts must be >= 1")
        if self.restart_attempts > self.max_attempts:
            raise PolicyValidationError(
                "restartAttempts must be <= maxAttempts (the ladder "
                "cannot have more restart rungs than total attempts)")
        for name, value in (
                ("actionTimeoutSeconds", self.action_timeout_seconds),
                ("settleSeconds", self.settle_seconds),
                ("revalidateTimeoutSeconds",
                 self.revalidate_timeout_seconds)):
            if value < 0:
                raise PolicyValidationError(f"{name} must be >= 0")
        if self.drain is not None:
            self.drain.validate()
        self.detection.validate()
        if self.reconfiguration is not None:
            self.reconfiguration.validate()
        if self.precursor is not None:
            self.precursor.validate()
            if self.precursor.enable and (
                    self.reconfiguration is None
                    or not self.reconfiguration.enable):
                raise PolicyValidationError(
                    "precursor.enable requires reconfiguration.enable "
                    "(the at-risk arc routes through the "
                    "SliceReconfigurer)")

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "enable": self.enable,
            "maxConcurrent": self.max_concurrent,
            "maxUnavailable": self.max_unavailable,
            "restartAttempts": self.restart_attempts,
            "maxAttempts": self.max_attempts,
            "actionTimeoutSeconds": self.action_timeout_seconds,
            "settleSeconds": self.settle_seconds,
            "revalidateTimeoutSeconds": self.revalidate_timeout_seconds,
            "detection": self.detection.to_dict(),
        }
        if self.drain is not None:
            out["drain"] = self.drain.to_dict()
        if self.reconfiguration is not None:
            out["reconfiguration"] = self.reconfiguration.to_dict()
        if self.precursor is not None:
            out["precursor"] = self.precursor.to_dict()
        return out

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "RemediationPolicySpec":
        spec = cls(
            enable=data.get("enable", False),
            max_concurrent=data.get("maxConcurrent", 1),
            max_unavailable=data.get("maxUnavailable", "10%"),
            restart_attempts=data.get("restartAttempts", 1),
            max_attempts=data.get("maxAttempts", 3),
            action_timeout_seconds=data.get("actionTimeoutSeconds", 600),
            settle_seconds=data.get("settleSeconds", 60),
            revalidate_timeout_seconds=data.get(
                "revalidateTimeoutSeconds", 900))
        if data.get("drain") is not None:
            spec.drain = DrainSpec.from_dict(data["drain"])
        if data.get("detection") is not None:
            spec.detection = WedgeDetectionSpec.from_dict(data["detection"])
        if data.get("reconfiguration") is not None:
            spec.reconfiguration = ReconfigurationPolicySpec.from_dict(
                data["reconfiguration"])
        if data.get("precursor") is not None:
            spec.precursor = PrecursorPolicySpec.from_dict(
                data["precursor"])
        return spec

    def deep_copy(self) -> "RemediationPolicySpec":
        return copy.deepcopy(self)
