"""Durable-state fsck: registry, auditor, and self-healing janitor.

The operator's only store is cluster metadata — node/DaemonSet labels
and annotations — and eighteen PRs of crash-ordered stamps assume the
operator itself wrote them. This package defends that store against
everything else that writes it (kubectl-editing humans, mutating
webhooks, stale operator builds mid-self-upgrade, torn multi-owner
writes): the :class:`DurableKeyRegistry` catalogs every owned key with
its codec, schema version, and repair action; the
:class:`StateAuditor` classifies live stamps (garbage / orphaned /
conflicting / version-skewed) before the state machines read them; the
:class:`Janitor` repairs findings through audited, crash-ordered,
idempotent patches — and parks what it cannot prove (quarantine, never
guess). See ``docs/durable-state.md`` for the full key reference.
"""

from tpu_operator_libs.fsck.auditor import (
    CLASSIFICATIONS,
    CONFLICTING,
    GARBAGE,
    ORPHANED,
    VERSION_SKEWED,
    Finding,
    StateAuditor,
)
from tpu_operator_libs.fsck.janitor import Janitor, RepairRecord
from tpu_operator_libs.fsck.registry import (
    REPAIR_CONVERT,
    REPAIR_DROP,
    REPAIR_NORMALIZE,
    REPAIR_PRESERVE,
    REPAIR_QUARANTINE,
    REPAIR_SWEEP,
    AuditContext,
    DurableKeyRegistry,
    DurableKeySpec,
    default_registry,
    fsck_quarantine_annotation,
)

__all__ = [
    "AuditContext",
    "CLASSIFICATIONS",
    "CONFLICTING",
    "DurableKeyRegistry",
    "DurableKeySpec",
    "Finding",
    "GARBAGE",
    "Janitor",
    "ORPHANED",
    "REPAIR_CONVERT",
    "REPAIR_DROP",
    "REPAIR_NORMALIZE",
    "REPAIR_PRESERVE",
    "REPAIR_QUARANTINE",
    "REPAIR_SWEEP",
    "RepairRecord",
    "StateAuditor",
    "VERSION_SKEWED",
    "default_registry",
    "fsck_quarantine_annotation",
]
