"""StateAuditor: classify every owned durable stamp against the
:class:`~tpu_operator_libs.fsck.registry.DurableKeyRegistry`.

The auditor is the fsck *read* half: it runs inside the reconcile loop
(before the state machines act, so a corrupted stamp is caught before
it can drive an admission/abort/rollback decision) and emits
:class:`Finding` records for the :class:`~tpu_operator_libs.fsck.
janitor.Janitor` to repair. It never writes.

Classification ladder per owned key, first hit wins:

1. **conflicting** — the key sits under an owned prefix but resolves to
   no registered spec (cross-subsystem collision, typo'd writer,
   squatting webhook), or a registered key appears on the wrong object
   kind / attribute (a node label where the catalog says DS
   annotation).
2. *(preserve keys stop here — user/runtime inputs are cataloged, never
   judged.)*
3. **version-skewed** — a ``v<K>;`` schema wrapper (bare payload = v1);
   a stale operator build wrote a different schema mid-self-upgrade.
4. **garbage** — the value fails the spec's validator (or its codec
   round-trip for map-shaped values).
5. **orphaned** — the value is well-formed but its owning arc is
   provably dead: the incumbent node vanished, the shard retired, the
   state machine left the stamp's owning states.
6. valid.

Cost: O(delta). A per-target digest of ``(labels, annotations)`` is
cached after a scan that produced **zero** findings for that target —
cache entries are deliberately NOT recorded for dirty targets, so a
finding whose repair crashed (the janitor runs under the chaos crash
fuse) is re-found by the next incarnation instead of being skipped as
already-seen. The digest walk is columnar-friendly: two sorted
key/value sweeps per object, no per-key allocation when clean.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Iterable, List, Optional, Tuple

from tpu_operator_libs.consts import GKE_NODEPOOL_LABEL
from tpu_operator_libs.fsck.registry import (
    KIND_DS_ANNOTATION,
    KIND_NODE_ANNOTATION,
    KIND_NODE_LABEL,
    REPAIR_CONVERT,
    REPAIR_DROP,
    REPAIR_PRESERVE,
    REPAIR_SWEEP,
    SCHEMA_WRAPPER_RE,
    AuditContext,
    DurableKeyRegistry,
    DurableKeySpec,
)

logger = logging.getLogger(__name__)

#: Finding classifications (the five-way tentpole taxonomy; ``valid``
#: never leaves the auditor).
GARBAGE = "garbage"
ORPHANED = "orphaned"
CONFLICTING = "conflicting"
VERSION_SKEWED = "version-skewed"
CLASSIFICATIONS = (GARBAGE, ORPHANED, CONFLICTING, VERSION_SKEWED)

TARGET_NODE = "node"
TARGET_DAEMON_SET = "daemonset"


@dataclass(frozen=True)
class Finding:
    """One corrupted stamp: what, where, why, and the repair to apply."""

    target_kind: str
    target: str
    key: str
    value: str
    classification: str
    #: Repair action the janitor should take (a registry REPAIR_*).
    repair: str
    reason: str
    owner: str = ""
    #: True when the key is a LABEL (repairs go through the label patch
    #: path); False for annotations.
    is_label: bool = False
    #: The spec that matched, for normalize/convert repairs (None for
    #: unregistered conflicting keys).
    spec: Optional[DurableKeySpec] = field(default=None, compare=False)


class StateAuditor:
    """Scan nodes + DaemonSets, classify owned stamps, record audits."""

    def __init__(self, registry: DurableKeyRegistry,
                 clock: "Optional[object]" = None,
                 audit: "Optional[object]" = None) -> None:
        self._registry = registry
        self._clock = clock
        self._audit = audit
        #: (kind, name) -> digest of the last ZERO-finding scan.
        self._clean_digests: "dict[Tuple[str, str], int]" = {}
        self.scans_total = 0
        self.targets_scanned_total = 0
        self.targets_skipped_total = 0
        self.findings_total: "dict[str, int]" = {
            c: 0 for c in CLASSIFICATIONS}

    # -- public ----------------------------------------------------------
    def scan(self, nodes: Iterable, daemon_sets: Iterable = ()) \
            -> "List[Finding]":
        """One audit pass over the fleet; returns every finding."""
        nodes = list(nodes)
        daemon_sets = list(daemon_sets)
        self.scans_total += 1

        try:
            shard_key = self._registry.key_for_role("upgrade",
                                                    "-upgrade.shard")
        except KeyError:  # pragma: no cover - registry always has it
            shard_key = ""
        try:
            state_key = self._registry.key_for_role("upgrade",
                                                    "-upgrade-state")
        except KeyError:  # pragma: no cover
            state_key = ""

        node_names = frozenset(n.metadata.name for n in nodes)
        shard_ids = frozenset(
            n.metadata.labels[shard_key] for n in nodes
            if shard_key and shard_key in n.metadata.labels)
        pools = frozenset(
            n.metadata.labels[GKE_NODEPOOL_LABEL] for n in nodes
            if GKE_NODEPOOL_LABEL in n.metadata.labels)

        findings: "List[Finding]" = []
        for node in nodes:
            meta = node.metadata
            digest_key = (TARGET_NODE, meta.name)
            digest = self._digest(meta)
            if self._clean_digests.get(digest_key) == digest:
                self.targets_skipped_total += 1
                continue
            self.targets_scanned_total += 1
            ctx = AuditContext(
                target=meta.name, target_kind=TARGET_NODE,
                labels=meta.labels, annotations=meta.annotations,
                node_names=node_names, shard_ids=shard_ids, pools=pools,
                upgrade_state=meta.labels.get(state_key, ""))
            target_findings = self._scan_meta(
                TARGET_NODE, meta.name, meta.labels, meta.annotations, ctx)
            if target_findings:
                findings.extend(target_findings)
            else:
                self._clean_digests[digest_key] = digest

        for ds in daemon_sets:
            meta = ds.metadata
            name = f"{meta.namespace}/{meta.name}"
            digest_key = (TARGET_DAEMON_SET, name)
            digest = self._digest(meta)
            if self._clean_digests.get(digest_key) == digest:
                self.targets_skipped_total += 1
                continue
            self.targets_scanned_total += 1
            ctx = AuditContext(
                target=name, target_kind=TARGET_DAEMON_SET,
                labels=meta.labels, annotations=meta.annotations,
                node_names=node_names, shard_ids=shard_ids, pools=pools)
            target_findings = self._scan_meta(
                TARGET_DAEMON_SET, name, meta.labels, meta.annotations,
                ctx)
            if target_findings:
                findings.extend(target_findings)
            else:
                self._clean_digests[digest_key] = digest

        for f in findings:
            self.findings_total[f.classification] = (
                self.findings_total.get(f.classification, 0) + 1)
            self._record(f)
        return findings

    # -- internals -------------------------------------------------------
    @staticmethod
    def _digest(meta) -> int:
        return hash((tuple(sorted(meta.labels.items())),
                     tuple(sorted(meta.annotations.items()))))

    def _scan_meta(self, target_kind: str, target: str, labels, annotations,
                   ctx: AuditContext) -> "List[Finding]":
        out: "List[Finding]" = []
        for key in sorted(labels):
            if not self._registry.owns(key):
                continue
            f = self._classify(target_kind, target, key, labels[key],
                               is_label=True, ctx=ctx)
            if f is not None:
                out.append(f)
        for key in sorted(annotations):
            if not self._registry.owns(key):
                continue
            f = self._classify(target_kind, target, key, annotations[key],
                               is_label=False, ctx=ctx)
            if f is not None:
                out.append(f)
        return out

    def _classify(self, target_kind: str, target: str, key: str,
                  value: str, is_label: bool,
                  ctx: AuditContext) -> Optional[Finding]:
        spec = self._registry.lookup(key)
        if spec is None:
            return Finding(
                target_kind, target, key, value, CONFLICTING, REPAIR_DROP,
                "key sits under an owned prefix but is registered to no "
                "subsystem (cross-subsystem collision or squatting "
                "writer)", owner="", is_label=is_label)

        actual_kind = self._actual_kind(target_kind, is_label)
        if actual_kind != spec.kind:
            return Finding(
                target_kind, target, key, value, CONFLICTING, REPAIR_DROP,
                f"registered as {spec.kind} but found as {actual_kind} "
                f"(a stamp on the wrong object never drives decisions "
                f"there)", owner=spec.owner, is_label=is_label, spec=spec)

        if spec.repair == REPAIR_PRESERVE:
            return None

        if SCHEMA_WRAPPER_RE.match(value):
            return Finding(
                target_kind, target, key, value, VERSION_SKEWED,
                REPAIR_CONVERT,
                "schema-version wrapper on a bare-payload (v1) key — a "
                "mixed-version operator fleet wrote a different schema",
                owner=spec.owner, is_label=is_label, spec=spec)

        try:
            ok = spec.validate(value)
        except Exception:  # defensive: validators must not raise
            logger.exception("validator for %s raised; treating %r as "
                             "garbage", key, value)
            ok = False
        if not ok:
            return Finding(
                target_kind, target, key, value, GARBAGE, spec.repair,
                f"value fails the {spec.owner} codec ({spec.codec})",
                owner=spec.owner, is_label=is_label, spec=spec)

        if spec.orphaned is not None:
            suffix = key[len(spec.key):] if spec.prefix else ""
            ctx.key_suffix = suffix
            try:
                reason = spec.orphaned(value, ctx)
            except Exception:  # defensive
                logger.exception("orphan predicate for %s raised", key)
                reason = None
            finally:
                ctx.key_suffix = ""
            if reason:
                return Finding(
                    target_kind, target, key, value, ORPHANED,
                    REPAIR_SWEEP, reason, owner=spec.owner,
                    is_label=is_label, spec=spec)
        return None

    @staticmethod
    def _actual_kind(target_kind: str, is_label: bool) -> str:
        if target_kind == TARGET_DAEMON_SET:
            # the operator only owns DS *annotations*; an owned key as a
            # DS label is a location mismatch by construction
            return KIND_DS_ANNOTATION if not is_label else "ds-label"
        return KIND_NODE_LABEL if is_label else KIND_NODE_ANNOTATION

    def _record(self, f: Finding) -> None:
        if self._audit is None:
            return
        self._audit.record(
            "fsck", f.target, decision=f.classification,
            rule=f"fsck/{f.classification}",
            inputs={"key": f.key, "value": f.value[:128],
                    "owner": f.owner or "unregistered",
                    "repair": f.repair, "reason": f.reason})
