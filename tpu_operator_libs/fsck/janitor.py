"""Janitor: repair auditor findings through audited, crash-ordered
writes.

The janitor is the fsck *write* half. It takes the
:class:`~tpu_operator_libs.fsck.auditor.Finding` list one audit pass
produced and applies each spec's repair action:

* **drop** / **sweep** — delete the key (garbage whose truth is
  re-derivable; orphans whose owning arc is provably dead).
* **normalize** — re-encode the decodable subset of a map-shaped value
  through its own codec; delete when nothing survives.
* **convert** — unwrap a ``v<K>;`` schema wrapper whose inner payload
  validates back to the current bare form; drop when it does not (a
  wrapper is never trusted further than its payload).
* **quarantine** — the state itself is ambiguous (garbled state label,
  unreadable cordon intent): park the node under BOTH machines' skip
  labels plus the fsck quarantine stamp, and never guess. A human
  clears all three after review.

Crash ordering. All annotation repairs for one node coalesce into ONE
merge patch; label repairs into one label patch; quarantine into one
meta patch (skip labels + stamp, atomic — a crash can not leave a
parked node unexplained or an explained node unparked). Every write
funnels through the injected ``guard`` — the chaos crash fuse in soak
runs — and every repair is idempotent: if the fuse detonates mid-
repair the write is lost, the next incarnation's auditor re-finds the
same corruption (the clean-digest cache only records zero-finding
targets) and re-repairs it.

Every applied repair is recorded twice: a DecisionAudit ``fsck-repair``
record, and a :class:`RepairRecord` appended to the injectable
``repair_log`` — a plain list the chaos harness threads through
operator incarnations so ``explain()`` chains survive crashes.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from tpu_operator_libs.consts import TRUE_STRING
from tpu_operator_libs.fsck.auditor import (
    TARGET_NODE,
    Finding,
)
from tpu_operator_libs.fsck.registry import (
    REPAIR_CONVERT,
    REPAIR_DROP,
    REPAIR_NORMALIZE,
    REPAIR_QUARANTINE,
    REPAIR_SWEEP,
    SCHEMA_WRAPPER_RE,
    DurableKeyRegistry,
    fsck_quarantine_annotation,
)

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class RepairRecord:
    """One applied repair and its full why-chain (explain() payload)."""

    at: float
    target_kind: str
    target: str
    key: str
    action: str
    #: The blocking explanation chain: finding reason → classification →
    #: repair + its crash-ordering note. Stored in the record (not the
    #: janitor) so chains survive operator-incarnation death.
    chain: Tuple[str, ...]


class Janitor:
    """Apply repairs for one audit pass, coalesced per target."""

    def __init__(self, client: "object", registry: DurableKeyRegistry,
                 upgrade_keys: "object",
                 remediation_keys: "Optional[object]" = None,
                 guard: Optional[Callable] = None,
                 audit: "Optional[object]" = None,
                 clock: "Optional[object]" = None,
                 repair_log: Optional["List[RepairRecord]"] = None) -> None:
        self._client = client
        self._registry = registry
        self._upgrade_keys = upgrade_keys
        self._remediation_keys = remediation_keys
        self._guard = guard if guard is not None else (lambda write: write())
        self._audit = audit
        self._clock = clock
        #: Injectable so the chaos harness can share one log across
        #: operator incarnations (records must outlive crashes).
        self.repair_log: "List[RepairRecord]" = (
            repair_log if repair_log is not None else [])
        self.repairs_total: "dict[str, int]" = {}
        self.quarantined_nodes: "set[str]" = set()

    # -- public ----------------------------------------------------------
    def repair(self, findings: Iterable[Finding]) -> int:
        """Apply every finding's repair; returns the repair count.

        Raises whatever the guarded writes raise (OperatorCrash under
        the chaos fuse, ApiServerError under transient faults) — the
        caller's incarnation/transient handling applies, and the
        auditor re-finds whatever was not committed."""
        applied = 0
        by_target: "Dict[Tuple[str, str], List[Finding]]" = {}
        for f in findings:
            by_target.setdefault((f.target_kind, f.target), []).append(f)

        for (kind, target), group in sorted(by_target.items()):
            quarantine = [f for f in group
                          if f.repair == REPAIR_QUARANTINE]
            rest = [f for f in group if f.repair != REPAIR_QUARANTINE]
            if kind == TARGET_NODE:
                applied += self._repair_node(target, rest, quarantine)
            else:
                applied += self._repair_daemon_set(target, rest, quarantine)
        return applied

    def explain(self, target: str, key: str) -> "dict":
        """The why-chain of the most recent repair touching (target,
        key): ``{"blocking": (...why lines...), "action": ..., "at":
        ...}``; empty chain when no repair has touched it."""
        for record in reversed(self.repair_log):
            if record.target == target and record.key == key:
                return {"blocking": list(record.chain),
                        "action": record.action, "at": record.at}
        return {"blocking": [], "action": "", "at": 0.0}

    # -- repair planning -------------------------------------------------
    def _planned_value(self, f: Finding) -> Optional[str]:
        """The post-repair value for one finding: None deletes."""
        if f.repair in (REPAIR_DROP, REPAIR_SWEEP):
            return None
        if f.repair == REPAIR_NORMALIZE:
            if f.spec is None or f.spec.normalize is None:
                return None
            try:
                survivor = f.spec.normalize(f.value)
            except Exception:  # defensive: normalizers must not raise
                logger.exception("normalize for %s raised; dropping",
                                 f.key)
                survivor = ""
            return survivor or None
        if f.repair == REPAIR_CONVERT:
            inner = SCHEMA_WRAPPER_RE.sub("", f.value, count=1)
            if f.spec is not None:
                try:
                    if f.spec.validate(inner):
                        return inner
                    if f.spec.normalize is not None:
                        survivor = f.spec.normalize(inner)
                        if survivor:
                            return survivor
                except Exception:  # defensive
                    logger.exception("convert for %s raised; dropping",
                                     f.key)
            return None
        logger.warning("unknown repair %r for %s; dropping", f.repair,
                       f.key)
        return None

    def _chain(self, f: Finding, action: str,
               value: Optional[str]) -> "Tuple[str, ...]":
        if value is None:
            effect = "delete the key"
        else:
            effect = f"rewrite to {value!r}"
        contract = f.spec.contract if f.spec is not None else \
            "unregistered key: no contract — removal is the contract"
        return (
            f"finding: {f.reason}",
            f"classified {f.classification} "
            f"(owner {f.owner or 'unregistered'})",
            f"repair {action}: {effect} [{contract}]",
        )

    # -- node repairs ----------------------------------------------------
    def _repair_node(self, name: str, rest: "List[Finding]",
                     quarantine: "List[Finding]") -> int:
        applied = 0
        ann_patch: "Dict[str, Optional[str]]" = {}
        ann_records: "List[Tuple[Finding, Optional[str]]]" = []
        label_patch: "Dict[str, Optional[str]]" = {}
        label_records: "List[Tuple[Finding, Optional[str]]]" = []
        for f in rest:
            value = self._planned_value(f)
            if f.is_label:
                label_patch[f.key] = value
                label_records.append((f, value))
            else:
                ann_patch[f.key] = value
                ann_records.append((f, value))

        # one merge patch per attribute family per node (crash-atomic:
        # either every annotation repair for the node lands or none).
        # The intent records go FIRST (write-ahead): if the fuse
        # detonates after the patch commits, the repair is still
        # audited; if it detonates before, the auditor re-finds the
        # corruption and a fresh intent+write follows.
        if ann_patch:
            applied += self._commit(ann_records)
            self._guard(lambda: self._client.patch_node_annotations(
                name, dict(ann_patch)))
        if label_patch:
            applied += self._commit(label_records)
            self._guard(lambda: self._client.patch_node_labels(
                name, dict(label_patch)))

        if quarantine:
            applied += self._quarantine_node(name, quarantine)
        return applied

    def _quarantine_node(self, name: str,
                         findings: "List[Finding]") -> int:
        """Park, never guess: both machines' skip labels + the fsck
        stamp in ONE meta patch."""
        reason = findings[0].classification
        stamp_key = fsck_quarantine_annotation(
            self._upgrade_keys.driver, self._upgrade_keys.domain)
        stamp = f"{reason}:{self._now():g}"
        labels: "Dict[str, Optional[str]]" = {
            self._upgrade_keys.skip_label: TRUE_STRING}
        if self._remediation_keys is not None:
            labels[self._remediation_keys.skip_label] = TRUE_STRING
        records = [(f, stamp) for f in findings]
        self.quarantined_nodes.add(name)
        applied = self._commit(records, action=REPAIR_QUARANTINE)
        self._guard(lambda: self._client.patch_node_meta(
            name, labels=labels, annotations={stamp_key: stamp}))
        return applied

    # -- DaemonSet repairs -----------------------------------------------
    def _repair_daemon_set(self, target: str, rest: "List[Finding]",
                           quarantine: "List[Finding]") -> int:
        # quarantine is a node concept; an ambiguous DS stamp of a
        # PRESERVE-adjacent kind would be registry-misconfigured — drop
        # nothing, log loudly, leave it for humans
        for f in quarantine:  # pragma: no cover - no DS key quarantines
            logger.warning("DS stamp %s on %s classified for quarantine; "
                           "leaving untouched", f.key, target)
        if not rest:
            return 0
        namespace, _, name = target.partition("/")
        patch: "Dict[str, Optional[str]]" = {}
        records: "List[Tuple[Finding, Optional[str]]]" = []
        for f in rest:
            value = self._planned_value(f)
            patch[f.key] = value
            records.append((f, value))
        applied = self._commit(records)
        self._guard(lambda: self._client.patch_daemon_set_annotations(
            namespace, name, dict(patch)))
        return applied

    # -- bookkeeping -----------------------------------------------------
    def _commit(self, records: "List[Tuple[Finding, Optional[str]]]",
                action: str = "") -> int:
        """Write-ahead intent: record + audit each repair BEFORE its
        guarded patch, so a crash-after-write repair is never
        unaudited (a crash-before-write intent is re-found and
        re-intended — duplicates are fine, silence is not)."""
        now = self._now()
        for f, value in records:
            act = action or f.repair
            chain = self._chain(f, act, value if act != REPAIR_QUARANTINE
                                else None)
            if act == REPAIR_QUARANTINE:
                chain = chain + (
                    "parked: skip labels for both machines + fsck stamp "
                    "in one atomic meta patch; a human clears all three",)
            self.repair_log.append(RepairRecord(
                at=now, target_kind=f.target_kind, target=f.target,
                key=f.key, action=act, chain=chain))
            self.repairs_total[act] = self.repairs_total.get(act, 0) + 1
            if self._audit is not None:
                self._audit.record(
                    "fsck-repair", f.target, decision=act,
                    rule=f"fsck/repair-{act}",
                    inputs={"key": f.key, "classification":
                            f.classification,
                            "new_value": "" if value is None else value,
                            "reason": f.reason})
        return len(records)

    def _now(self) -> float:
        if self._clock is not None:
            return self._clock.now()
        return 0.0
