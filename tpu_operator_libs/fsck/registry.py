"""DurableKeyRegistry: the one catalog of every durable key the
operator owns.

Eighteen PRs of crash-ordered durable stamps left the operator's only
store — node/DaemonSet labels and annotations — described piecemeal:
the key *names* live in :mod:`tpu_operator_libs.consts` (four
instance-scoped ``*Keys`` families), the value *grammars* in the
subsystems' codecs (``upgrade.predictor.decode_durations``,
``health.precursor.decode_rates``, ``topology.slice_topology.
decode_degraded_slices``, ``federation.ledger``), and the
crash-ordering contracts in docstrings. Nothing knew the whole
surface, so nothing could *defend* it: every crash-safety proof
assumes the operator itself wrote the state, while production
annotations are also touched by kubectl-editing humans, mutating
webhooks, and stale operator versions mid-self-upgrade.

This module is the missing catalog. A :class:`DurableKeySpec` binds
one key (or key prefix) to its owner subsystem, object kind, value
validator, schema version, default repair action, and crash-ordering
contract; :func:`default_registry` enumerates every key of
``UpgradeKeys`` / ``RemediationKeys`` / ``TopologyKeys`` /
``FederationKeys`` (plus fsck's own quarantine stamp). The
:class:`~tpu_operator_libs.fsck.auditor.StateAuditor` classifies live
stamps against it, and the :class:`~tpu_operator_libs.fsck.janitor.
Janitor` repairs what fails.

Schema versioning convention: a bare payload IS schema version 1.  A
mixed-version operator fleet (the operator's own rolling upgrade)
marks other schemata by wrapping the payload as ``v<K>;<payload>``;
the janitor's ``convert`` repair unwraps a recognized wrapper whose
inner payload validates and rewrites the current (bare) form, so the
fleet converges on one schema instead of fighting.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Callable, Mapping, Optional

from tpu_operator_libs.consts import (
    TRUE_STRING,
    FederationKeys,
    RemediationKeys,
    TopologyKeys,
    UpgradeKeys,
    UpgradeState,
)

if TYPE_CHECKING:  # pragma: no cover - types only
    pass

# -- repair actions --------------------------------------------------------
#: Delete the key: the value is garbage and the truth is re-derivable
#: (or conservatively "absent" — timers restart, samples are lost but
#: never invented).
REPAIR_DROP = "drop"
#: Re-encode the decodable subset of a map-shaped value through its own
#: codec; delete the key when nothing survives. The repair for
#: hand-edited or torn composite stamps (``drain=12,garbage``).
REPAIR_NORMALIZE = "normalize"
#: Delete an orphaned stamp whose owning arc is provably dead (the
#: incumbent node no longer exists, the shard is carried by no node,
#: the state machine left the states that own the stamp).
REPAIR_SWEEP = "sweep"
#: Park the node — skip labels for both machines plus the fsck
#: quarantine stamp — and never guess: an ambiguous state label is a
#: human's call, not the janitor's.
REPAIR_QUARANTINE = "quarantine"
#: Unwrap a ``v<K>;`` schema wrapper back to the current bare form
#: (drop when the inner payload does not validate).
REPAIR_CONVERT = "convert"
#: Never repaired: operator *input* keys (skip labels, re-arm and
#: on-demand-upgrade requests, the safe-load handshake) are written by
#: humans/the runtime and any value must be honored, and fail-safe
#: records (the quarantined-revision halt) must never be auto-removed.
REPAIR_PRESERVE = "preserve"

#: Target-kind tags (where a key legally lives).
KIND_NODE_LABEL = "node-label"
KIND_NODE_ANNOTATION = "node-annotation"
KIND_DS_ANNOTATION = "ds-annotation"

#: ``v<K>;`` schema-wrapper pattern (bare payload = schema v1).
SCHEMA_WRAPPER_RE = re.compile(r"^v(\d+);")


@dataclass
class AuditContext:
    """The cluster facts orphan predicates may consult — everything is
    captured once per scan (cheap sets), never read per-key."""

    target: str
    target_kind: str
    labels: Mapping[str, str]
    annotations: Mapping[str, str]
    #: Live node names (a stamp naming a vanished incumbent is orphaned).
    node_names: frozenset = frozenset()
    #: Shard ids some live node currently carries (a per-shard canary
    #: attestation for a retired shard is orphaned).
    shard_ids: frozenset = frozenset()
    #: Live nodepool (slice) names.
    pools: frozenset = frozenset()
    #: The target node's upgrade-state label value ("" off-flow).
    upgrade_state: str = ""
    #: For prefix families: the suffix after the registered prefix of
    #: the key under audit (e.g. the shard id of a per-shard canary
    #: attestation). Set per-key by the auditor; "" for exact keys.
    key_suffix: str = ""


@dataclass(frozen=True)
class DurableKeySpec:
    """One owned key (or key-prefix) family and how to defend it."""

    key: str
    owner: str
    kind: str
    #: Human-readable value grammar (the docs/durable-state.md column).
    codec: str
    #: Default repair for a value that fails ``validate``.
    repair: str
    #: Crash-ordering contract, one line (the docs table column).
    contract: str
    #: True when ``key`` is a prefix (``<key><suffix>`` families like
    #: the artifact stamps and per-shard canary attestations).
    prefix: bool = False
    schema_version: int = 1
    #: Value validator; never raises. PRESERVE keys keep the default.
    validate: Callable[[str], bool] = field(default=lambda value: True)
    #: For REPAIR_NORMALIZE: re-encode the decodable subset ("" deletes).
    normalize: Optional[Callable[[str], str]] = None
    #: Orphan predicate: a reason string when the owning arc is provably
    #: dead (sweep), None while it may be alive. Only consulted for
    #: values that validated — garbage is already classified.
    orphaned: Optional[Callable[[str, AuditContext], Optional[str]]] = None

    def matches(self, key: str) -> bool:
        if self.prefix:
            return key.startswith(self.key) and len(key) > len(self.key)
        return key == self.key


class DurableKeyRegistry:
    """Exact + longest-prefix lookup over the owned-key catalog."""

    def __init__(self, specs: "list[DurableKeySpec]",
                 owned_prefixes: "tuple[str, ...]") -> None:
        self._exact = {s.key: s for s in specs if not s.prefix}
        # longest prefix wins, so overlapping families stay unambiguous
        self._prefixed = sorted((s for s in specs if s.prefix),
                                key=lambda s: -len(s.key))
        self._specs = tuple(specs)
        #: Key prefixes this operator instance OWNS: any key under one
        #: of these that resolves to no spec is a conflicting stamp
        #: (cross-subsystem collision, typo'd writer, squatting webhook).
        self.owned_prefixes = owned_prefixes

    @property
    def specs(self) -> "tuple[DurableKeySpec, ...]":
        return self._specs

    def owns(self, key: str) -> bool:
        return any(key.startswith(p) for p in self.owned_prefixes)

    def lookup(self, key: str) -> Optional[DurableKeySpec]:
        spec = self._exact.get(key)
        if spec is not None:
            return spec
        for candidate in self._prefixed:
            if candidate.matches(key):
                return candidate
        return None

    def key_for_role(self, owner: str, suffix: str) -> str:
        """The registered key whose full name ends with ``suffix`` for
        ``owner`` (auditor bootstrap: find the state/shard label keys
        without re-plumbing the consts instances)."""
        for spec in self._specs:
            if spec.owner == owner and spec.key.endswith(suffix):
                return spec.key
        raise KeyError(f"{owner}:{suffix} not registered")


# -- validators ------------------------------------------------------------
def _is_epoch(value: str) -> bool:
    try:
        return float(value) >= 0.0
    except ValueError:
        return False


def _is_int(value: str) -> bool:
    try:
        int(value)
        return True
    except ValueError:
        return False


def _is_nonneg_int(value: str) -> bool:
    return _is_int(value) and int(value) >= 0


def _is_true(value: str) -> bool:
    return value == TRUE_STRING


def _is_token(value: str) -> bool:
    """An opaque single token: non-empty, no whitespace, no the
    list/pair separators the composite codecs claim."""
    return bool(value) and not re.search(r"[\s,;]", value)


def _is_hash_epoch(value: str) -> bool:
    """``<hash>:<epoch-seconds>`` (canary/bake attestations)."""
    head, sep, raw = value.rpartition(":")
    return bool(sep) and _is_token(head) and _is_epoch(raw)


def _is_name_epoch(value: str) -> bool:
    """``<name>:<epoch-seconds>`` (prewarm-ready join stamps)."""
    return _is_hash_epoch(value)


def _is_preshift_reservation(value: str) -> bool:
    """``<source>:<revision>:<slots>:<epoch>`` (region pre-shift
    reserve stamps)."""
    parts = value.split(":")
    return (len(parts) == 4 and _is_token(parts[0])
            and _is_token(parts[1]) and _is_nonneg_int(parts[2])
            and _is_epoch(parts[3]))


def _is_preshift_ready(value: str) -> bool:
    """``<source>:<revision>:<epoch>`` (region pre-shift ready
    stamps)."""
    parts = value.split(":")
    return (len(parts) == 3 and _is_token(parts[0])
            and _is_token(parts[1]) and _is_epoch(parts[2]))


def _is_phase_stamp(value: str) -> bool:
    from tpu_operator_libs.upgrade.predictor import _parse_stamp

    phase, _ = _parse_stamp(value)
    return phase is not None


def _durations_canonical(value: str) -> bool:
    from tpu_operator_libs.upgrade.predictor import (
        decode_durations,
        encode_durations,
    )

    return bool(value) and encode_durations(decode_durations(value)) == value


def _normalize_durations(value: str) -> str:
    from tpu_operator_libs.upgrade.predictor import (
        decode_durations,
        encode_durations,
    )

    return encode_durations(decode_durations(value))


def _rates_canonical(value: str) -> bool:
    from tpu_operator_libs.health.precursor import (
        decode_rates,
        encode_rates,
    )

    return bool(value) and encode_rates(decode_rates(value)) == value


def _normalize_rates(value: str) -> str:
    from tpu_operator_libs.health.precursor import (
        decode_rates,
        encode_rates,
    )

    return encode_rates(decode_rates(value))


def _degraded_canonical(value: str) -> bool:
    from tpu_operator_libs.topology.slice_topology import (
        decode_degraded_slices,
        encode_degraded_slices,
    )

    return bool(value) and encode_degraded_slices(
        decode_degraded_slices(value)) == value


def _normalize_degraded(value: str) -> str:
    from tpu_operator_libs.topology.slice_topology import (
        decode_degraded_slices,
        encode_degraded_slices,
    )

    return encode_degraded_slices(decode_degraded_slices(value))


def _is_reservation(value: str) -> bool:
    """``<incumbent>:<model>:<class>`` (prewarm reserve stamps)."""
    parts = value.split(":")
    return len(parts) == 3 and all(_is_token(p) for p in parts)


def _is_slice_reservation(value: str) -> bool:
    """``<slice-id>/<missing-host>:<epoch>`` (spare reserved-for)."""
    head, sep, raw = value.rpartition(":")
    if not sep or not _is_epoch(raw):
        return False
    slice_id, slash, host = head.partition("/")
    return bool(slash) and _is_token(slice_id) and _is_token(host)


def _is_remap_stamp(value: str) -> bool:
    """``<epoch>:<missing-host>`` (remapped-at join stamps)."""
    raw, sep, host = value.partition(":")
    return bool(sep) and _is_epoch(raw) and _is_token(host)


def _member_of(enum_values: "frozenset[str]") -> Callable[[str], bool]:
    return lambda value: value in enum_values


def default_registry(driver: str = "libtpu",
                     domain: str = "google.com") -> DurableKeyRegistry:
    """The full owned-key catalog for one driver/domain instance."""
    up = UpgradeKeys(driver=driver, domain=domain)
    rem = RemediationKeys(driver=driver, domain=domain)
    topo = TopologyKeys(driver=driver, domain=domain)
    fed = FederationKeys(driver=driver, domain=domain)

    from tpu_operator_libs.consts import RemediationState

    upgrade_states = frozenset(str(s) for s in UpgradeState)
    remediation_states = frozenset(str(s) for s in RemediationState)
    #: The upgrade machine's REST states. Arc-scoped stamps are only
    #: declared orphaned when the machine is at rest — deliberately
    #: maximally conservative: any in-flow state (including FAILED,
    #: which keeps its evidence for humans, and ROLLBACK, which
    #: re-enters the flow) counts as a live arc, so the janitor can
    #: never fight the operator over a stamp mid-journey.
    rest_states = frozenset(("", str(UpgradeState.DONE)))

    def _dead_arc(what: str):
        def orphaned(value: str, ctx: AuditContext) -> Optional[str]:
            if ctx.upgrade_state not in rest_states:
                return None
            return (f"{what} stamp survives with the upgrade machine at "
                    f"rest (state {ctx.upgrade_state or 'unset'!r}) — "
                    f"the owning arc is over")
        return orphaned

    def _dead_incumbent(value: str, ctx: AuditContext) -> Optional[str]:
        incumbent = value.split(":", 1)[0]
        if incumbent in ctx.node_names:
            return None
        return (f"prewarm stamp names incumbent {incumbent!r}, which no "
                f"longer exists (recycled spare residue)")

    def _torn_ready(value: str, ctx: AuditContext) -> Optional[str]:
        dead = _dead_incumbent(value, ctx)
        if dead is not None:
            return dead
        if up.prewarm_reservation_annotation not in ctx.annotations:
            return ("prewarm-ready join stamp without its reserve stamp "
                    "— a torn half-of-a-pair write (ready implies "
                    "reservation; never invent the missing half)")
        return None

    def _torn_preshift_ready(value: str, ctx: AuditContext) -> Optional[str]:
        if fed.preshift_reservation_annotation not in ctx.annotations:
            return ("pre-shift ready stamp without its reservation — a "
                    "torn half-of-a-pair write (ready implies "
                    "reservation; never invent the missing half)")
        return None

    def _dead_shard(value: str, ctx: AuditContext) -> Optional[str]:
        shard = ctx.key_suffix
        if shard and shard not in ctx.shard_ids:
            return (f"canary attestation for shard {shard!r}, which no "
                    f"live node carries (retired shard residue)")
        return None

    def _dead_pool(value: str, ctx: AuditContext) -> Optional[str]:
        slice_id = value.partition("/")[0]
        if slice_id in ctx.pools:
            return None
        return (f"spare reservation names slice {slice_id!r}, which no "
                f"longer exists")

    specs: "list[DurableKeySpec]" = [
        # ---- upgrade machine -------------------------------------------
        DurableKeySpec(
            up.state_label, "upgrade", KIND_NODE_LABEL,
            "UpgradeState enum value", REPAIR_QUARANTINE,
            "THE durable commit point; every transition is one label "
            "patch with its bookkeeping riding the same patch",
            validate=_member_of(upgrade_states)),
        DurableKeySpec(
            up.skip_label, "upgrade", KIND_NODE_LABEL,
            "operator input (presence opts the node out)",
            REPAIR_PRESERVE, "human-owned input; never repaired"),
        DurableKeySpec(
            up.shard_label, "upgrade", KIND_NODE_LABEL,
            "int shard id (ring-derived)", REPAIR_DROP,
            "idempotent re-stamp: concurrent stampers always write "
            "identical ring-derived values",
            validate=_is_nonneg_int),
        DurableKeySpec(
            up.wait_for_safe_load_annotation, "upgrade",
            KIND_NODE_ANNOTATION, "runtime init-container input",
            REPAIR_PRESERVE, "runtime-owned handshake; never repaired"),
        DurableKeySpec(
            up.initial_state_annotation, "upgrade", KIND_NODE_ANNOTATION,
            '"true" (node was already unschedulable)', REPAIR_QUARANTINE,
            "rides the cordon-committing patch; read at uncordon — a "
            "garbled value makes cordon intent ambiguous (never guess)",
            validate=_is_true,
            orphaned=_dead_arc("initial-state")),
        DurableKeySpec(
            up.pod_completion_start_annotation, "upgrade",
            KIND_NODE_ANNOTATION, "epoch seconds", REPAIR_DROP,
            "checkpoint stamp: absent means the wait-for-jobs timer "
            "restarts (conservative)",
            validate=_is_epoch,
            orphaned=_dead_arc("pod-completion-start")),
        DurableKeySpec(
            up.validation_start_annotation, "upgrade",
            KIND_NODE_ANNOTATION, "epoch seconds", REPAIR_DROP,
            "checkpoint stamp: absent means the validation timer "
            "restarts (conservative)",
            validate=_is_epoch,
            orphaned=_dead_arc("validation-start")),
        DurableKeySpec(
            up.upgrade_requested_annotation, "upgrade",
            KIND_NODE_ANNOTATION, "operator input (on-demand upgrade)",
            REPAIR_PRESERVE, "human-owned input; never repaired"),
        DurableKeySpec(
            up.quarantined_revision_annotation, "upgrade",
            KIND_DS_ANNOTATION, "condemned revision hash",
            REPAIR_PRESERVE,
            "fail-safe halt record: auto-removing it would un-quarantine "
            "a bad build — never repaired",
            validate=_is_token),
        DurableKeySpec(
            up.canary_passed_annotation, "upgrade", KIND_DS_ANNOTATION,
            "<revision-hash>:<epoch>", REPAIR_DROP,
            "absent means the canary re-bakes (conservative: waves wait)",
            validate=_is_hash_epoch),
        DurableKeySpec(
            up.canary_shard_passed_prefix, "upgrade", KIND_DS_ANNOTATION,
            "<prefix><shard-id> = <revision-hash>", REPAIR_DROP,
            "per-shard attestation; absent means the shard re-attests",
            prefix=True, validate=_is_token, orphaned=_dead_shard),
        DurableKeySpec(
            up.phase_start_annotation, "upgrade", KIND_NODE_ANNOTATION,
            "<phase>:<epoch>", REPAIR_DROP,
            "rides the transition patch; a garbled stamp reads as 'no "
            "open phase' — the sample is lost, never invented",
            validate=_is_phase_stamp,
            orphaned=_dead_arc("phase-start")),
        DurableKeySpec(
            up.phase_durations_annotation, "upgrade",
            KIND_NODE_ANNOTATION, "drain=<s>,restart=<s>,validate=<s>",
            REPAIR_NORMALIZE,
            "durable model seed (outlives the arc); malformed entries "
            "are re-encoded out, an empty survivor deletes the key",
            validate=_durations_canonical, normalize=_normalize_durations),
        DurableKeySpec(
            up.trace_id_annotation, "upgrade", KIND_NODE_ANNOTATION,
            "opaque trace id token", REPAIR_DROP,
            "opens/closes with the journey on the state-commit patch; "
            "residue past upgrade-done is swept",
            validate=_is_token,
            orphaned=_dead_arc("trace-id")),
        DurableKeySpec(
            up.prewarm_reservation_annotation, "upgrade",
            KIND_NODE_ANNOTATION, "<incumbent>:<model>:<class>",
            REPAIR_DROP,
            "RESERVE stamp, crash-ordered before the ready stamp; a "
            "reservation naming a vanished incumbent is swept",
            validate=_is_reservation, orphaned=_dead_incumbent),
        DurableKeySpec(
            up.prewarm_ready_annotation, "upgrade", KIND_NODE_ANNOTATION,
            "<incumbent>:<epoch>", REPAIR_DROP,
            "JOIN stamp: ready implies reservation — a ready stamp "
            "without its reserve half (torn pair) is swept, never "
            "completed by guessing",
            validate=_is_name_epoch, orphaned=_torn_ready),
        DurableKeySpec(
            up.artifact_stamp_prefix, "upgrade", KIND_NODE_ANNOTATION,
            "<prefix><artifact> = <revision-hash>", REPAIR_DROP,
            "written in DAG dependency order, one patch each; absent "
            "means the artifact re-verifies (conservative)",
            prefix=True, validate=_is_token),
        # ---- remediation machine ---------------------------------------
        DurableKeySpec(
            rem.state_label, "remediation", KIND_NODE_LABEL,
            "RemediationState enum value", REPAIR_QUARANTINE,
            "the unplanned-fault machine's commit point (same provider "
            "discipline as the upgrade label)",
            validate=_member_of(remediation_states)),
        DurableKeySpec(
            rem.skip_label, "remediation", KIND_NODE_LABEL,
            "operator input (presence opts the node out)",
            REPAIR_PRESERVE, "human-owned input; never repaired"),
        DurableKeySpec(
            rem.wedge_since_annotation, "remediation",
            KIND_NODE_ANNOTATION, "epoch seconds", REPAIR_DROP,
            "debounce anchor: absent means the grace window restarts",
            validate=_is_epoch),
        DurableKeySpec(
            rem.wedge_reason_annotation, "remediation",
            KIND_NODE_ANNOTATION, "reason slug", REPAIR_DROP,
            "evidence beside the state label; re-derived on re-detect",
            validate=_is_token),
        DurableKeySpec(
            rem.attempt_annotation, "remediation", KIND_NODE_ANNOTATION,
            "int attempt count", REPAIR_DROP,
            "escalation rung pointer; absent restarts the ladder "
            "(conservative: more attempts before condemning)",
            validate=_is_nonneg_int),
        DurableKeySpec(
            rem.action_start_annotation, "remediation",
            KIND_NODE_ANNOTATION, "epoch seconds", REPAIR_DROP,
            "action-timeout anchor: absent means the timer restarts",
            validate=_is_epoch),
        DurableKeySpec(
            rem.restart_pod_uid_annotation, "remediation",
            KIND_NODE_ANNOTATION, "pod UID token", REPAIR_DROP,
            "recreation detector; absent falls back to the timeout",
            validate=_is_token),
        DurableKeySpec(
            rem.settle_start_annotation, "remediation",
            KIND_NODE_ANNOTATION, "epoch seconds", REPAIR_DROP,
            "stability-window anchor: absent means settling restarts",
            validate=_is_epoch),
        DurableKeySpec(
            rem.reboot_requested_annotation, "remediation",
            KIND_NODE_ANNOTATION, "epoch seconds", REPAIR_DROP,
            "host-agent handshake stamp; absent means the rung "
            "re-requests",
            validate=_is_epoch),
        DurableKeySpec(
            rem.initial_state_annotation, "remediation",
            KIND_NODE_ANNOTATION, '"true" (was already unschedulable)',
            REPAIR_QUARANTINE,
            "read at uncordon — a garbled value makes cordon intent "
            "ambiguous (never guess)",
            validate=_is_true),
        DurableKeySpec(
            rem.rearm_annotation, "remediation", KIND_NODE_ANNOTATION,
            "operator input (re-arm after manual repair)",
            REPAIR_PRESERVE, "human-owned input; never repaired"),
        DurableKeySpec(
            rem.condemned_annotation, "remediation", KIND_NODE_ANNOTATION,
            "epoch seconds", REPAIR_QUARANTINE,
            "durable give-up record keying slice remaps and MTTR; a "
            "garbled stamp on a parked node is a human's call",
            validate=_is_epoch),
        DurableKeySpec(
            rem.at_risk_annotation, "remediation", KIND_NODE_ANNOTATION,
            "epoch seconds", REPAIR_QUARANTINE,
            "condemn-before-fail anchor, crash-atomic with the at-risk "
            "commit; a garbled stamp is a human's call",
            validate=_is_epoch),
        DurableKeySpec(
            rem.at_risk_reason_annotation, "remediation",
            KIND_NODE_ANNOTATION, "precursor verdict slug", REPAIR_DROP,
            "evidence beside the at-risk stamp; re-stamped on the next "
            "verdict",
            validate=_is_token),
        DurableKeySpec(
            rem.precursor_rates_annotation, "remediation",
            KIND_NODE_ANNOTATION, "ecc=<r>,link-flap=<r>,...",
            REPAIR_NORMALIZE,
            "durable model seed on HEALTHY nodes (outside the "
            "remediation-residue namespace); malformed entries are "
            "re-encoded out",
            validate=_rates_canonical, normalize=_normalize_rates),
        # ---- topology / reconfiguration --------------------------------
        DurableKeySpec(
            topo.spare_pool_label, "topology", KIND_NODE_LABEL,
            '"true" (hot-standby member)', REPAIR_DROP,
            "a node with a garbled spare marker is NOT trusted as a "
            "spare (never hand workloads a bogus standby)",
            validate=_is_true),
        DurableKeySpec(
            topo.reserved_for_annotation, "topology",
            KIND_NODE_ANNOTATION, "<slice>/<host>:<epoch>", REPAIR_DROP,
            "reserve→join→release commit #1; a reservation naming a "
            "vanished slice is swept",
            validate=_is_slice_reservation, orphaned=_dead_pool),
        DurableKeySpec(
            topo.remapped_at_annotation, "topology", KIND_NODE_ANNOTATION,
            "<epoch>:<missing-host>", REPAIR_DROP,
            "join stamp riding the pool-label patch; sticky-down window "
            "anchor",
            validate=_is_remap_stamp),
        DurableKeySpec(
            topo.released_from_annotation, "topology",
            KIND_NODE_ANNOTATION, "slice id token", REPAIR_DROP,
            "audit trail on a parked node; informational",
            validate=_is_token),
        DurableKeySpec(
            topo.degraded_slices_annotation, "topology",
            KIND_DS_ANNOTATION, "slice:host[+host],...", REPAIR_NORMALIZE,
            "written in ONE patch before the condemned node releases; "
            "malformed fragments are re-encoded out",
            validate=_degraded_canonical, normalize=_normalize_degraded),
        # ---- federation ------------------------------------------------
        DurableKeySpec(
            fed.budget_share_annotation, "federation", KIND_DS_ANNOTATION,
            "non-negative int node count", REPAIR_DROP,
            "absent/garbled means the region admits NOTHING — the "
            "conservative side of the ledger inequality",
            validate=_is_nonneg_int),
        DurableKeySpec(
            fed.bake_passed_annotation, "federation", KIND_DS_ANNOTATION,
            "<revision-hash>:<epoch>", REPAIR_DROP,
            "absent means the canary region re-bakes (waves wait)",
            validate=_is_hash_epoch),
        DurableKeySpec(
            fed.probe_annotation, "federation", KIND_DS_ANNOTATION,
            "epoch seconds", REPAIR_DROP,
            "freshness probe, re-stamped every pass; absent reads as "
            "unreachable (shares may only decrease)",
            validate=_is_epoch),
        DurableKeySpec(
            fed.preshift_reservation_annotation, "federation",
            KIND_DS_ANNOTATION, "<source>:<revision>:<slots>:<epoch>",
            REPAIR_DROP,
            "region-level pre-shift RESERVE stamp, crash-ordered before "
            "the ready stamp; released with it in ONE patch when the "
            "source region's rollout quiesced (zero residue)",
            validate=_is_preshift_reservation),
        DurableKeySpec(
            fed.preshift_ready_annotation, "federation",
            KIND_DS_ANNOTATION, "<source>:<revision>:<epoch>",
            REPAIR_DROP,
            "pre-shift commit #2: sessions may route here; a ready "
            "stamp without its reservation is a torn pair",
            validate=_is_preshift_ready, orphaned=_torn_preshift_ready),
        # ---- fsck itself -----------------------------------------------
        DurableKeySpec(
            fsck_quarantine_annotation(driver, domain), "fsck",
            KIND_NODE_ANNOTATION, "<reason-slug>:<epoch>",
            REPAIR_PRESERVE,
            "the janitor's park-never-guess record; cleared by humans "
            "with the machines' re-arm inputs"),
    ]
    return DurableKeyRegistry(specs,
                              owned_prefixes=(f"{domain}/{driver}-",))


def fsck_quarantine_annotation(driver: str = "libtpu",
                               domain: str = "google.com") -> str:
    """NODE annotation ``<reason-slug>:<epoch>`` the janitor stamps when
    it parks a node whose durable state is ambiguous (garbled state
    label, unreadable cordon intent). Paired with both machines' skip
    labels in the same repair; a human clears all three after manual
    review."""
    return f"{domain}/{driver}-fsck.quarantined"
