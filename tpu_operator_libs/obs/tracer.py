"""Per-node upgrade-journey span trees (the tracing half of obs/).

A *journey* is one node's trip through the upgrade state machine:
opened when the node leaves ``upgrade-required`` for the flow (or is
discovered mid-flow by a fresh incarnation), closed when it reaches
``upgrade-done`` or is aborted back to ``upgrade-required``. Every
state dwell becomes a child span, so the trace reads as the causal
timeline an on-call reconstructs by hand today: admit → cordon →
wait-for-jobs → drain → pod-restart → validate → uncordon → done, with
abort / rollback / failure arcs appearing exactly where they happened.

Crash-atomicity comes for free from the seam this rides:
:meth:`UpgradeJourneyTracer.observe_transition` is installed as (part
of) the state provider's ``transition_observer``, which runs inside the
durable-commit path — the trace-id annotation it returns rides the SAME
merge patch as the state-label commit. A restarted operator (or the
next shard owner after a takeover) re-adopts the journey from the
trace-id annotation and the predictor's phase-start stamp alone: same
trace id, span clock resumed from the durable stamp, no residue when
the journey ends (the id is deleted on the closing transition's patch,
exactly like the phase stamps).

Memory is bounded: open journeys are O(in-flight nodes); completed
journeys live in a ring (``max_completed``).
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Optional

from tpu_operator_libs.consts import (
    IN_PROGRESS_STATES,
    UpgradeKeys,
    UpgradeState,
)
from tpu_operator_libs.upgrade.predictor import PHASE_OF_STATE, _parse_stamp
from tpu_operator_libs.util import Clock

if TYPE_CHECKING:  # pragma: no cover - types only
    from tpu_operator_libs.k8s.objects import Node

#: Label values during which a journey is open. FAILED is deliberately
#: included: a node parked in upgrade-failed is mid-journey (its dwell
#: is the evidence a retrospective wants), and the FAILED→drain
#: recovery arc continues the same trace.
_ACTIVE_STATES = frozenset(str(s) for s in IN_PROGRESS_STATES)

_DONE = str(UpgradeState.DONE)
_REQUIRED = str(UpgradeState.UPGRADE_REQUIRED)
_ABORT = str(UpgradeState.ABORT_REQUIRED)
_ROLLBACK = str(UpgradeState.ROLLBACK_REQUIRED)


def _hex_id(seed: str, nbytes: int) -> str:
    return hashlib.sha256(seed.encode()).hexdigest()[:nbytes * 2]


#: Per-process salt distinguishing id sequences across operator
#: incarnations (two incarnations both start their counters at 1; the
#: salt keeps an adopted journey's NEW span ids from colliding with the
#: dead owner's). Cheap counter ids, not hashes: the observer runs
#: inside the provider's commit path under the tracer lock, and a
#: sha256 per span measurably serialized 8 bucket workers at 1024
#: nodes.
_PROCESS_SALT = int.from_bytes(os.urandom(4), "big")


@dataclass(slots=True)
class Span:
    """One state dwell (or the journey root)."""

    name: str
    span_id: str
    parent_span_id: str
    start: float
    end: Optional[float] = None

    def as_dict(self) -> dict:
        out = {"name": self.name, "spanId": self.span_id,
               "startSeconds": round(self.start, 3)}
        if self.parent_span_id:
            out["parentSpanId"] = self.parent_span_id
        if self.end is not None:
            out["endSeconds"] = round(self.end, 3)
            out["durationSeconds"] = round(self.end - self.start, 3)
        return out


@dataclass
class Journey:
    """One node's span tree for one trip through the flow."""

    trace_id: str
    node: str
    root: Span
    spans: list[Span] = field(default_factory=list)
    outcome: str = ""  # "" while open; done|aborted|rollback at close
    #: True when a fresh incarnation adopted this journey mid-flow from
    #: the durable trace-id annotation (span clocks before adoption are
    #: reconstructed from the phase-start stamp, not observed).
    resumed: bool = False

    @property
    def open_span(self) -> Optional[Span]:
        for span in reversed(self.spans):
            if span.end is None:
                return span
        return None

    def as_dict(self) -> dict:
        return {
            "traceId": self.trace_id,
            "node": self.node,
            "outcome": self.outcome or "open",
            "resumed": self.resumed,
            "root": self.root.as_dict(),
            "spans": [s.as_dict() for s in self.spans],
        }


class UpgradeJourneyTracer:
    """Assembles per-node journeys from the transition-observer seam.

    Thread-safe: the observer runs on bucket-pool and async worker
    threads concurrently (the provider's commit path).
    """

    def __init__(self, keys: Optional[UpgradeKeys] = None,
                 clock: Optional[Clock] = None,
                 max_completed: int = 256,
                 max_exemplars: int = 64) -> None:
        self.keys = keys or UpgradeKeys()
        self._clock = clock or Clock()
        self._lock = threading.Lock()
        self._open: dict[str, Journey] = {}
        #: Deferred intermediate transitions (name, old, new, at):
        #: appended lock-free from the commit path (deque.append is
        #: atomic under the GIL) and materialized into spans on the
        #: next read or journey-boundary event. The majority of a
        #: node's transitions are intermediate, and doing their span
        #: bookkeeping inline held the tracer lock inside the
        #: provider's commit path ~5µs per transition — a measurable
        #: slice of pass time at 1024 nodes × 8 workers.
        self._pending: deque = deque()
        #: Closed journeys as nested tuples of scalars (see
        #: _journey_row): CPython untracks scalar-only tuples, so the
        #: ring costs generational GC nothing — a ring of live
        #: Journey/Span objects was rescanned on every gen2 collection
        #: (the measured bulk of obs overhead at 1024 nodes).
        self._completed: list[tuple] = []
        self._max_completed = max_completed
        #: (phase, seconds, trace_id) of recently closed phase spans —
        #: the exemplar feed for the phase-duration histograms.
        self._exemplars: list[tuple[str, float, str]] = []
        self._max_exemplars = max_exemplars
        #: phase -> trace id of the most recently closed span of that
        #: phase (exemplar attachment for already-drained samples).
        self._last_trace_by_phase: dict[str, str] = {}
        #: trace id of the most recent journey this tracer touched —
        #: the pass-duration histogram's exemplar.
        self.last_touched_trace_id: Optional[str] = None
        self._seq = 0
        #: lifetime accounting (metrics feed)
        self.journeys_opened_total = 0
        self.journeys_resumed_total = 0
        self.spans_closed_total = 0
        self.completed_by_outcome: dict[str, int] = {}

    # ------------------------------------------------------------------
    # observer side (provider transition seam)
    # ------------------------------------------------------------------
    def observe_transition(self, node: "Node", old_label: str,
                           new_label: str,
                           ) -> "Optional[dict[str, Optional[str]]]":
        """Open/advance/close the node's journey for one durable state
        transition; returns annotation updates (trace-id stamp or its
        deletion) to ride the transition's merge patch."""
        active_old = old_label in _ACTIVE_STATES
        active_new = new_label in _ACTIVE_STATES
        annotations = node.metadata.annotations
        trace_key = self.keys.trace_id_annotation
        if not active_old and not active_new:
            # idle-side transition (unknown <-> required <-> done):
            # nothing to trace — the lock-free fast path the fleet's
            # triage churn rides. Clear any orphaned id left by a
            # crashed close (belt and suspenders — the close deletes
            # it on the same patch).
            if trace_key in annotations:
                return {trace_key: None}
            return None
        now = self._clock.now()
        name = node.metadata.name
        if active_old and active_new and name in self._open:
            # intermediate transition of a known journey: nothing to
            # stamp — defer the span bookkeeping out of the commit
            # path (GIL-safe dict read + atomic deque append, no lock)
            self._pending.append((name, old_label, new_label, now))
            return None
        updates: dict[str, Optional[str]] = {}
        with self._lock:
            self._materialize_locked()
            journey = self._open.get(name)
            if journey is None and active_old:
                # fresh incarnation / shard takeover: adopt the journey
                # from durable state — same trace id, span clock from
                # the crash-atomic phase-start stamp
                journey = self._adopt(name, old_label, annotations, now)
                if annotations.get(trace_key) != journey.trace_id:
                    updates[trace_key] = journey.trace_id
            if active_new and journey is None:
                journey = self._open_journey(name, now)
                updates[trace_key] = journey.trace_id
            if journey is None:
                if trace_key in annotations:
                    updates[trace_key] = None
                return updates or None
            self.last_touched_trace_id = journey.trace_id
            open_span = journey.open_span
            if open_span is not None and open_span.name != new_label:
                self._close_span(journey, open_span, now)
            if active_new:
                if open_span is None or open_span.name != new_label:
                    journey.spans.append(Span(
                        name=new_label, span_id=self._span_id(name, now),
                        parent_span_id=journey.root.span_id, start=now))
            else:
                self._close_journey(journey, new_label, now)
                updates[trace_key] = None
        return updates or None

    def _trace_id(self) -> str:
        # 32-hex OTLP trace id from (process salt, counter, clock) —
        # unique without hashing (called with the lock held)
        self._seq += 1
        return (f"{_PROCESS_SALT:08x}{self._seq & 0xFFFFFFFFFFFF:012x}"
                f"{int(self._clock.now() * 1e3) & 0xFFFFFFFFFFFF:012x}")

    def _materialize_locked(self) -> None:
        """Fold deferred intermediate transitions into their journeys'
        span lists (call with the lock held). Per-node ordering is the
        provider's per-node commit order (its KeyedLock serializes a
        node's transitions); cross-node interleaving is irrelevant —
        spans carry their own observation timestamps."""
        while True:
            try:
                name, _old, new_label, at = self._pending.popleft()
            except IndexError:
                return
            journey = self._open.get(name)
            if journey is None:
                continue
            self.last_touched_trace_id = journey.trace_id
            open_span = journey.open_span
            if open_span is not None and open_span.name != new_label:
                self._close_span(journey, open_span, at)
            if open_span is None or open_span.name != new_label:
                journey.spans.append(Span(
                    name=new_label, span_id=self._span_id(name, at),
                    parent_span_id=journey.root.span_id, start=at))

    def _open_journey(self, name: str, now: float) -> Journey:
        trace_id = self._trace_id()
        root = Span(name="upgrade-journey",
                    span_id=self._span_id(name, now),
                    parent_span_id="", start=now)
        journey = Journey(trace_id=trace_id, node=name, root=root)
        self._open[name] = journey
        self.journeys_opened_total += 1
        return journey

    def _adopt(self, name: str, old_label: str,
               annotations: "dict[str, str]", now: float) -> Journey:
        trace_id = annotations.get(self.keys.trace_id_annotation)
        stamp_phase, stamp_at = _parse_stamp(
            annotations.get(self.keys.phase_start_annotation))
        # the durable stamp bounds the open span's start; without one
        # (predictor disabled) the adoption instant is the honest floor
        start = stamp_at if stamp_phase is not None else now
        if not trace_id:
            trace_id = self._trace_id()
        root = Span(name="upgrade-journey",
                    span_id=self._span_id(name, start),
                    parent_span_id="", start=start)
        journey = Journey(trace_id=trace_id, node=name, root=root,
                          resumed=True)
        journey.spans.append(Span(
            name=old_label, span_id=self._span_id(name, now),
            parent_span_id=root.span_id, start=start))
        self._open[name] = journey
        self.journeys_opened_total += 1
        self.journeys_resumed_total += 1
        return journey

    def _span_id(self, name: str, now: float) -> str:
        # 16-hex OTLP span id (called with the lock held)
        self._seq += 1
        return (f"{_PROCESS_SALT & 0xFFFFFF:06x}"
                f"{self._seq & 0xFFFFFFFFFF:010x}")

    def _close_span(self, journey: Journey, span: Span,
                    now: float) -> None:
        span.end = now
        self.spans_closed_total += 1
        phase = PHASE_OF_STATE.get(span.name)
        if phase is not None:
            self._exemplars.append((phase, now - span.start,
                                    journey.trace_id))
            del self._exemplars[:-self._max_exemplars]
            self._last_trace_by_phase[phase] = journey.trace_id

    @staticmethod
    def _span_row(span: Span) -> tuple:
        return (span.name, span.span_id, span.parent_span_id,
                span.start, span.end)

    @staticmethod
    def _row_as_dict(row: tuple) -> dict:
        name, span_id, parent, start, end = row
        out = {"name": name, "spanId": span_id,
               "startSeconds": round(start, 3)}
        if parent:
            out["parentSpanId"] = parent
        if end is not None:
            out["endSeconds"] = round(end, 3)
            out["durationSeconds"] = round(end - start, 3)
        return out

    @staticmethod
    def _journey_as_dict(row: tuple) -> dict:
        trace_id, node, outcome, resumed, root, spans = row
        return {
            "traceId": trace_id,
            "node": node,
            "outcome": outcome or "open",
            "resumed": resumed,
            "root": UpgradeJourneyTracer._row_as_dict(root),
            "spans": [UpgradeJourneyTracer._row_as_dict(s)
                      for s in spans],
        }

    def _close_journey(self, journey: Journey, new_label: str,
                       now: float) -> None:
        journey.root.end = now
        last = journey.spans[-1].name if journey.spans else ""
        if new_label == _DONE:
            outcome = "done"
        elif new_label == _REQUIRED:
            outcome = "aborted" if last == _ABORT else "rolled-back" \
                if last == _ROLLBACK else "returned"
        else:
            outcome = new_label or "unknown"
        journey.outcome = outcome
        self._open.pop(journey.node, None)
        self._completed.append((
            journey.trace_id, journey.node, outcome, journey.resumed,
            self._span_row(journey.root),
            tuple(self._span_row(s) for s in journey.spans)))
        del self._completed[:-self._max_completed]
        self.completed_by_outcome[outcome] = \
            self.completed_by_outcome.get(outcome, 0) + 1

    # ------------------------------------------------------------------
    # read side
    # ------------------------------------------------------------------
    def spans_for(self, node_name: str, limit: int = 3) -> "list[dict]":
        """The node's recent span history: its open journey (if any)
        plus its most recent completed journeys, newest first."""
        with self._lock:
            self._materialize_locked()
            out: list[dict] = []
            open_journey = self._open.get(node_name)
            if open_journey is not None:
                out.append(open_journey.as_dict())
            for row in reversed(self._completed):
                if len(out) >= limit:
                    break
                if row[1] == node_name:
                    out.append(self._journey_as_dict(row))
            return out

    def drain_phase_exemplars(self) -> "list[tuple[str, float, str]]":
        """(phase, seconds, trace_id) of phase spans closed since the
        last drain — the exemplar feed for observe_journeys."""
        with self._lock:
            self._materialize_locked()
            out = self._exemplars
            self._exemplars = []
            return out

    def last_trace_for_phase(self, phase: str) -> Optional[str]:
        with self._lock:
            self._materialize_locked()
            return self._last_trace_by_phase.get(phase)

    @property
    def open_journeys(self) -> int:
        with self._lock:
            self._materialize_locked()
            return len(self._open)

    def summary(self) -> dict:
        """Per-pass roll-up for ``cluster_status["trace"]``: open/
        completed counts, outcome split, duration percentiles over the
        retained ring, and the most recent closed journeys."""
        with self._lock:
            self._materialize_locked()
            durations = sorted(
                row[4][4] - row[4][3] for row in self._completed
                if row[4][4] is not None)
            recent = [{
                "node": row[1], "traceId": row[0],
                "outcome": row[2],
                "seconds": round(row[4][4] - row[4][3], 3)
                if row[4][4] is not None else None,
            } for row in self._completed[-5:]][::-1]
            summary = {
                "openJourneys": len(self._open),
                "completedRetained": len(self._completed),
                "journeysOpenedTotal": self.journeys_opened_total,
                "journeysResumedTotal": self.journeys_resumed_total,
                "byOutcome": dict(sorted(
                    self.completed_by_outcome.items())),
            }
            if durations:
                summary["p50Seconds"] = round(
                    durations[len(durations) // 2], 3)
                summary["p95Seconds"] = round(
                    durations[min(len(durations) - 1,
                                  int(len(durations) * 0.95))], 3)
            if recent:
                summary["recent"] = recent
            return summary

    def dump_traces(self) -> dict:
        """Every retained journey as OTLP-shaped JSON (resourceSpans →
        scopeSpans → spans; times in unix nanos of the operator clock,
        which is the virtual clock under simulation)."""
        def nanos(seconds: Optional[float]) -> Optional[int]:
            return None if seconds is None else int(seconds * 1e9)

        def otlp_span(trace_id: str, node: str, outcome: str,
                      span_row: tuple) -> dict:
            name, span_id, parent, start, end = span_row
            out = {
                "traceId": trace_id,
                "spanId": span_id,
                "name": name,
                "startTimeUnixNano": nanos(start),
                "attributes": [
                    {"key": "node", "value": {"stringValue": node}},
                ],
            }
            if parent:
                out["parentSpanId"] = parent
            if end is not None:
                out["endTimeUnixNano"] = nanos(end)
            if not parent and outcome:
                out["status"] = {
                    "code": "STATUS_CODE_OK" if outcome == "done"
                    else "STATUS_CODE_ERROR",
                    "message": outcome,
                }
            return out

        with self._lock:
            self._materialize_locked()
            rows = list(self._completed) + [
                (j.trace_id, j.node, j.outcome, j.resumed,
                 self._span_row(j.root),
                 tuple(self._span_row(s) for s in j.spans))
                for j in self._open.values()]
            spans = [
                otlp_span(trace_id, node, outcome, span_row)
                for trace_id, node, outcome, _resumed, root, children
                in rows
                for span_row in (root,) + children]
        return {"resourceSpans": [{
            "resource": {"attributes": [
                {"key": "service.name",
                 "value": {"stringValue":
                           f"{self.keys.driver}-upgrade-operator"}},
            ]},
            "scopeSpans": [{
                "scope": {"name": "tpu_operator_libs.obs"},
                "spans": spans,
            }],
        }]}
