"""Upgrade-journey tracing and decision auditing.

The operator makes layered, interacting per-node decisions — shard
ownership, planner rank, maintenance window, capacity budget,
canary/rollout halt, slice constraints — and each layer already exports
gauges. What gauges cannot answer is the 3am question: *why is node X
not upgrading, and what happened to the nodes that did?* This package
is the layer that answers it:

- :class:`~tpu_operator_libs.obs.tracer.UpgradeJourneyTracer` — per-node
  span trees (admit → cordon → drain → pod-restart → validate → done,
  plus the abort/rollback/failure arcs) assembled from the state
  provider's ``transition_observer`` seam and the predictor's
  crash-atomic phase-start stamps, so a journey survives operator
  restarts and shard takeovers. Exported as OTLP-shaped JSON
  (``dump_traces()``) and summarized per pass in
  ``cluster_status["trace"]``.
- :class:`~tpu_operator_libs.obs.audit.DecisionAudit` — a bounded
  ring-buffer recorder threaded through every decision point in
  ``apply_state`` (budget/capacity clamp, planner rank, window defer,
  canary freeze, shard split, abort trigger); each record carries the
  decision, its numeric inputs and the winning rule.
- ``ClusterUpgradeStateManager.explain(node)`` — the public API over
  both: the node's current blocking-reason chain plus its recent span
  history, served at ``/explain/<node>`` by the example operators and
  probed by the chaos gates (every parked node must explain itself).

Install via ``manager.with_observability(OperatorObservability(keys,
clock=clock))``; without it, not a single extra annotation is written
and behavior is reference-identical.
"""

from __future__ import annotations

from typing import Callable, Optional

from tpu_operator_libs.consts import UpgradeKeys
from tpu_operator_libs.obs.audit import DecisionAudit, DecisionRecord
from tpu_operator_libs.obs.tracer import UpgradeJourneyTracer
from tpu_operator_libs.util import Clock

__all__ = [
    "DecisionAudit",
    "DecisionRecord",
    "OperatorObservability",
    "UpgradeJourneyTracer",
]


class OperatorObservability:
    """One operator incarnation's observability bundle: the journey
    tracer + the decision audit, plus the optional cross-replica
    explain router.

    ``peer_resolver`` (sharded deployments): ``shard -> object with an
    explain(node_name) method`` (typically the owning replica's state
    manager); ``ClusterUpgradeStateManager.explain`` routes a
    non-owned node's query through it. Without a resolver the local
    explain still answers from durable node state — the ring buffer
    that died with a deposed owner is not required for a non-empty
    blocking chain (see the handover regression in tests/test_obs.py).
    """

    def __init__(self, keys: Optional[UpgradeKeys] = None,
                 clock: Optional[Clock] = None,
                 max_completed_journeys: int = 256,
                 max_audit_records: int = 8192) -> None:
        self.keys = keys or UpgradeKeys()
        self.clock = clock or Clock()
        self.tracer = UpgradeJourneyTracer(
            self.keys, clock=self.clock,
            max_completed=max_completed_journeys)
        self.audit = DecisionAudit(max_records=max_audit_records,
                                   clock=self.clock)
        #: shard -> explain()-bearing peer (see class docstring).
        self.peer_resolver: Optional[Callable[[int], object]] = None
        #: Bound (REAL seconds) on one routed peer-explain attempt —
        #: the cross-replica hop is an HTTP call to the owning
        #: replica's /explain in production, and a slow or dead peer
        #: must degrade to the durable-label fallback instead of
        #: stalling the caller's request (explain is the mid-incident
        #: tool; an explain that hangs during the incident is worse
        #: than none).
        self.peer_timeout_seconds: float = 2.0
        #: Retries after the first failed/timed-out peer attempt (one
        #: retry absorbs a transient hiccup; anything more just delays
        #: the fallback).
        self.peer_retries: int = 1

    def dump_traces(self) -> dict:
        """OTLP-shaped JSON export of every retained journey."""
        return self.tracer.dump_traces()
