"""Bounded ring-buffer decision recorder (the audit half of obs/).

Every decision point in ``apply_state`` records what it decided, the
numeric inputs it decided FROM, and the winning rule:

- ``budget`` — the pass's slot math (static vs capacity-effective
  budget, maxParallel, in-progress, the freeze);
- ``shard-split`` — the global budget's durable per-shard split and
  clamp;
- ``canary`` — canary-wave restriction / fleet halt;
- ``admit`` / ``hold`` — the planner's per-candidate verdict (LPT rank
  for admits; the blocking rule for holds);
- ``window`` — maintenance-window admit/defer with the predicted
  completion;
- ``abort`` / ``aborted`` — mid-flight abort trigger and completion.

The buffer is deliberately in-memory and bounded (it dies with the
process — durable truth stays on node labels/annotations, where
``explain`` falls back when the ring is gone, e.g. after a shard
takeover). ``mirror`` lets a harness keep its own cross-incarnation
log: the chaos monitor wires it to audit every observed
admission/abort edge against a matching record.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Optional

from tpu_operator_libs.util import Clock

#: kinds that concern the whole fleet (returned by latest_fleet and
#: folded into every node's explain chain).
FLEET_KINDS = ("budget", "canary", "shard-split", "pass")


@dataclass(slots=True)
class DecisionRecord:
    """One decision, with everything needed to re-derive it."""

    seq: int
    pass_seq: int
    at: float
    kind: str
    node: str  # "" for fleet-level decisions
    decision: str
    rule: str
    inputs: dict = field(default_factory=dict)

    def as_dict(self) -> dict:
        return {"seq": self.seq, "pass": self.pass_seq,
                "at": round(self.at, 3), "kind": self.kind,
                "node": self.node, "decision": self.decision,
                "rule": self.rule, "inputs": dict(self.inputs)}

    def describe(self) -> str:
        subject = self.node or "fleet"
        inputs = ", ".join(f"{key}={value}" for key, value
                           in sorted(self.inputs.items()))
        return (f"[t={self.at:g} pass={self.pass_seq}] {self.kind} "
                f"{subject}: {self.decision} ({self.rule})"
                + (f" [{inputs}]" if inputs else ""))


def _flatten_value(value):
    """Scalars pass through; lists/tuples/dicts become nested tuples —
    the ring must hold only GC-untrackable shapes (see class
    docstring)."""
    if isinstance(value, (list, tuple)):
        return tuple(_flatten_value(v) for v in value)
    if isinstance(value, dict):
        return tuple(sorted(
            (k, _flatten_value(v)) for k, v in value.items()))
    return value


class DecisionAudit:
    """Thread-safe bounded decision ring.

    ``mirror`` (optional) is called with every record OUTSIDE the
    ring's retention — a monitor-held log that survives the recorder's
    process; a mirror failure never blocks the decision.

    Storage is flat tuples of scalars, rehydrated into
    :class:`DecisionRecord` on read. Not a style choice: CPython's GC
    *untracks* tuples that contain only untracked objects, while a
    ring of 8k dataclass+dict records is ~30k tracked objects rescanned
    on every gen2 collection — measured as most of the observability
    layer's pass-time overhead at 1024 nodes (the same generational-GC
    amplification ``OperatorManager.gc_freeze_after_sync`` exists
    for)."""

    def __init__(self, max_records: int = 8192,
                 clock: Optional[Clock] = None) -> None:
        self._clock = clock or Clock()
        self._lock = threading.Lock()
        #: (seq, pass_seq, at, kind, node, decision, rule, inputs_kv)
        self._records: list[tuple] = []
        self._max_records = max_records
        self.mirror: Optional[Callable[[DecisionRecord], None]] = None
        #: node -> rule of its most recent hold record (record_hold's
        #: dedup memory; cleared by an admit/abort for the node).
        self._last_hold_rule: dict[str, str] = {}
        #: lifetime accounting (metrics feed)
        self.records_total = 0
        self.dropped_total = 0
        self.pass_seq = 0

    def begin_pass(self) -> int:
        """Mark the start of one apply_state pass; fleet-level records
        of the same pass share the returned sequence number."""
        with self._lock:
            self.pass_seq += 1
            return self.pass_seq

    def record_hold(self, node: str, rule: str,
                    inputs: "Optional[dict]" = None) -> None:
        """Record a planner hold, deduplicated on the blocking rule: a
        node parked behind the same gate for 50 passes is ONE fact,
        not 50 records — the dedup keeps a 1024-node fleet's steady
        passes from churning the ring (and the audit overhead under
        the bench's 3% budget) while a rule CHANGE (budget→canary)
        still lands a fresh record. Any admit/abort record for the
        node re-arms it.

        The unchanged-rule check is deliberately lock-free (a GIL-safe
        dict read): the planner calls this once per held candidate per
        pass — O(fleet) — and taking the ring lock a thousand times a
        pass was a measurable slice of the obs overhead budget. The
        worst race is one duplicate hold record, which the ring
        tolerates by design."""
        if self._last_hold_rule.get(node) == rule:
            return
        with self._lock:
            self._last_hold_rule[node] = rule
        self.record("hold", node, decision="hold", rule=rule,
                    inputs=inputs)

    def record_holds(self, nodes: "list[str]", rule: str,
                     inputs: "Optional[dict]" = None) -> None:
        """Batch :meth:`record_hold` for a uniform rule: one C-speed
        pass finds the changed nodes, and only those pay the record
        path — the per-call overhead of a thousand no-op
        ``record_hold`` calls per pass was itself a visible slice of
        the obs overhead budget."""
        last = self._last_hold_rule
        changed = [node for node in nodes if last.get(node) != rule]
        for node in changed:
            self.record_hold(node, rule, inputs)

    def record(self, kind: str, node: str, decision: str, rule: str,
               inputs: "Optional[dict]" = None,
               ) -> Optional[DecisionRecord]:
        """Record one decision. Returns the rehydrated record only
        when a mirror is installed (the harness path) — production
        callers discard it, and rehydrating thousands of wave-time
        records nobody reads is measurable overhead."""
        flat_inputs = _flatten_value(inputs) if inputs else ()
        with self._lock:
            if node and kind != "hold":
                # a non-hold decision supersedes the hold-dedup memory:
                # the next hold is a NEW fact worth a fresh record
                self._last_hold_rule.pop(node, None)
            self.records_total += 1
            row = (self.records_total, self.pass_seq,
                   self._clock.now(), kind, node, decision, rule,
                   flat_inputs)
            self._records.append(row)
            if len(self._records) > self._max_records:
                overflow = len(self._records) - self._max_records
                del self._records[:overflow]
                self.dropped_total += overflow
            mirror = self.mirror
        if mirror is None:
            return None
        rec = self._rehydrate(row)
        try:
            mirror(rec)
        except Exception:  # noqa: BLE001 — a harness hook must
            pass  # never block the decision path
        return rec

    @staticmethod
    def _rehydrate(row: tuple) -> DecisionRecord:
        seq, pass_seq, at, kind, node, decision, rule, inputs_kv = row

        def thaw(value):
            if isinstance(value, tuple):
                if value and all(isinstance(item, tuple)
                                 and len(item) == 2
                                 and isinstance(item[0], str)
                                 for item in value):
                    return {k: thaw(v) for k, v in value}
                return [thaw(item) for item in value]
            return value

        return DecisionRecord(
            seq=seq, pass_seq=pass_seq, at=at, kind=kind, node=node,
            decision=decision, rule=rule,
            inputs=thaw(inputs_kv) if inputs_kv else {})

    # ------------------------------------------------------------------
    # read side
    # ------------------------------------------------------------------
    def records_for(self, node: str,
                    limit: int = 10) -> "list[DecisionRecord]":
        """The node's most recent records, newest first."""
        with self._lock:
            rows = [row for row in reversed(self._records)
                    if row[4] == node][:limit]
        return [self._rehydrate(row) for row in rows]

    def latest_fleet(self) -> "dict[str, DecisionRecord]":
        """kind -> most recent fleet-level record (newest pass wins)."""
        rows: dict[str, tuple] = {}
        with self._lock:
            for row in reversed(self._records):
                if not row[4] and row[3] in FLEET_KINDS \
                        and row[3] not in rows:
                    rows[row[3]] = row
                    if len(rows) == len(FLEET_KINDS):
                        break
        return {kind: self._rehydrate(row)
                for kind, row in rows.items()}

    def tail(self, limit: int = 50) -> "list[DecisionRecord]":
        with self._lock:
            rows = list(self._records[-limit:])
        return [self._rehydrate(row) for row in rows]

    @property
    def retained(self) -> int:
        with self._lock:
            return len(self._records)
