"""tpu_operator_libs: TPU-native Kubernetes operator support libraries.

A from-scratch, TPU-first re-design of the capability surface of
NVIDIA's ``k8s-operator-libs`` (reference: /root/reference): a cluster-wide,
per-node rolling-upgrade state machine for accelerator-runtime DaemonSets
(libtpu / TPU device plugin on GKE TPU node pools), with cordon / drain /
pod-deletion / validation / safe-load managers, a declarative upgrade policy,
and durable state recorded in node labels so every reconcile is stateless and
idempotent (reference: pkg/upgrade/upgrade_state.go:68-72).

Beyond the reference's capability surface this package adds what TPU hardware
demands:

- ICI-topology-aware upgrade planning: on multi-host TPU slices nodes are not
  independent (draining one host idles the whole ICI domain), so the upgrade
  unit is a sub-slice, not a node (``tpu_operator_libs.topology``).
- A JAX-native ICI fabric health gate run before uncordoning upgraded nodes
  (``tpu_operator_libs.health``), replacing the reference's OFED/RDMA story.
- An Orbax checkpoint-durability gate so live JAX training jobs are only
  evicted once their latest checkpoint is committed
  (``tpu_operator_libs.health.checkpoint_gate``).
- An auto-remediation subsystem — the unplanned-fault dual of the
  upgrade machine: wedge detection (NotReady kubelets, crash-looping
  libtpu pods, stuck-Terminating workloads, node-problem-detector
  conditions) with durable debounce, and a quarantine → drain →
  runtime-restart → reboot → revalidate escalation ladder
  (``tpu_operator_libs.remediation``).
"""

__version__ = "0.1.0"

from tpu_operator_libs.consts import (  # noqa: F401
    RemediationState,
    UpgradeState,
)
from tpu_operator_libs.api.remediation_policy import (  # noqa: F401
    RemediationPolicySpec,
    WedgeDetectionSpec,
)
from tpu_operator_libs.api.upgrade_policy import (  # noqa: F401
    DrainSpec,
    PodDeletionSpec,
    UpgradePolicySpec,
    WaitForCompletionSpec,
)
