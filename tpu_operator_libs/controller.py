"""Watch-driven controller runtime: work queue, informers, reconcile loop.

The reference library has no main loop of its own — it is embedded in a
controller built with sigs.k8s.io/controller-runtime, which supplies the
informer caches, the rate-limited work queue, and the "any relevant event
enqueues a reconcile" wiring (SURVEY.md §1 L0/L5). This build owns its
substrate, so those pieces live here, shaped like their client-go
namesakes:

- :class:`ExponentialBackoffRateLimiter` — per-key exponential backoff
  (client-go ``workqueue.DefaultControllerRateLimiter`` semantics).
- :class:`WorkQueue` — deduplicating delaying queue with the three-set
  (dirty/queue/processing) contract: adds while a key is being processed
  mark it dirty and re-enqueue it on :meth:`WorkQueue.done`, so a burst of
  events coalesces into at most one queued reconcile per key.
- :class:`Informer` — list+watch cache with add/update/delete handlers and
  a ``has_synced`` barrier.
- :class:`Controller` — wires watches → keys → work queue → the consumer's
  reconcile function, with error backoff and periodic resync, replacing
  the fixed-interval polling loop a consumer would otherwise write
  (examples/libtpu_operator.py uses it in live mode).

The upgrade flow itself stays cluster-scoped: one reconcile key
(:data:`CLUSTER_KEY`) covers BuildState+ApplyState, exactly like the
reference consumer's singleton reconcile (SURVEY.md §3.1).
"""

from __future__ import annotations

import heapq
import logging
import random
import threading
import time
from dataclasses import dataclass
from typing import TYPE_CHECKING, Callable, Optional

from tpu_operator_libs.k8s.watch import (
    BOOKMARK,
    DELETED,
    EXPIRED,
    Watch,
    WatchEvent,
)

if TYPE_CHECKING:
    from tpu_operator_libs.metrics import MetricsRegistry

logger = logging.getLogger(__name__)

#: The single reconcile key for cluster-scoped upgrade controllers.
CLUSTER_KEY = "cluster"


def _cluster_key_fn(_event: "WatchEvent") -> str:
    """Default key function: every event maps to the cluster singleton.
    Identity-compared in the pump to exempt the singleton from
    DELETED-event key forgetting."""
    return CLUSTER_KEY


class ExponentialBackoffRateLimiter:
    """Per-key exponential backoff: base * 2^retries, capped + jittered.

    Defaults match client-go's item-bucket limiter (5 ms base, 16 m 40 s
    cap is client-go's 1000 s; we default the cap lower because driver
    upgrades re-reconcile anyway on the next event).

    ``jitter`` randomizes that fraction of each delay (AWS "full jitter"
    at the default 1.0: delay ~ U(0, base*2^n]). A purely deterministic
    schedule synchronizes every failed key — and, worse, every replica
    of the operator fleet retrying the same outage — into aligned retry
    waves that thundering-herd the apiserver exactly when it is least
    healthy. Pass ``jitter=0.0`` for the deterministic schedule (tests).
    """

    def __init__(self, base: float = 0.005, max_delay: float = 60.0,
                 jitter: float = 1.0,
                 rng: Optional[random.Random] = None) -> None:
        if base <= 0:
            raise ValueError("base must be positive")
        if not 0.0 <= jitter <= 1.0:
            raise ValueError("jitter must be in [0, 1]")
        self._base = base
        self._max = max_delay
        self._jitter = jitter
        self._rng = rng or random.Random()
        self._retries: dict[str, int] = {}
        self._lock = threading.Lock()

    def when(self, key: str) -> float:
        """Delay before the next retry of ``key``; increments the count."""
        with self._lock:
            n = self._retries.get(key, 0)
            self._retries[key] = n + 1
            delay = min(self._base * (2 ** n), self._max)
            if self._jitter:
                # rng under the lock: random.Random is not thread-safe
                delay *= 1.0 - self._jitter * self._rng.random()
        return delay

    def forget(self, key: str) -> None:
        with self._lock:
            self._retries.pop(key, None)

    def retries(self, key: str) -> int:
        with self._lock:
            return self._retries.get(key, 0)


class WorkQueue:
    """Deduplicating, delaying work queue (client-go workqueue contract).

    Invariants:
    - A key is queued at most once at a time; adding an already-queued key
      is a no-op (event bursts coalesce).
    - Adding a key that is currently being processed marks it dirty; it is
      re-queued when :meth:`done` is called — no update is ever lost, and
      no key is processed concurrently with itself.
    """

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._queue: list[str] = []
        self._dirty: set[str] = set()
        self._processing: set[str] = set()
        self._delayed: list[tuple[float, int, str]] = []  # (due, seq, key)
        self._seq = 0
        self._shutdown = False

    # -- producers -------------------------------------------------------
    def add(self, key: str) -> None:
        with self._cond:
            if self._shutdown or key in self._dirty:
                return
            self._dirty.add(key)
            if key in self._processing:
                return
            self._queue.append(key)
            self._cond.notify()

    def add_after(self, key: str, delay: float) -> None:
        if delay <= 0:
            self.add(key)
            return
        with self._cond:
            if self._shutdown:
                return
            self._seq += 1
            heapq.heappush(self._delayed,
                           (time.monotonic() + delay, self._seq, key))
            self._cond.notify()

    # -- consumer --------------------------------------------------------
    def _promote_due(self) -> Optional[float]:
        """Move due delayed items into the queue; return seconds until the
        next delayed item, or None. Caller holds the lock."""
        now = time.monotonic()
        while self._delayed and self._delayed[0][0] <= now:
            _, _, key = heapq.heappop(self._delayed)
            if key not in self._dirty:
                self._dirty.add(key)
                if key not in self._processing:
                    self._queue.append(key)
        if self._delayed:
            return max(self._delayed[0][0] - now, 0.0)
        return None

    def get(self, timeout: Optional[float] = None) -> Optional[str]:
        """Next key (marking it processing), or None on timeout/shutdown."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                next_delay = self._promote_due()
                if self._queue:
                    key = self._queue.pop(0)
                    self._dirty.discard(key)
                    self._processing.add(key)
                    return key
                if self._shutdown:
                    return None
                wait = next_delay
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                    wait = remaining if wait is None else min(wait, remaining)
                self._cond.wait(wait)

    def done(self, key: str) -> None:
        with self._cond:
            self._processing.discard(key)
            if key in self._dirty:
                self._queue.append(key)
                self._cond.notify()

    # -- lifecycle -------------------------------------------------------
    def shut_down(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()

    def __len__(self) -> int:
        with self._cond:
            return len(self._queue) + len(self._delayed)


def default_key_fn(obj: object) -> tuple[str, str]:
    meta = getattr(obj, "metadata")
    return (getattr(meta, "namespace", "") or "", meta.name)


# How long a deletion tombstone can outlive its key before _apply prunes
# it. Only a refresh() whose list started before the tombstone needs it;
# no list takes 10 minutes, so this is safely conservative while keeping
# _last_applied bounded even with periodic relisting disabled.
_TOMBSTONE_TTL = 600.0
# Sweep cadence for the amortized tombstone prune in _apply (the sweep is
# O(len(_last_applied)) under _store_lock, so not on every delete).
_TOMBSTONE_PRUNE_EVERY = 64


class Informer:
    """List+watch cache for one object kind.

    ``lister`` provides the initial snapshot (fires add handlers, like a
    client-go informer's initial sync); ``watch`` streams subsequent
    events. The store always holds snapshot copies.

    **Lister freshness requirement**: ``refresh()`` treats the list
    snapshot as at-least-as-fresh as the moment the list started — a key
    absent from the store with a pre-list tombstone but present in the
    snapshot is taken to mean the object was *recreated* (lost watch
    ADD), and is resurrected. That inference only holds for quorum
    reads: a lister backed by a stale cache (e.g. a real apiserver list
    at ``resourceVersion=0``, which may be served from any replica's
    watch cache) can return a snapshot predating a delivered DELETE and
    would silently undo it. Listers plugged in here must issue quorum
    list requests (client-go's default of ``resourceVersion=""``), never
    ``resourceVersion=0``.
    """

    def __init__(self, lister: Callable[[], list], watch: Watch,
                 key_fn: Callable[[object], tuple[str, str]] = default_key_fn,
                 name: str = "informer",
                 threaded: bool = True,
                 ingest_filter: Optional[Callable[[object], bool]] = None,
                 rewatch: Optional[Callable[[], Watch]] = None) -> None:
        self._lister = lister
        self._watch = watch
        self._key_fn = key_fn
        self._name = name
        # Unthreaded drive mode: start() performs the initial list
        # inline and events apply only on pump() — the deterministic
        # single-threaded discipline the virtual-clock benches and the
        # chaos harness need (a background pump racing a FakeClock
        # would make snapshot content depend on thread scheduling).
        self._threaded = threaded
        # Ingest filter (partition pushdown seam): objects rejected by
        # the predicate never enter the store — a listed/added object is
        # skipped, a MODIFIED of a stored key that stopped matching is
        # converted to a delete. The predicate may change its answers
        # over time (shard ownership moves); callers must refresh()
        # after such a change, because dropped events are gone.
        self._ingest_filter = ingest_filter
        # Re-subscribe seam for pump mode: a server-side stream drop
        # stops the Watch; with a factory the next pump() opens a fresh
        # stream and relists (the informer reconnect path).
        self._rewatch = rewatch
        # set when a pump-mode refresh failed (e.g. transient apiserver
        # error on an overflow BOOKMARK): retried on the next pump so
        # the consumed marker cannot strand the cache stale.
        self._needs_refresh = False
        #: 410-expired recoveries performed (observability): each EXPIRED
        #: marker that forced a relist + fresh watch bumps this.
        self.expired_relists = 0
        self._store: dict[tuple[str, str], object] = {}
        # Monotonic time of the last watch-event apply per key; deleted
        # keys keep their entry as a tombstone. refresh() consults these
        # so a list snapshot can never overwrite state applied after the
        # list began (client-go serializes Replace through DeltaFIFO for
        # the same reason).
        self._last_applied: dict[tuple[str, str], float] = {}
        self._deletes_since_prune = 0
        self._store_lock = threading.Lock()
        self._synced = threading.Event()
        self._handlers: list[tuple[
            Optional[Callable[[object], None]],
            Optional[Callable[[object, object], None]],
            Optional[Callable[[object], None]]]] = []
        self._thread: Optional[threading.Thread] = None

    def add_event_handler(self,
                          on_add: Optional[Callable[[object], None]] = None,
                          on_update: Optional[Callable[[object, object], None]] = None,
                          on_delete: Optional[Callable[[object], None]] = None) -> None:
        self._handlers.append((on_add, on_update, on_delete))

    def start(self) -> None:
        if not self._threaded:
            if not self._synced.is_set():
                self._initial_list()
            return
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name=self._name, daemon=True)
        self._thread.start()

    def _initial_list(self) -> None:
        """Inline initial sync for unthreaded informers. Unlike the
        threaded path there is no retry loop: the caller owns pacing,
        and a deterministic harness wants the error, not a sleep."""
        for obj in self._lister():
            try:
                key = self._key_fn(obj)
            except Exception:
                logger.exception("%s: key function failed on listed "
                                 "object", self._name)
                continue
            if self._ingest_filter is not None \
                    and not self._ingest_filter(obj):
                continue
            with self._store_lock:
                self._store[key] = obj
            self._dispatch_add(obj)
        self._synced.set()

    def resubscribe(self) -> None:
        """Replace the watch stream through the ``rewatch`` factory and
        schedule a relist (pump mode only).

        The seam a server-side selector change rides on: when the
        subscription's selector must move (shard handover narrowing a
        partition watch), the OLD stream's events no longer describe
        the wanted view and a fresh subscription + relist is the only
        repair. Ordering matters for crash safety: the new stream is
        opened BEFORE the old one stops, so no event gap opens between
        the two, and the relist (applied through the ingest filter)
        retires cached objects the new selector no longer covers.
        Threaded informers cannot use this — their ``_run`` loop exits
        permanently when its watch stops."""
        if self._threaded:
            raise RuntimeError(f"{self._name}: resubscribe() is for "
                               f"unthreaded informers")
        if self._rewatch is None:
            raise RuntimeError(f"{self._name}: resubscribe() needs a "
                               f"rewatch factory")
        old = self._watch
        self._watch = self._rewatch()
        old.stop()
        self._needs_refresh = True

    def pump(self, max_events: Optional[int] = None) -> int:
        """Apply every queued watch event inline (unthreaded mode).

        Returns the number of events applied. A stopped watch is
        re-subscribed through the ``rewatch`` factory (plus a relist:
        the gap's deletes never replay); an overflow BOOKMARK triggers
        the same relist repair the threaded loop performs. A failed
        relist is remembered and retried on the next pump.
        """
        if self._threaded:
            raise RuntimeError(f"{self._name}: pump() is for "
                               f"unthreaded informers")
        applied = 0
        if self._watch.stopped and self._rewatch is not None:
            # Drain the dead stream's backlog before replacing it: an
            # in-band EXPIRED marker (410) must be observed here — it
            # is the difference between inferring a relist from a
            # silently closed stream and the server-declared expiry
            # the counters track. The backlog's regular events were
            # delivered before the stream died and apply normally; the
            # relist below heals anything after them.
            while True:
                event = self._watch.get(timeout=0.0)
                if event is None:
                    break
                if event.type == EXPIRED:
                    logger.warning("%s: watch cursor expired (410); "
                                   "relisting", self._name)
                    self.expired_relists += 1
                    continue
                if event.type == BOOKMARK:
                    continue  # the pending relist already repairs this
                applied += 1
                try:
                    self._apply(event)
                except Exception:
                    logger.exception("%s: failed to apply watch event",
                                     self._name)
            self._watch = self._rewatch()
            self._needs_refresh = True
        if self._needs_refresh:
            self._needs_refresh = False
            try:
                self.refresh()
            except Exception:
                self._needs_refresh = True
                raise
        while max_events is None or applied < max_events:
            event = self._watch.get(timeout=0.0)
            if event is None:
                break
            applied += 1
            if event.type == BOOKMARK:
                logger.warning("%s: watch overflow bookmark; relisting",
                               self._name)
                try:
                    self.refresh()
                except Exception:
                    self._needs_refresh = True
                    raise
                continue
            if event.type == EXPIRED:
                # 410 Gone: the server cannot replay the gap — the old
                # stream is dead. Open the fresh watch BEFORE relisting
                # (no event gap between stream and list), then relist.
                # Re-watching without relisting would loop 410 forever.
                logger.warning("%s: watch cursor expired (410); "
                               "relisting", self._name)
                self.expired_relists += 1
                if self._rewatch is not None:
                    self._watch = self._rewatch()
                try:
                    self.refresh()
                except Exception:
                    self._needs_refresh = True
                    raise
                continue
            try:
                self._apply(event)
            except Exception:
                logger.exception("%s: failed to apply watch event",
                                 self._name)
        return applied

    def _run(self) -> None:
        # The initial list retries with backoff like a client-go informer:
        # one transient API error at startup must not leave the cache
        # permanently empty with has_synced() never firing.
        backoff = 0.5
        while not self._watch.stopped:
            try:
                objects = self._lister()
                break
            except Exception:
                logger.exception("%s: initial list failed; retrying in "
                                 "%.1fs", self._name, backoff)
                if self._watch.stopped:
                    return
                time.sleep(backoff)
                backoff = min(backoff * 2, 30.0)
        else:
            return
        for obj in objects:
            try:
                key = self._key_fn(obj)
            except Exception:
                logger.exception("%s: key function failed on listed object",
                                 self._name)
                continue
            if self._ingest_filter is not None \
                    and not self._ingest_filter(obj):
                continue
            with self._store_lock:
                self._store[key] = obj
            self._dispatch_add(obj)
        self._synced.set()
        for event in self._watch:
            try:
                if event.type == BOOKMARK:
                    # a bounded watch dropped events on overflow: the
                    # cache may have missed adds/updates/deletes — only
                    # a relist repairs it
                    logger.warning("%s: watch overflow bookmark; "
                                   "relisting", self._name)
                    self.refresh()
                    continue
                if event.type == EXPIRED:
                    # 410 Gone: relist while draining; the stopped
                    # stream then ends this loop (threaded informers
                    # have no rewatch seam — the owner restarts them)
                    logger.warning("%s: watch cursor expired (410); "
                                   "relisting", self._name)
                    self.expired_relists += 1
                    self.refresh()
                    continue
                self._apply(event)
            except Exception:
                # one malformed event must not freeze the cache forever
                logger.exception("%s: failed to apply watch event",
                                 self._name)

    def _apply(self, event: WatchEvent) -> None:
        obj = event.object
        key = self._key_fn(obj)
        if event.type != DELETED and self._ingest_filter is not None \
                and not self._ingest_filter(obj):
            # the object does not (or no longer) belong in this cache:
            # drop it, and if an older version was stored, retire it
            # the same way a DELETED would
            with self._store_lock:
                if key not in self._store:
                    return
            event = WatchEvent(DELETED, event.kind, obj)
        if event.type == DELETED:
            with self._store_lock:
                old = self._store.pop(key, None)
                now = time.monotonic()
                self._last_applied[key] = now  # tombstone
                # Tombstones exist only to stop an in-flight refresh()
                # from resurrecting a concurrently-deleted key; one older
                # than any plausible list duration protects nothing.
                # refresh() prunes the tombstones it creates itself; this
                # amortized sweep bounds the watch-DELETED path even with
                # periodic relisting disabled (CachedReadClient
                # relist_interval=None). Amortized (every 64th delete)
                # because the sweep scans all of _last_applied — live
                # keys included — under _store_lock.
                self._deletes_since_prune += 1
                if self._deletes_since_prune >= _TOMBSTONE_PRUNE_EVERY:
                    self._deletes_since_prune = 0
                    cutoff = now - _TOMBSTONE_TTL
                    for k in [k for k, t in self._last_applied.items()
                              if t < cutoff and k not in self._store]:
                        del self._last_applied[k]
            for _, _, on_delete in self._handlers:
                if on_delete is not None:
                    self._safe(on_delete, old if old is not None else obj)
            return
        with self._store_lock:
            old = self._store.get(key)
            self._store[key] = obj
            self._last_applied[key] = time.monotonic()
        # An ADDED for a key already in the store happens when a restarted
        # server watch re-delivers the current object set; client-go
        # converts those to updates so derived state is not double-counted
        # and modifications hidden by the watch gap still surface.
        if old is None:
            self._dispatch_add(obj)
        else:
            for _, on_update, _ in self._handlers:
                if on_update is not None:
                    self._safe(on_update, old, obj)

    def _dispatch_add(self, obj: object) -> None:
        for on_add, _, _ in self._handlers:
            if on_add is not None:
                self._safe(on_add, obj)

    @staticmethod
    def _safe(fn: Callable, *args: object) -> None:
        try:
            fn(*args)
        except Exception:  # handler bugs must not kill the watch pump
            logger.exception("informer event handler failed")

    def has_synced(self, timeout: Optional[float] = None) -> bool:
        return self._synced.wait(timeout=timeout)

    def refresh(self) -> None:
        """Relist and reconcile the store (client-go ``Reflector.Replace``).

        A restarted live watch re-delivers current objects as ADDED but
        never emits DELETED for objects removed during the stream gap, so
        a long-lived cache must periodically reconcile against a full
        list. The list snapshot races the watch pump, and there is no
        cross-backend resourceVersion to order by — so any key whose last
        watch event applied *after* the list began is left untouched (the
        event is newer than the snapshot; the next relist converges it).
        Deleted keys leave tombstones for the same reason: a DELETED that
        lands mid-list must not be undone by the stale snapshot."""
        list_started = time.monotonic()
        objects = self._lister()
        fresh: dict[tuple[str, str], object] = {}
        for obj in objects:
            if self._ingest_filter is not None \
                    and not self._ingest_filter(obj):
                # partition pushdown: an object outside the filter is
                # absent from the "server" view, so a stored copy is
                # pruned by the deletion sweep below — this is what
                # makes refresh() the repair step after an ownership
                # handover (newly-unowned objects retire here)
                continue
            try:
                fresh[self._key_fn(obj)] = obj
            except Exception:
                logger.exception("%s: key function failed on relisted "
                                 "object", self._name)
        deleted: list[object] = []
        added: list[object] = []
        updated: list[tuple[object, object]] = []
        with self._store_lock:
            def newer_than_list(key: tuple[str, str]) -> bool:
                return self._last_applied.get(key, -1.0) >= list_started

            # Tombstones older than the list have served their purpose:
            # the snapshot was taken after those deletes applied, so if
            # it still contains such a key the object was RECREATED and
            # the watch ADD was lost — exactly the gap relist heals.
            # Pruning first lets the fresh-object loop apply it now
            # instead of one relist interval later. Delete-during-list
            # tombstones are >= list_started and are preserved by the
            # newer_than_list check below.
            for key in [k for k, t in self._last_applied.items()
                        if k not in self._store and t < list_started]:
                del self._last_applied[key]
            for key in [k for k in self._store if k not in fresh]:
                if newer_than_list(key):
                    continue  # added by a watch event during the list
                deleted.append(self._store.pop(key))
                self._last_applied[key] = list_started
            for key, obj in fresh.items():
                if newer_than_list(key):
                    continue  # modified/deleted during the list; keep event
                old = self._store.get(key)
                self._store[key] = obj
                self._last_applied[key] = list_started
                if old is None:
                    added.append(obj)
                elif old != obj:
                    updated.append((old, obj))
        for obj in deleted:
            for _, _, on_delete in self._handlers:
                if on_delete is not None:
                    self._safe(on_delete, obj)
        for obj in added:
            self._dispatch_add(obj)
        for old, obj in updated:
            for _, on_update, _ in self._handlers:
                if on_update is not None:
                    self._safe(on_update, old, obj)
        # a completed relist satisfies any pending refresh request
        # (resubscribe(), a failed earlier refresh) — without this an
        # inline refresh after resubscribe would relist a second time
        # on the next pump for nothing
        self._needs_refresh = False

    def apply_external(self, obj: object) -> None:
        """Apply a write RESULT directly to the cache (read-your-writes).

        The caller just performed a mutation against the backend and
        holds the fresh object the server returned; applying it here
        makes the cache reflect the write immediately instead of after
        the watch round-trip — which is what turns the provider's
        read-back poll (node_upgrade_state_provider.go:100-117) into a
        no-wait check and lets a write wave pipeline instead of each
        write blocking on the watch pump. The freshness stamp protects
        it from an in-flight relist exactly like a watch event; the
        mutation's own watch event lands later as an equal-value update.
        """
        key = self._key_fn(obj)
        if self._ingest_filter is not None \
                and not self._ingest_filter(obj):
            # a write result outside the partition filter must not
            # smuggle the object into the cache; retire a stored copy
            meta = getattr(obj, "metadata", None)
            if meta is not None:
                self.apply_external_delete(meta.namespace, meta.name)
            return
        with self._store_lock:
            old = self._store.get(key)
            self._store[key] = obj
            self._last_applied[key] = time.monotonic()
        if old is None:
            self._dispatch_add(obj)
        else:
            for _, on_update, _ in self._handlers:
                if on_update is not None:
                    self._safe(on_update, old, obj)

    def apply_external_delete(self, namespace: str, name: str) -> None:
        """Delete-side of :meth:`apply_external`: the caller deleted the
        object on the backend; drop it from the cache now (tombstoned,
        so a racing relist cannot resurrect it)."""
        key = (namespace, name)
        with self._store_lock:
            old = self._store.pop(key, None)
            self._last_applied[key] = time.monotonic()  # tombstone
        if old is not None:
            for _, _, on_delete in self._handlers:
                if on_delete is not None:
                    self._safe(on_delete, old)

    def set_ingest_filter(
            self, pred: Optional[Callable[[object], bool]]) -> None:
        """Install (or clear) the ingest filter. The store is NOT
        rewritten here — call :meth:`refresh` afterwards to admit
        newly-matching objects and retire newly-rejected ones."""
        self._ingest_filter = pred

    def get(self, namespace: str, name: str) -> Optional[object]:
        with self._store_lock:
            return self._store.get((namespace, name))

    def list(self) -> list:
        with self._store_lock:
            return list(self._store.values())

    def __len__(self) -> int:
        with self._store_lock:
            return len(self._store)

    def stop(self) -> None:
        self._watch.stop()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


@dataclass
class ReconcileResult:
    """Outcome of one reconcile (controller-runtime ``ctrl.Result``).

    ``forget=True`` additionally drops the key from the resync set — the
    reconciler's way of saying "this object is gone" for deletions the
    watch never observed (stream-gap deletions emit no DELETED event).
    """

    requeue: bool = False
    requeue_after: Optional[float] = None
    forget: bool = False


class Controller:
    """Drives a reconcile function from watch events.

    Every event on a registered watch enqueues ``key`` (default: the
    cluster-scoped singleton). Worker threads pop keys and call
    ``reconcile(key)``; an exception or ``ReconcileResult(requeue=True)``
    re-enqueues with exponential backoff, ``requeue_after`` re-enqueues
    after a fixed delay, success forgets the backoff. ``resync_period``
    re-enqueues every key seen so far on a timer — the safety net for
    missed events, mirroring controller-runtime's SyncPeriod.
    """

    def __init__(self, reconcile: Callable[[str], Optional[ReconcileResult]],
                 name: str = "upgrade-controller",
                 rate_limiter: Optional[ExponentialBackoffRateLimiter] = None,
                 resync_period: Optional[float] = None,
                 metrics: Optional["MetricsRegistry"] = None) -> None:
        self._reconcile = reconcile
        self._name = name
        self._metrics = metrics
        self._limiter = rate_limiter or ExponentialBackoffRateLimiter()
        # 0/negative would busy-loop the resync thread; treat as disabled.
        if resync_period is not None and resync_period <= 0:
            resync_period = None
        self._resync_period = resync_period
        self.queue = WorkQueue()
        self._watches: list[tuple[Watch, Callable[[WatchEvent], Optional[str]]]] = []
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        self._reconcile_count = 0
        self._error_count = 0
        self._count_lock = threading.Lock()
        # Every key ever enqueued; the resync timer re-enqueues all of
        # them (not just CLUSTER_KEY) so controllers with per-object
        # key functions also get the missed-event safety net.
        self._known_keys: set[str] = set()
        self._known_lock = threading.Lock()

    def _enqueue(self, key: str) -> None:
        with self._known_lock:
            self._known_keys.add(key)
        self.queue.add(key)

    def enqueue(self, key: str = CLUSTER_KEY) -> None:
        """Externally trigger a reconcile for ``key`` (default: the
        cluster singleton). Lets event sources that are not Watch objects
        — e.g. a read cache's post-apply informer handlers — drive the
        controller."""
        self._enqueue(key)

    def forget_key(self, key: str) -> None:
        """Stop resyncing ``key`` (e.g. the reconciler found its object
        gone). A later event for the key re-registers it."""
        with self._known_lock:
            self._known_keys.discard(key)
        self._limiter.forget(key)

    # -- wiring ----------------------------------------------------------
    def watch(self, watch: Watch,
              key_fn: Optional[Callable[[WatchEvent], Optional[str]]] = None) -> None:
        """Enqueue ``key_fn(event)`` for every event (None = skip event;
        default maps everything to :data:`CLUSTER_KEY`). Must be called
        before :meth:`start` — pump threads are spawned there.

        With a custom per-object ``key_fn``, a DELETED event still
        enqueues one final reconcile for its key, after which the key is
        forgotten so the resync timer stops re-enqueueing dead objects
        (the known-key set would otherwise grow forever in a churny
        namespace). The default cluster-singleton key is never forgotten.

        This is best-effort: a deletion during a watch-stream gap emits
        no DELETED event (restarted live streams re-list current objects
        only), so a per-object reconciler should also return
        ``ReconcileResult(forget=True)`` when it finds its object gone.
        """
        if self._threads:
            raise RuntimeError(
                "Controller.watch() after start(): the watch would never "
                "be pumped; register watches before starting")
        if key_fn is None:
            key_fn = _cluster_key_fn
        self._watches.append((watch, key_fn))

    # -- lifecycle -------------------------------------------------------
    def start(self, workers: int = 1, initial_sync: bool = True) -> None:
        """Start pumps + workers; ``initial_sync`` seeds one reconcile so
        state converges even if no event ever fires."""
        if self._threads:
            raise RuntimeError("controller already started")
        if initial_sync:
            self._enqueue(CLUSTER_KEY)
        for i, (watch, key_fn) in enumerate(self._watches):
            t = threading.Thread(target=self._pump, args=(watch, key_fn),
                                 name=f"{self._name}-watch-{i}", daemon=True)
            t.start()
            self._threads.append(t)
        for i in range(workers):
            t = threading.Thread(target=self._worker,
                                 name=f"{self._name}-worker-{i}", daemon=True)
            t.start()
            self._threads.append(t)
        if self._resync_period is not None:
            t = threading.Thread(target=self._resync,
                                 name=f"{self._name}-resync", daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        for watch, _ in self._watches:
            watch.stop()
        self.queue.shut_down()
        deadline = time.monotonic() + timeout
        for t in self._threads:
            remaining = deadline - time.monotonic()
            if remaining > 0:
                t.join(remaining)
        self._threads = []

    # -- introspection ---------------------------------------------------
    @property
    def reconcile_count(self) -> int:
        with self._count_lock:
            return self._reconcile_count

    @property
    def error_count(self) -> int:
        with self._count_lock:
            return self._error_count

    # -- internals -------------------------------------------------------
    def _pump(self, watch: Watch, key_fn: Callable[[WatchEvent], Optional[str]]) -> None:
        for event in watch:
            if self._stop.is_set():
                return
            if event.type in (BOOKMARK, EXPIRED) \
                    and key_fn is not _cluster_key_fn:
                # overflow/expiry markers carry no object, so a
                # per-object key function cannot resolve them; the
                # resync timer remains the repair path for those
                # controllers
                continue
            try:
                key = key_fn(event)
            except Exception:
                logger.exception("watch key function failed")
                continue
            if key is not None:
                self._enqueue(key)
                if event.type == DELETED and key_fn is not _cluster_key_fn:
                    # final cleanup reconcile is queued; drop the key from
                    # the resync set so dead objects aren't re-enqueued
                    # forever
                    with self._known_lock:
                        self._known_keys.discard(key)

    def _observe(self, started: float, error: bool) -> None:
        if self._metrics is None:
            return
        labels = {"controller": self._name}
        self._metrics.observe_histogram(
            "reconcile_duration_seconds", time.monotonic() - started,
            "Wall-clock seconds per reconcile pass", labels)
        if error:
            self._metrics.inc_counter("reconcile_errors_total",
                                      "Reconciles that raised", labels)
        self._metrics.set_gauge("workqueue_depth", len(self.queue),
                                "Keys queued or delay-pending", labels)

    def _worker(self) -> None:
        while not self._stop.is_set():
            key = self.queue.get(timeout=0.5)
            if key is None:
                continue
            started = time.monotonic()
            try:
                result = self._reconcile(key)
            except Exception as exc:
                with self._count_lock:
                    self._reconcile_count += 1
                    self._error_count += 1
                delay = self._limiter.when(key)
                # An apiserver that answered 429 with Retry-After has
                # told us exactly when it wants the retry; coming back
                # sooner just feeds the throttle (the typed error carries
                # the header, k8s.client.ApiServerError.retry_after).
                retry_after = getattr(exc, "retry_after", None)
                if retry_after is not None and retry_after > delay:
                    delay = float(retry_after)
                logger.exception("reconcile %r failed; retrying in %.3fs",
                                 key, delay)
                self.queue.done(key)
                self.queue.add_after(key, delay)
                self._observe(started, error=True)
                continue
            with self._count_lock:
                self._reconcile_count += 1
            self.queue.done(key)
            self._observe(started, error=False)
            if result is not None and result.forget:
                self.forget_key(key)
                continue
            if result is not None and result.requeue_after is not None:
                self.queue.add_after(key, result.requeue_after)
            elif result is not None and result.requeue:
                self.queue.add_after(key, self._limiter.when(key))
            else:
                self._limiter.forget(key)

    def _resync(self) -> None:
        # Only keys actually seen are resynced: injecting CLUSTER_KEY
        # into a per-object controller that never registered it would
        # hand its reconciler a key it cannot resolve. Cluster-scoped
        # controllers register CLUSTER_KEY via initial_sync or their
        # first event.
        assert self._resync_period is not None
        while not self._stop.wait(self._resync_period):
            with self._known_lock:
                keys = set(self._known_keys)
            for key in keys:
                self.queue.add(key)
